// GF(2^8) Reed-Solomon matrix-multiply kernels for the host CPU.
//
// Role: (a) the CPU fallback / small-object path of the framework (the
// device pipeline wins only when batches amortize transfer+dispatch), and
// (b) the "SIMD reedsolomon" baseline bench.py compares the TPU path
// against (reference behavior: the codec library wrapped at the
// reference's cmd/erasure-coding.go:56 runs AVX2 table-lookup kernels).
//
// Two paths, runtime-dispatched:
//   * GFNI+AVX512BW: one vgf2p8affineqb per (input-shard x output-shard)
//     per 64 bytes — the 8x8 GF(2) bit-matrix form this framework also
//     uses on the MXU (ops/rs_pallas.py), in silicon.
//   * Portable: 4-bit split lookup tables (the classic SSSE3 formulation,
//     in scalar C so it runs anywhere; compilers autovectorize the XORs).
//
// The GF(2^8) field (poly 0x11D, generator 2) matches ops/gf256.py; the
// Python layer passes fully-built coding matrices, so this file contains
// no matrix algebra — only the byte-level matmul.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#include <cpuid.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// Field tables (built once at load; poly 0x11D, generator 2)
// ---------------------------------------------------------------------------

uint8_t g_mul[256][256];

struct TableInit {
  TableInit() {
    uint8_t exp_t[512];
    int log_t[256];
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      exp_t[i] = static_cast<uint8_t>(x);
      log_t[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 510; ++i) exp_t[i] = exp_t[i - 255];
    log_t[0] = 0;
    for (int a = 0; a < 256; ++a) {
      for (int b = 0; b < 256; ++b) {
        g_mul[a][b] = (a && b)
            ? exp_t[log_t[a] + log_t[b]]
            : 0;
      }
    }
  }
} g_table_init;

// 8x8 bit-matrix of multiply-by-c packed for GF2P8AFFINEQB: output-bit q's
// row lives in byte (7-q) of the qword; row bit p = bit q of c*(2^p).
uint64_t AffineQword(uint8_t c) {
  uint64_t qw = 0;
  for (int q = 0; q < 8; ++q) {
    uint8_t row = 0;
    for (int p = 0; p < 8; ++p) {
      uint8_t prod = g_mul[c][static_cast<uint8_t>(1u << p)];
      if ((prod >> q) & 1) row |= static_cast<uint8_t>(1u << p);
    }
    qw |= static_cast<uint64_t>(row) << (8 * (7 - q));
  }
  return qw;
}

// ---------------------------------------------------------------------------
// CPU feature detection
// ---------------------------------------------------------------------------

bool DetectGfniAvx512() {
#if defined(__x86_64__)
  unsigned eax, ebx, ecx, edx;
  if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
  const bool avx512f = ebx & (1u << 16);
  const bool avx512bw = ebx & (1u << 30);
  const bool gfni = ecx & (1u << 8);
  return avx512f && avx512bw && gfni;
#else
  return false;
#endif
}

const bool g_has_gfni = DetectGfniAvx512();

// ---------------------------------------------------------------------------
// GFNI/AVX512 path
// ---------------------------------------------------------------------------

#if defined(__x86_64__)
__attribute__((target("avx512f,avx512bw,gfni")))
void MatmulGfni(const uint8_t* matrix, size_t r, size_t k,
                const uint8_t* data, size_t stride_in,
                uint8_t* out, size_t stride_out, size_t len) {
  // Precompute affine qwords for the whole matrix (r*k tiny; heap so an
  // arbitrarily large recovery matrix can never overrun the stack).
  std::vector<uint64_t> aff(r * k);
  for (size_t j = 0; j < r; ++j)
    for (size_t i = 0; i < k; ++i)
      aff[j * k + i] = AffineQword(matrix[j * k + i]);

  size_t s = 0;
  for (; s + 64 <= len; s += 64) {
    for (size_t j = 0; j < r; ++j) {
      __m512i acc = _mm512_setzero_si512();
      for (size_t i = 0; i < k; ++i) {
        __m512i v = _mm512_loadu_si512(
            reinterpret_cast<const void*>(data + i * stride_in + s));
        __m512i a = _mm512_set1_epi64(static_cast<long long>(aff[j * k + i]));
        acc = _mm512_xor_si512(acc, _mm512_gf2p8affine_epi64_epi8(v, a, 0));
      }
      _mm512_storeu_si512(reinterpret_cast<void*>(out + j * stride_out + s),
                          acc);
    }
  }
  if (s < len) {
    // tail: bounce through a 64-byte scratch
    const size_t tail = len - s;
    for (size_t j = 0; j < r; ++j) {
      uint8_t accbuf[64];
      __m512i acc = _mm512_setzero_si512();
      for (size_t i = 0; i < k; ++i) {
        uint8_t buf[64] = {0};
        std::memcpy(buf, data + i * stride_in + s, tail);
        __m512i v = _mm512_loadu_si512(reinterpret_cast<const void*>(buf));
        __m512i a = _mm512_set1_epi64(static_cast<long long>(aff[j * k + i]));
        acc = _mm512_xor_si512(acc, _mm512_gf2p8affine_epi64_epi8(v, a, 0));
      }
      _mm512_storeu_si512(reinterpret_cast<void*>(accbuf), acc);
      std::memcpy(out + j * stride_out + s, accbuf, tail);
    }
  }
}
#endif  // __x86_64__

// ---------------------------------------------------------------------------
// Portable path: 4-bit split tables (low/high nibble), XOR-accumulate
// ---------------------------------------------------------------------------

void MatmulPortable(const uint8_t* matrix, size_t r, size_t k,
                    const uint8_t* data, size_t stride_in,
                    uint8_t* out, size_t stride_out, size_t len) {
  for (size_t j = 0; j < r; ++j) {
    uint8_t* dst = out + j * stride_out;
    std::memset(dst, 0, len);
    for (size_t i = 0; i < k; ++i) {
      const uint8_t c = matrix[j * k + i];
      if (c == 0) continue;
      const uint8_t* src = data + i * stride_in;
      // nibble tables for constant c
      uint8_t lo[16], hi[16];
      for (int t = 0; t < 16; ++t) {
        lo[t] = g_mul[c][t];
        hi[t] = g_mul[c][t << 4];
      }
      if (c == 1) {
        for (size_t s = 0; s < len; ++s) dst[s] ^= src[s];
      } else {
        for (size_t s = 0; s < len; ++s) {
          const uint8_t b = src[s];
          dst[s] ^= static_cast<uint8_t>(lo[b & 0xf] ^ hi[b >> 4]);
        }
      }
    }
  }
}

}  // namespace

extern "C" {

// out(r x len) = matrix(r x k) (x) data(k x len) over GF(2^8).
// data/out are row-major with explicit strides (numpy-compatible).
// force_path: 0 = auto, 1 = portable, 2 = gfni (for benchmarking).
void gf_matmul(const uint8_t* matrix, size_t r, size_t k,
               const uint8_t* data, size_t stride_in,
               uint8_t* out, size_t stride_out, size_t len,
               int force_path) {
#if defined(__x86_64__)
  const bool use_gfni =
      (force_path == 2) || (force_path == 0 && g_has_gfni);
  if (use_gfni && g_has_gfni) {
    MatmulGfni(matrix, r, k, data, stride_in, out, stride_out, len);
    return;
  }
#endif
  MatmulPortable(matrix, r, k, data, stride_in, out, stride_out, len);
}

int gf_has_gfni() { return g_has_gfni ? 1 : 0; }

uint8_t gf_mul_one(uint8_t a, uint8_t b) { return g_mul[a][b]; }

}  // extern "C"
