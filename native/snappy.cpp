// Snappy block-format codec + CRC32C — the CPU side of the S2-interop
// compression path (reference cmd/object-api-utils.go:869
// newS2CompressReader / s2.NewReader).
//
// The WRITE side emits pure snappy block format, which every S2 reader
// accepts (snappy is a strict subset of S2), wrapped by the Python
// framing layer (minio_tpu/features/snappy.py) into the snappy framing
// format — also valid S2 stream input. The READ side decodes snappy
// blocks plus the S2 repeat-offset extension in its unextended form;
// extended repeat-length encodings return -2 ("unsupported") rather
// than risk mis-decoding a format we cannot validate offline. Every
// framed chunk is CRC32C-checked, so even a wrong guess would surface
// as a checksum error, never as corrupt payload bytes.
//
// Build: part of libminio_tpu_native.so (make -C native).

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__x86_64__)
#include <immintrin.h>
#include <cpuid.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli). Hardware SSE4.2 when available, else slicing table.
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];

static void crc32c_init_table() {
    const uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
        crc32c_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc32c_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc32c_table[0][c & 0xff] ^ (c >> 8);
            crc32c_table[t][i] = c;
        }
    }
}

#if defined(__x86_64__)
static int has_sse42_cached = -1;
static bool has_sse42() {
    if (has_sse42_cached < 0) {
        unsigned a, b, c, d;
        has_sse42_cached =
            (__get_cpuid(1, &a, &b, &c, &d) && (c & bit_SSE4_2)) ? 1 : 0;
    }
    return has_sse42_cached == 1;
}

__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t* p, size_t n) {
    uint64_t c = crc;
    while (n >= 8) {
        uint64_t v;
        memcpy(&v, p, 8);
        c = _mm_crc32_u64(c, v);
        p += 8; n -= 8;
    }
    uint32_t c32 = (uint32_t)c;
    while (n--) c32 = _mm_crc32_u8(c32, *p++);
    return c32;
}
#endif

static uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t n) {
    static const bool once = [] { crc32c_init_table(); return true; }();
    (void)once;
    while (n >= 8) {
        uint64_t v;
        memcpy(&v, p, 8);
        v ^= crc;
        crc = crc32c_table[7][v & 0xff] ^
              crc32c_table[6][(v >> 8) & 0xff] ^
              crc32c_table[5][(v >> 16) & 0xff] ^
              crc32c_table[4][(v >> 24) & 0xff] ^
              crc32c_table[3][(v >> 32) & 0xff] ^
              crc32c_table[2][(v >> 40) & 0xff] ^
              crc32c_table[1][(v >> 48) & 0xff] ^
              crc32c_table[0][(v >> 56) & 0xff];
        p += 8; n -= 8;
    }
    while (n--)
        crc = crc32c_table[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
    return crc;
}

uint32_t snappy_crc32c(const uint8_t* data, size_t n) {
    uint32_t crc = 0xffffffffu;
#if defined(__x86_64__)
    if (has_sse42())
        crc = crc32c_hw(crc, data, n);
    else
#endif
        crc = crc32c_sw(crc, data, n);
    return crc ^ 0xffffffffu;
}

// ---------------------------------------------------------------------------
// snappy block compress (golang/snappy-compatible output)
// ---------------------------------------------------------------------------

static inline uint32_t load32(const uint8_t* p) {
    uint32_t v; memcpy(&v, p, 4); return v;
}
static inline uint64_t load64(const uint8_t* p) {
    uint64_t v; memcpy(&v, p, 8); return v;
}

size_t snappy_max_compressed_length(size_t n) {
    // worst case: varint header + all-literal with 1 extra tag byte
    // per 2^32... use the canonical bound 32 + n + n/6
    return 32 + n + n / 6;
}

static uint8_t* emit_varint(uint8_t* dst, uint64_t v) {
    while (v >= 0x80) {
        *dst++ = (uint8_t)(v) | 0x80;
        v >>= 7;
    }
    *dst++ = (uint8_t)v;
    return dst;
}

static uint8_t* emit_literal(uint8_t* dst, const uint8_t* src, size_t n) {
    if (n == 0) return dst;
    size_t n1 = n - 1;
    if (n1 < 60) {
        *dst++ = (uint8_t)(n1 << 2);
    } else if (n1 < (1u << 8)) {
        *dst++ = 60 << 2;
        *dst++ = (uint8_t)n1;
    } else if (n1 < (1u << 16)) {
        *dst++ = 61 << 2;
        *dst++ = (uint8_t)n1; *dst++ = (uint8_t)(n1 >> 8);
    } else if (n1 < (1u << 24)) {
        *dst++ = 62 << 2;
        *dst++ = (uint8_t)n1; *dst++ = (uint8_t)(n1 >> 8);
        *dst++ = (uint8_t)(n1 >> 16);
    } else {
        *dst++ = 63 << 2;
        *dst++ = (uint8_t)n1; *dst++ = (uint8_t)(n1 >> 8);
        *dst++ = (uint8_t)(n1 >> 16); *dst++ = (uint8_t)(n1 >> 24);
    }
    memcpy(dst, src, n);
    return dst + n;
}

static uint8_t* emit_copy(uint8_t* dst, size_t offset, size_t length) {
    // long matches: chunks of <=64 via copy2
    while (length >= 68) {
        *dst++ = (63 << 2) | 2;                 // copy2, len 64
        *dst++ = (uint8_t)offset; *dst++ = (uint8_t)(offset >> 8);
        length -= 64;
    }
    if (length > 64) {
        *dst++ = (59 << 2) | 2;                 // copy2, len 60
        *dst++ = (uint8_t)offset; *dst++ = (uint8_t)(offset >> 8);
        length -= 60;
    }
    if (length >= 12 || offset >= 2048) {
        *dst++ = (uint8_t)(((length - 1) << 2) | 2);   // copy2
        *dst++ = (uint8_t)offset; *dst++ = (uint8_t)(offset >> 8);
    } else {
        // copy1: 4 <= length <= 11, offset < 2048
        *dst++ = (uint8_t)(((offset >> 8) << 5) |
                           ((length - 4) << 2) | 1);
        *dst++ = (uint8_t)offset;
    }
    return dst;
}

int snappy_compress_block(const uint8_t* src, size_t n,
                          uint8_t* dst, size_t* dst_len) {
    uint8_t* d = emit_varint(dst, n);
    if (n < 16) {
        d = emit_literal(d, src, n);
        *dst_len = (size_t)(d - dst);
        return 0;
    }

    // hash table of positions; size scales with input (max 1<<14)
    const int max_table_bits = 14;
    int table_bits = 8;
    while (table_bits < max_table_bits &&
           (size_t(1) << table_bits) < n)
        table_bits++;
    uint32_t shift = 32 - table_bits;
    uint16_t table[1 << 14];
    memset(table, 0, sizeof(uint16_t) * (size_t(1) << table_bits));

    // s_limit leaves margin so 8-byte loads stay in bounds
    size_t s_limit = n - 15;
    size_t next_emit = 0;
    size_t s = 1;
    const uint32_t mul = 0x1e35a7bd;

    while (s < s_limit) {
        // find a match, skipping faster the longer we go without one
        size_t skip = 32;
        size_t candidate;
        uint32_t h = (load32(src + s) * mul) >> shift;
        for (;;) {
            candidate = table[h];
            table[h] = (uint16_t)s;
            if (candidate < s && s - candidate < (1u << 16) &&
                load32(src + candidate) == load32(src + s))
                break;
            s += (skip >> 5);
            skip++;
            if (s >= s_limit) goto tail;
            h = (load32(src + s) * mul) >> shift;
        }

        d = emit_literal(d, src + next_emit, s - next_emit);

        // extend the match forward
        {
            size_t base = s;
            size_t m_start = candidate;
            size_t matched = 4;
            s += 4; candidate += 4;
            bool mismatched = false;
            while (s + 8 <= n) {
                uint64_t x = load64(src + s) ^ load64(src + candidate);
                if (x != 0) {
                    matched += (size_t)(__builtin_ctzll(x) >> 3);
                    mismatched = true;
                    break;
                }
                s += 8; candidate += 8; matched += 8;
            }
            if (!mismatched) {
                while (s < n && src[s] == src[candidate]) {
                    s++; candidate++; matched++;
                }
            }
            s = base + matched;
            d = emit_copy(d, base - m_start, matched);
            next_emit = s;
            if (s >= s_limit) break;
            // re-seed the table at s-1 and s for denser matching
            uint32_t h2 = (load32(src + s - 1) * mul) >> shift;
            table[h2] = (uint16_t)(s - 1);
        }
    }
tail:
    if (next_emit < n)
        d = emit_literal(d, src + next_emit, n - next_emit);
    *dst_len = (size_t)(d - dst);
    return 0;
}

// ---------------------------------------------------------------------------
// snappy/S2 block decompress
// ---------------------------------------------------------------------------

int64_t snappy_uncompressed_length(const uint8_t* src, size_t n) {
    uint64_t v = 0;
    int shift = 0;
    for (size_t i = 0; i < n && i < 10; i++) {
        v |= (uint64_t)(src[i] & 0x7f) << shift;
        if (!(src[i] & 0x80))
            return (int64_t)v;
        shift += 7;
    }
    return -1;
}

// returns bytes written, -1 on corrupt input, -2 on an S2 encoding
// outside the supported subset
int64_t snappy_uncompress_block(const uint8_t* src, size_t n,
                                uint8_t* dst, size_t dst_cap) {
    size_t s = 0;
    // varint length header
    uint64_t want = 0;
    {
        int shift = 0;
        for (;;) {
            if (s >= n || shift > 63) return -1;
            uint8_t b = src[s++];
            want |= (uint64_t)(b & 0x7f) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
    }
    if (want > dst_cap) return -1;

    size_t d = 0;
    size_t last_offset = 0;          // S2 repeat state
    while (s < n) {
        uint8_t tag = src[s];
        size_t length, offset;
        switch (tag & 3) {
        case 0: {                    // literal
            length = tag >> 2;
            s++;
            if (length >= 60) {
                size_t extra = length - 59;     // 1..4 bytes
                if (s + extra > n) return -1;
                length = 0;
                for (size_t i = 0; i < extra; i++)
                    length |= (size_t)src[s + i] << (8 * i);
                s += extra;
            }
            length += 1;
            if (s + length > n || d + length > dst_cap) return -1;
            memcpy(dst + d, src + s, length);
            s += length; d += length;
            continue;
        }
        case 1: {                    // copy1 (or S2 repeat)
            if (s + 2 > n) return -1;
            length = ((tag >> 2) & 0x7);
            offset = ((size_t)(tag & 0xe0) << 3) | src[s + 1];
            s += 2;
            if (offset == 0) {
                // S2 repeat-offset. Lengths 4..8 (codes 0..4) are the
                // unextended form; codes 5..7 signal extended length
                // bytes whose exact bias we cannot validate offline —
                // refuse rather than risk a wrong reconstruction.
                if (length >= 5) return -2;
                length += 4;
                offset = last_offset;
                if (offset == 0) return -1;     // repeat before any copy
            } else {
                length += 4;
            }
            break;
        }
        case 2: {                    // copy2
            if (s + 3 > n) return -1;
            length = (tag >> 2) + 1;
            offset = (size_t)src[s + 1] | ((size_t)src[s + 2] << 8);
            s += 3;
            if (offset == 0) return -2;         // S2 extended repeat
            break;
        }
        default: {                   // copy4
            if (s + 5 > n) return -1;
            length = (tag >> 2) + 1;
            offset = (size_t)src[s + 1] | ((size_t)src[s + 2] << 8) |
                     ((size_t)src[s + 3] << 16) |
                     ((size_t)src[s + 4] << 24);
            s += 5;
            if (offset == 0) return -2;
            break;
        }
        }
        if (offset > d || d + length > dst_cap) return -1;
        last_offset = offset;
        // overlapping copies must proceed byte-wise when offset < length
        if (offset >= length) {
            memcpy(dst + d, dst + d - offset, length);
            d += length;
        } else {
            for (size_t i = 0; i < length; i++, d++)
                dst[d] = dst[d - offset];
        }
    }
    if (d != want) return -1;
    return (int64_t)d;
}

}  // extern "C"
