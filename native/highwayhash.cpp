// HighwayHash — portable C++ implementation (algorithm is public domain).
//
// Role in this framework: HighwayHash-256 is the default per-shard bitrot
// checksum (reference behavior: cmd/bitrot.go:30-58 — algorithm
// "highwayhash256S" keyed with the magic pi-digest key). The hot GET/PUT
// paths checksum every shard block; this library provides the CPU engine
// (single-shot + batched) that the Python layer binds via ctypes. A
// device-side batched implementation is the TPU counterpart.
//
// Layout notes: state is 4 u64 lanes per register (v0, v1, mul0, mul1).
// The batched entry points hash many equal-length shards in one call to
// amortize FFI overhead (one call per encode step, not per shard).

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

struct HHState {
  uint64_t v0[4];
  uint64_t v1[4];
  uint64_t mul0[4];
  uint64_t mul1[4];
};

static const uint64_t kMul0[4] = {
    0xdbe6d5d5fe4cce2full, 0xa4093822299f31d0ull,
    0x13198a2e03707344ull, 0x243f6a8885a308d3ull};
static const uint64_t kMul1[4] = {
    0x3bd39e10cb0ef593ull, 0xc0acf169b5f18a8cull,
    0xbe5466cf34e90c6cull, 0x452821e638d01377ull};

inline uint64_t Rot32(uint64_t x) { return (x >> 32) | (x << 32); }

inline void Reset(const uint64_t key[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->mul0[i] = kMul0[i];
    s->mul1[i] = kMul1[i];
    s->v0[i] = kMul0[i] ^ key[i];
    s->v1[i] = kMul1[i] ^ Rot32(key[i]);
  }
}

inline void ZipperMergeAndAdd(const uint64_t v1, const uint64_t v0,
                              uint64_t* add1, uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ull) | (v1 & 0xff00000000ull)) >> 24) |
           (((v0 & 0xff0000000000ull) | (v1 & 0xff000000000000ull)) >> 16) |
           (v0 & 0xff0000ull) | ((v0 & 0xff00ull) << 32) |
           ((v1 & 0xff00000000000000ull) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ull) | (v0 & 0xff00000000ull)) >> 24) |
           (v1 & 0xff0000ull) | ((v1 & 0xff0000000000ull) >> 16) |
           ((v1 & 0xff00ull) << 24) | ((v0 & 0xff000000000000ull) >> 8) |
           ((v1 & 0xffull) << 48) | (v0 & 0xff00000000000000ull);
}

inline void Update(const uint64_t lanes[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->v1[i] += s->mul0[i] + lanes[i];
    s->mul0[i] ^= (s->v1[i] & 0xffffffff) * (s->v0[i] >> 32);
    s->v0[i] += s->mul1[i];
    s->mul1[i] ^= (s->v0[i] & 0xffffffff) * (s->v1[i] >> 32);
  }
  ZipperMergeAndAdd(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  ZipperMergeAndAdd(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  ZipperMergeAndAdd(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  ZipperMergeAndAdd(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

inline uint64_t Read64LE(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/ARM LE)
}

inline void UpdatePacket(const uint8_t* packet, HHState* s) {
  uint64_t lanes[4] = {Read64LE(packet), Read64LE(packet + 8),
                       Read64LE(packet + 16), Read64LE(packet + 24)};
  Update(lanes, s);
}

inline void Rotate32By(uint64_t count, uint64_t lanes[4]) {
  for (int i = 0; i < 4; ++i) {
    uint32_t half0 = static_cast<uint32_t>(lanes[i] & 0xffffffff);
    uint32_t half1 = static_cast<uint32_t>(lanes[i] >> 32);
    lanes[i] = (count == 0)
                   ? lanes[i]
                   : ((static_cast<uint64_t>((half0 << count) |
                                             (half0 >> (32 - count)))) |
                      (static_cast<uint64_t>((half1 << count) |
                                             (half1 >> (32 - count)))
                       << 32));
  }
}

inline void UpdateRemainder(const uint8_t* bytes, const size_t size_mod32,
                            HHState* s) {
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~3ull);
  uint8_t packet[32] = {0};
  for (int i = 0; i < 4; ++i) {
    s->v0[i] += (static_cast<uint64_t>(size_mod32) << 32) + size_mod32;
  }
  Rotate32By(size_mod32, s->v1);
  std::memcpy(packet, bytes, size_mod32 & ~3ull);
  if (size_mod32 & 16) {
    for (int i = 0; i < 4; ++i) {
      // signed offset: reaches back into the already-copied bytes when
      // size_mod4 < 4 (the upstream algorithm's unsigned wraparound,
      // made explicit)
      packet[28 + i] =
          remainder[static_cast<ptrdiff_t>(size_mod4) + i - 4];
    }
  } else if (size_mod4) {
    packet[16 + 0] = remainder[0];
    packet[16 + 1] = remainder[size_mod4 >> 1];
    packet[16 + 2] = remainder[size_mod4 - 1];
  }
  UpdatePacket(packet, s);
}

inline void Permute(const uint64_t v[4], uint64_t permuted[4]) {
  permuted[0] = Rot32(v[2]);
  permuted[1] = Rot32(v[3]);
  permuted[2] = Rot32(v[0]);
  permuted[3] = Rot32(v[1]);
}

inline void PermuteAndUpdate(HHState* s) {
  uint64_t permuted[4];
  Permute(s->v0, permuted);
  Update(permuted, s);
}

inline void ModularReduction(uint64_t a3_unmasked, uint64_t a2, uint64_t a1,
                             uint64_t a0, uint64_t* m1, uint64_t* m0) {
  const uint64_t a3 = a3_unmasked & 0x3FFFFFFFFFFFFFFFull;
  *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
  *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

inline void ProcessAll(const uint8_t* data, size_t size, HHState* s) {
  size_t i;
  for (i = 0; i + 32 <= size; i += 32) {
    UpdatePacket(data + i, s);
  }
  if ((size & 31) != 0) UpdateRemainder(data + i, size & 31, s);
}

inline uint64_t Finalize64(HHState* s) {
  for (int i = 0; i < 4; ++i) PermuteAndUpdate(s);
  return s->v0[0] + s->v1[0] + s->mul0[0] + s->mul1[0];
}

inline void Finalize256(HHState* s, uint64_t hash[4]) {
  for (int i = 0; i < 10; ++i) PermuteAndUpdate(s);
  ModularReduction(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0],
                   s->v0[1] + s->mul0[1], s->v0[0] + s->mul0[0],
                   &hash[1], &hash[0]);
  ModularReduction(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2],
                   s->v0[3] + s->mul0[3], s->v0[2] + s->mul0[2],
                   &hash[3], &hash[2]);
}

}  // namespace

extern "C" {

// 64-bit variant, used for self-test against published vectors.
uint64_t hh64(const uint8_t* key32, const uint8_t* data, size_t size) {
  uint64_t key[4];
  std::memcpy(key, key32, 32);
  HHState s;
  Reset(key, &s);
  ProcessAll(data, size, &s);
  return Finalize64(&s);
}

// 256-bit digest of one buffer (32-byte output, little-endian u64 x4).
void hh256(const uint8_t* key32, const uint8_t* data, size_t size,
           uint8_t* out32) {
  uint64_t key[4];
  std::memcpy(key, key32, 32);
  HHState s;
  Reset(key, &s);
  ProcessAll(data, size, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  std::memcpy(out32, hash, 32);
}

// Batched 256-bit digests: n buffers of equal length `size`, laid out
// contiguously with stride `stride` bytes; out = n x 32 bytes.
// One FFI call per erasure-encode step (n = shards).
void hh256_batch(const uint8_t* key32, const uint8_t* data, size_t n,
                 size_t size, size_t stride, uint8_t* out) {
  for (size_t j = 0; j < n; ++j) {
    hh256(key32, data + j * stride, size, out + j * 32);
  }
}

// Streaming interface: caller owns an opaque 128-byte state blob.
void hh_init(const uint8_t* key32, uint8_t* state128) {
  uint64_t key[4];
  std::memcpy(key, key32, 32);
  HHState s;
  Reset(key, &s);
  std::memcpy(state128, &s, sizeof(HHState));
}

// Append full 32-byte packets only (size % 32 == 0).
void hh_update_packets(uint8_t* state128, const uint8_t* data, size_t size) {
  HHState s;
  std::memcpy(&s, state128, sizeof(HHState));
  for (size_t i = 0; i + 32 <= size; i += 32) UpdatePacket(data + i, &s);
  std::memcpy(state128, &s, sizeof(HHState));
}

// Final call: append remainder (< 32 bytes) and emit 256-bit digest.
void hh_final256(uint8_t* state128, const uint8_t* remainder, size_t rem_size,
                 uint8_t* out32) {
  HHState s;
  std::memcpy(&s, state128, sizeof(HHState));
  if (rem_size) UpdateRemainder(remainder, rem_size & 31, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  std::memcpy(out32, hash, 32);
}

}  // extern "C"
