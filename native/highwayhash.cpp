// HighwayHash — portable C++ implementation (algorithm is public domain).
//
// Role in this framework: HighwayHash-256 is the default per-shard bitrot
// checksum (reference behavior: cmd/bitrot.go:30-58 — algorithm
// "highwayhash256S" keyed with the magic pi-digest key). The hot GET/PUT
// paths checksum every shard block; this library provides the CPU engine
// (single-shot + batched) that the Python layer binds via ctypes. A
// device-side batched implementation is the TPU counterpart.
//
// Layout notes: state is 4 u64 lanes per register (v0, v1, mul0, mul1).
// The batched entry points hash many equal-length shards in one call to
// amortize FFI overhead (one call per encode step, not per shard).

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define HH_X86 1
#endif

namespace {

struct HHState {
  uint64_t v0[4];
  uint64_t v1[4];
  uint64_t mul0[4];
  uint64_t mul1[4];
};

static const uint64_t kMul0[4] = {
    0xdbe6d5d5fe4cce2full, 0xa4093822299f31d0ull,
    0x13198a2e03707344ull, 0x243f6a8885a308d3ull};
static const uint64_t kMul1[4] = {
    0x3bd39e10cb0ef593ull, 0xc0acf169b5f18a8cull,
    0xbe5466cf34e90c6cull, 0x452821e638d01377ull};

inline uint64_t Rot32(uint64_t x) { return (x >> 32) | (x << 32); }

inline void Reset(const uint64_t key[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->mul0[i] = kMul0[i];
    s->mul1[i] = kMul1[i];
    s->v0[i] = kMul0[i] ^ key[i];
    s->v1[i] = kMul1[i] ^ Rot32(key[i]);
  }
}

inline void ZipperMergeAndAdd(const uint64_t v1, const uint64_t v0,
                              uint64_t* add1, uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ull) | (v1 & 0xff00000000ull)) >> 24) |
           (((v0 & 0xff0000000000ull) | (v1 & 0xff000000000000ull)) >> 16) |
           (v0 & 0xff0000ull) | ((v0 & 0xff00ull) << 32) |
           ((v1 & 0xff00000000000000ull) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ull) | (v0 & 0xff00000000ull)) >> 24) |
           (v1 & 0xff0000ull) | ((v1 & 0xff0000000000ull) >> 16) |
           ((v1 & 0xff00ull) << 24) | ((v0 & 0xff000000000000ull) >> 8) |
           ((v1 & 0xffull) << 48) | (v0 & 0xff00000000000000ull);
}

inline void Update(const uint64_t lanes[4], HHState* s) {
  for (int i = 0; i < 4; ++i) {
    s->v1[i] += s->mul0[i] + lanes[i];
    s->mul0[i] ^= (s->v1[i] & 0xffffffff) * (s->v0[i] >> 32);
    s->v0[i] += s->mul1[i];
    s->mul1[i] ^= (s->v0[i] & 0xffffffff) * (s->v1[i] >> 32);
  }
  ZipperMergeAndAdd(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  ZipperMergeAndAdd(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  ZipperMergeAndAdd(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  ZipperMergeAndAdd(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

inline uint64_t Read64LE(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/ARM LE)
}

inline void UpdatePacket(const uint8_t* packet, HHState* s) {
  uint64_t lanes[4] = {Read64LE(packet), Read64LE(packet + 8),
                       Read64LE(packet + 16), Read64LE(packet + 24)};
  Update(lanes, s);
}

inline void Rotate32By(uint64_t count, uint64_t lanes[4]) {
  for (int i = 0; i < 4; ++i) {
    uint32_t half0 = static_cast<uint32_t>(lanes[i] & 0xffffffff);
    uint32_t half1 = static_cast<uint32_t>(lanes[i] >> 32);
    lanes[i] = (count == 0)
                   ? lanes[i]
                   : ((static_cast<uint64_t>((half0 << count) |
                                             (half0 >> (32 - count)))) |
                      (static_cast<uint64_t>((half1 << count) |
                                             (half1 >> (32 - count)))
                       << 32));
  }
}

inline void UpdateRemainder(const uint8_t* bytes, const size_t size_mod32,
                            HHState* s) {
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~3ull);
  uint8_t packet[32] = {0};
  for (int i = 0; i < 4; ++i) {
    s->v0[i] += (static_cast<uint64_t>(size_mod32) << 32) + size_mod32;
  }
  Rotate32By(size_mod32, s->v1);
  std::memcpy(packet, bytes, size_mod32 & ~3ull);
  if (size_mod32 & 16) {
    for (int i = 0; i < 4; ++i) {
      // signed offset: reaches back into the already-copied bytes when
      // size_mod4 < 4 (the upstream algorithm's unsigned wraparound,
      // made explicit)
      packet[28 + i] =
          remainder[static_cast<ptrdiff_t>(size_mod4) + i - 4];
    }
  } else if (size_mod4) {
    packet[16 + 0] = remainder[0];
    packet[16 + 1] = remainder[size_mod4 >> 1];
    packet[16 + 2] = remainder[size_mod4 - 1];
  }
  UpdatePacket(packet, s);
}

inline void Permute(const uint64_t v[4], uint64_t permuted[4]) {
  permuted[0] = Rot32(v[2]);
  permuted[1] = Rot32(v[3]);
  permuted[2] = Rot32(v[0]);
  permuted[3] = Rot32(v[1]);
}

inline void PermuteAndUpdate(HHState* s) {
  uint64_t permuted[4];
  Permute(s->v0, permuted);
  Update(permuted, s);
}

inline void ModularReduction(uint64_t a3_unmasked, uint64_t a2, uint64_t a1,
                             uint64_t a0, uint64_t* m1, uint64_t* m0) {
  const uint64_t a3 = a3_unmasked & 0x3FFFFFFFFFFFFFFFull;
  *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
  *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

#ifdef HH_X86
// AVX2 packet loop: the whole HHState maps onto four __m256i (one per
// 4 x u64 register file). The zipper-merge byte permutation — derived
// from the scalar mask/shift cascade above — is a single in-lane
// per-128-bit pshufb:
//   dst byte j of each half <- src byte {3,12,2,5,14,1,15,0,
//                                        11,4,10,13,9,6,8,7}[j]
// and Update's cross-half pairing (lanes {1,0} and {3,2}) is exactly
// the two 128-bit lanes of a 256-bit register.
__attribute__((target("avx2"))) inline __m256i ZipperMergeV(__m256i v) {
  const __m256i mask = _mm256_setr_epi8(
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7,
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7);
  return _mm256_shuffle_epi8(v, mask);
}

__attribute__((target("avx2")))
void ProcessPacketsAVX2(const uint8_t* data, size_t n_packets, HHState* s) {
  __m256i v0 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v0));
  __m256i v1 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v1));
  __m256i mul0 =
      _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul0));
  __m256i mul1 =
      _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul1));
  for (size_t i = 0; i < n_packets; ++i) {
    const __m256i lanes = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(data + i * 32));
    // v1 += mul0 + lanes
    v1 = _mm256_add_epi64(v1, _mm256_add_epi64(mul0, lanes));
    // mul0 ^= (v1 & 0xffffffff) * (v0 >> 32)   [mul_epu32 = lo32*lo32]
    mul0 = _mm256_xor_si256(
        mul0, _mm256_mul_epu32(v1, _mm256_srli_epi64(v0, 32)));
    // v0 += mul1
    v0 = _mm256_add_epi64(v0, mul1);
    // mul1 ^= (v0 & 0xffffffff) * (v1 >> 32)
    mul1 = _mm256_xor_si256(
        mul1, _mm256_mul_epu32(v0, _mm256_srli_epi64(v1, 32)));
    // v0 += zipper(v1); then v1 += zipper(updated v0)
    v0 = _mm256_add_epi64(v0, ZipperMergeV(v1));
    v1 = _mm256_add_epi64(v1, ZipperMergeV(v0));
  }
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v0), v0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v1), v1);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul0), mul0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul1), mul1);
}

bool DetectAVX2() {
  return __builtin_cpu_supports("avx2");
}
const bool g_has_avx2 = DetectAVX2();
#endif  // HH_X86

inline void ProcessPackets(const uint8_t* data, size_t n_packets,
                           HHState* s) {
#ifdef HH_X86
  if (g_has_avx2) {
    ProcessPacketsAVX2(data, n_packets, s);
    return;
  }
#endif
  for (size_t i = 0; i < n_packets; ++i) UpdatePacket(data + i * 32, s);
}

inline void ProcessAll(const uint8_t* data, size_t size, HHState* s) {
  const size_t n_packets = size / 32;
  ProcessPackets(data, n_packets, s);
  if ((size & 31) != 0)
    UpdateRemainder(data + n_packets * 32, size & 31, s);
}

inline uint64_t Finalize64(HHState* s) {
  for (int i = 0; i < 4; ++i) PermuteAndUpdate(s);
  return s->v0[0] + s->v1[0] + s->mul0[0] + s->mul1[0];
}

inline void Finalize256(HHState* s, uint64_t hash[4]) {
  for (int i = 0; i < 10; ++i) PermuteAndUpdate(s);
  ModularReduction(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0],
                   s->v0[1] + s->mul0[1], s->v0[0] + s->mul0[0],
                   &hash[1], &hash[0]);
  ModularReduction(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2],
                   s->v0[3] + s->mul0[3], s->v0[2] + s->mul0[2],
                   &hash[3], &hash[2]);
}

}  // namespace

extern "C" {

// 64-bit variant, used for self-test against published vectors.
uint64_t hh64(const uint8_t* key32, const uint8_t* data, size_t size) {
  uint64_t key[4];
  std::memcpy(key, key32, 32);
  HHState s;
  Reset(key, &s);
  ProcessAll(data, size, &s);
  return Finalize64(&s);
}

// 256-bit digest of one buffer (32-byte output, little-endian u64 x4).
void hh256(const uint8_t* key32, const uint8_t* data, size_t size,
           uint8_t* out32) {
  uint64_t key[4];
  std::memcpy(key, key32, 32);
  HHState s;
  Reset(key, &s);
  ProcessAll(data, size, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  std::memcpy(out32, hash, 32);
}

// Batched 256-bit digests: n buffers of equal length `size`, laid out
// contiguously with stride `stride` bytes; out = n x 32 bytes.
// One FFI call per erasure-encode step (n = shards).
void hh256_batch(const uint8_t* key32, const uint8_t* data, size_t n,
                 size_t size, size_t stride, uint8_t* out) {
  for (size_t j = 0; j < n; ++j) {
    hh256(key32, data + j * stride, size, out + j * 32);
  }
}

// Streaming interface: caller owns an opaque 128-byte state blob.
void hh_init(const uint8_t* key32, uint8_t* state128) {
  uint64_t key[4];
  std::memcpy(key, key32, 32);
  HHState s;
  Reset(key, &s);
  std::memcpy(state128, &s, sizeof(HHState));
}

// Append full 32-byte packets only (size % 32 == 0).
void hh_update_packets(uint8_t* state128, const uint8_t* data, size_t size) {
  HHState s;
  std::memcpy(&s, state128, sizeof(HHState));
  ProcessPackets(data, size / 32, &s);
  std::memcpy(state128, &s, sizeof(HHState));
}

// 1 when the AVX2 packet loop is in use (tests/bench introspection).
int hh_has_avx2() {
#ifdef HH_X86
  return g_has_avx2 ? 1 : 0;
#else
  return 0;
#endif
}

// Final call: append remainder (< 32 bytes) and emit 256-bit digest.
void hh_final256(uint8_t* state128, const uint8_t* remainder, size_t rem_size,
                 uint8_t* out32) {
  HHState s;
  std::memcpy(&s, state128, sizeof(HHState));
  if (rem_size) UpdateRemainder(remainder, rem_size & 31, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  std::memcpy(out32, hash, 32);
}

}  // extern "C"
