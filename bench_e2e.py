#!/usr/bin/env python
"""End-to-end PutObject/GetObject benchmark — BASELINE config #2 shape.

Boots a single-node S3 server over local drives (EC 12+4, 1 MiB blocks)
and drives `--streams` concurrent `--size`-byte PutObject requests
through the full stack: SigV4 auth, HashReader MD5, erasure encode,
streaming bitrot, shard writes, xl.meta commit — then GETs everything
back. Reports aggregate GiB/s for both phases plus a per-stage wall-time
breakdown (utils/stagetimer) so the host overhead is attributable, not a
single opaque number.

This complements bench.py (the driver's kernel metric of record): on the
axon tunnel host the device cannot sit on this path (host->device moves
~15 MiB/s), so e2e runs use the CPU data path; on a real TPU host the
same code coalesces concurrent streams into shared device dispatches.

Usage: python bench_e2e.py [--streams 32] [--size 16777216] [--drives 16]
       [--unsigned]  # UNSIGNED-PAYLOAD (no content-sha256 on either side)
"""

from __future__ import annotations

import argparse
import concurrent.futures as cf
import hashlib
import http.client
import json
import os
import tempfile
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=32)
    ap.add_argument("--size", type=int, default=16 << 20)
    ap.add_argument("--drives", type=int, default=16)
    ap.add_argument("--parity", type=int, default=4)
    ap.add_argument("--unsigned", action="store_true",
                    help="sign with UNSIGNED-PAYLOAD: no client-side "
                         "sha256 and no server-side body verification "
                         "(what SDKs do over TLS)")
    ap.add_argument("--skip-get", action="store_true")
    ap.add_argument("--root", default="",
                    help="drive directory root; defaults to /dev/shm "
                         "(tmpfs) when present so the measurement is of "
                         "the HOST PATH, not this VM's ~60 MiB/s virtio "
                         "disk — pass a disk path to include real drive "
                         "IO")
    ap.add_argument("--device", action="store_true",
                    help="allow device routing (only sane on hosts with "
                         "real PCIe to the chip — the axon tunnel moves "
                         "~15 MiB/s and would dominate)")
    args = ap.parse_args()
    if not args.device:
        os.environ["MINIO_TPU_DEVICE_MIN_BYTES"] = str(1 << 60)

    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    from minio_tpu.utils import stagetimer

    creds = Credentials("benchkey1234", "benchsecret12345")
    base = args.root or ("/dev/shm" if os.path.isdir("/dev/shm")
                         else tempfile.gettempdir())
    root = tempfile.mkdtemp(prefix="bench_e2e_", dir=base)
    sched = BatchScheduler()
    sets = ErasureSets.from_drives(
        [f"{root}/d{i}" for i in range(args.drives)], 1, args.drives,
        args.parity, block_size=1 << 20, scheduler=sched)
    srv = S3Server(sets, creds=creds).start()
    sets.make_bucket("bench")

    payload = os.urandom(args.size)
    # client-side: the payload hash is a property of the (single) payload,
    # not per-request work — hoist it so the 1-core bench host doesn't
    # charge the server path for the client's sha256
    payload_hash = sig.UNSIGNED_PAYLOAD if args.unsigned else \
        hashlib.sha256(payload).hexdigest()

    def put(i: int) -> None:
        path = f"/bench/obj{i}"
        hdrs = sig.sign_v4("PUT", path, {},
                           {"host": f"127.0.0.1:{srv.port}"},
                           payload_hash, creds, "us-east-1")
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=600)
        conn.request("PUT", path, body=payload, headers=hdrs)
        resp = conn.getresponse()
        resp.read()
        conn.close()
        assert resp.status == 200, resp.status

    def get(i: int) -> None:
        path = f"/bench/obj{i}"
        hdrs = sig.sign_v4("GET", path, {},
                           {"host": f"127.0.0.1:{srv.port}"},
                           sig.UNSIGNED_PAYLOAD, creds, "us-east-1")
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=600)
        conn.request("GET", path, headers=hdrs)
        resp = conn.getresponse()
        n = 0
        while True:
            chunk = resp.read(1 << 20)
            if not chunk:
                break
            n += len(chunk)
        conn.close()
        assert resp.status == 200 and n == args.size, (resp.status, n)

    # teardown in finally: drive dirs default to RAM-backed tmpfs, so a
    # failed assertion must not leak hundreds of MiB per run
    try:
        put(999)                  # warm caches / lazy imports
        stagetimer.enable()
        stagetimer.reset()

        t0 = time.perf_counter()
        with cf.ThreadPoolExecutor(max_workers=args.streams) as ex:
            list(ex.map(put, range(args.streams)))
        put_wall = time.perf_counter() - t0
        put_stages = stagetimer.report()

        total = args.streams * args.size
        out = {
            "metric": "e2e PutObject GiB/s "
                      f"(EC {args.drives - args.parity}+{args.parity}, "
                      f"{args.streams} concurrent {args.size >> 20} MiB"
                      f"{', unsigned' if args.unsigned else ''})",
            "value": round(total / put_wall / 2**30, 3),
            "unit": "GiB/s",
            "wall_s": round(put_wall, 2),
            "scheduler": {"batches": sched.batches,
                          "coalesced": sched.coalesced},
            "put_stages": put_stages,
        }

        if not args.skip_get:
            stagetimer.reset()
            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=args.streams) as ex:
                list(ex.map(get, range(args.streams)))
            get_wall = time.perf_counter() - t0
            out["get_gib_s"] = round(total / get_wall / 2**30, 3)
            out["get_wall_s"] = round(get_wall, 2)
            out["get_stages"] = stagetimer.report()

        print(json.dumps(out))
    finally:
        srv.stop()
        sets.close()
        sched.close()
        import shutil
        shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
