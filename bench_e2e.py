#!/usr/bin/env python
"""End-to-end PutObject benchmark — BASELINE config #2.

Boots a single-node S3 server over local drives (EC 12+4, 1 MiB blocks)
and drives `--streams` concurrent `--size`-byte PutObject requests
through the full stack: SigV4 auth, HashReader MD5, erasure encode,
streaming bitrot, shard writes, xl.meta commit. Reports aggregate GiB/s
plus scheduler coalescing stats.

This complements bench.py (the driver's kernel metric of record): on the
axon tunnel host the device cannot sit on this path (host->device moves
~15 MiB/s), so e2e runs use the CPU data path; on a real TPU host the
same code coalesces concurrent streams into shared device dispatches.

Usage: python bench_e2e.py [--streams 32] [--size 16777216] [--drives 16]
"""

from __future__ import annotations

import argparse
import concurrent.futures as cf
import hashlib
import http.client
import json
import os
import tempfile
import time
import urllib.parse


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=32)
    ap.add_argument("--size", type=int, default=16 << 20)
    ap.add_argument("--drives", type=int, default=16)
    ap.add_argument("--parity", type=int, default=4)
    ap.add_argument("--device", action="store_true",
                    help="allow device routing (only sane on hosts with "
                         "real PCIe to the chip — the axon tunnel moves "
                         "~15 MiB/s and would dominate)")
    args = ap.parse_args()
    if not args.device:
        os.environ["MINIO_TPU_DEVICE_MIN_BYTES"] = str(1 << 60)

    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server

    creds = Credentials("benchkey1234", "benchsecret12345")
    root = tempfile.mkdtemp(prefix="bench_e2e_")
    sched = BatchScheduler()
    sets = ErasureSets.from_drives(
        [f"{root}/d{i}" for i in range(args.drives)], 1, args.drives,
        args.parity, block_size=1 << 20, scheduler=sched)
    srv = S3Server(sets, creds=creds).start()
    sets.make_bucket("bench")

    payload = os.urandom(args.size)

    def put(i: int) -> float:
        body = payload
        path = f"/bench/obj{i}"
        hdrs = {"host": f"127.0.0.1:{srv.port}"}
        hdrs = sig.sign_v4("PUT", path, {}, hdrs,
                           hashlib.sha256(body).hexdigest(), creds,
                           "us-east-1")
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=600)
        t0 = time.perf_counter()
        conn.request("PUT", path, body=body, headers=hdrs)
        resp = conn.getresponse()
        resp.read()
        conn.close()
        assert resp.status == 200, resp.status
        return time.perf_counter() - t0

    # warm one request (compiles/caches nothing on CPU, but fair)
    put(999)

    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(max_workers=args.streams) as ex:
        list(ex.map(put, range(args.streams)))
    wall = time.perf_counter() - t0

    total = args.streams * args.size
    out = {
        "metric": "e2e PutObject GiB/s "
                  f"(EC {args.drives - args.parity}+{args.parity}, "
                  f"{args.streams} concurrent {args.size >> 20} MiB)",
        "value": round(total / wall / 2**30, 3),
        "unit": "GiB/s",
        "wall_s": round(wall, 2),
        "scheduler": {"batches": sched.batches,
                      "coalesced": sched.coalesced},
    }
    print(json.dumps(out))
    srv.stop()
    sets.close()
    sched.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
