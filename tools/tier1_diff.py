#!/usr/bin/env python3
"""tier1_diff — regression gate on the tier-1 FAILURE-NAME SET.

The tier-1 suite carries ~39 environmental failures at the seed
(missing optional modules, sandbox networking), so its raw exit code
says nothing about a change: it is nonzero before AND after. What a
change must not do is add NEW failure names. This tool:

  1. runs the tier-1 pytest command from ROADMAP.md (or parses an
     existing log via --log),
  2. extracts the set of FAILED/ERROR test ids,
  3. diffs it against the committed baseline list (the "Tier-1 failure
     baseline" section of BASELINE.md),
  4. exits nonzero ONLY when new failure names appeared.

Fixed (no-longer-failing) names are reported but never fail the gate —
shrink the baseline with --update once a fix is deliberate.

Usage:
    python tools/tier1_diff.py                 # run suite + diff
    python tools/tier1_diff.py --log t1.log    # diff an existing log
    python tools/tier1_diff.py --log t1.log --update
                                               # rewrite the baseline
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_MD = os.path.join(REPO, "BASELINE.md")
SECTION = "## Tier-1 failure baseline"

# the ROADMAP.md "Tier-1 verify" pytest invocation (sans shell plumbing)
TIER1_CMD = [
    sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
    "--continue-on-collection-errors", "-p", "no:cacheprovider",
    "-p", "no:xdist", "-p", "no:randomly",
]
TIER1_TIMEOUT_S = 870

_FAIL_RE = re.compile(r"^(?:FAILED|ERROR)\s+(\S+?)(?:\s+-\s.*)?$")


def parse_failures(text: str) -> set[str]:
    out = set()
    for line in text.splitlines():
        m = _FAIL_RE.match(line.strip())
        if m:
            out.add(m.group(1).rstrip("-").strip())
    return out


def read_baseline() -> set[str]:
    try:
        with open(BASELINE_MD) as f:
            text = f.read()
    except OSError:
        return set()
    if SECTION not in text:
        return set()
    body = text.split(SECTION, 1)[1]
    # the section runs until the next heading (or EOF)
    body = re.split(r"\n## ", body, 1)[0]
    names = set()
    for line in body.splitlines():
        m = re.match(r"^- `([^`]+)`", line.strip())
        if m:
            names.add(m.group(1))
    return names


def write_baseline(names: set[str]) -> None:
    with open(BASELINE_MD) as f:
        text = f.read()
    lines = [SECTION, "",
             "Failure names (`FAILED`/`ERROR` test ids) present at the "
             "current baseline; `tools/tier1_diff.py` gates on NEW "
             "names only. Regenerate with `--update`.", ""]
    lines += [f"- `{n}`" for n in sorted(names)]
    block = "\n".join(lines) + "\n"
    if SECTION in text:
        head, tail = text.split(SECTION, 1)
        rest = re.split(r"\n(## .*)", tail, 1)
        trailer = "\n".join(rest[1:]) if len(rest) > 1 else ""
        text = head + block + ("\n" + trailer if trailer else "")
    else:
        text = text.rstrip("\n") + "\n\n" + block
    with open(BASELINE_MD, "w") as f:
        f.write(text)


def run_tier1() -> str:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(TIER1_CMD, cwd=REPO, env=env, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        out, _ = proc.communicate(timeout=TIER1_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        out += "\ntier1_diff: suite TIMED OUT\n"
    sys.stdout.write(out[-2000:])       # tail for context
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tier1_diff")
    ap.add_argument("--log", help="parse this pytest log instead of "
                    "running the suite")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the BASELINE.md failure list from "
                    "this run")
    ap.add_argument("--json", metavar="PATH",
                    help="also write a machine-readable report "
                    "(names added/removed vs BASELINE.md, gate "
                    "verdict) to PATH, or '-' for stdout — for CI "
                    "logs")
    args = ap.parse_args(argv)

    if args.log:
        with open(args.log) as f:
            text = f.read()
    else:
        text = run_tier1()
    current = parse_failures(text)
    if args.update:
        write_baseline(current)
        print(f"baseline updated: {len(current)} failure name(s)")
        return 0
    baseline = read_baseline()
    new = sorted(current - baseline)
    fixed = sorted(baseline - current)
    if args.json:
        import json
        report = json.dumps({
            "current_failures": len(current),
            "baseline_failures": len(baseline),
            "new": new,
            "fixed": fixed,
            "gate": "fail" if new else "pass",
        }, indent=2)
        if args.json == "-":
            print(report)
        else:
            with open(args.json, "w") as f:
                f.write(report + "\n")
    print(f"tier-1 failures: {len(current)} current, "
          f"{len(baseline)} baseline")
    if fixed:
        print(f"\nno longer failing ({len(fixed)}) — consider "
              "--update:")
        for n in fixed:
            print(f"  - {n}")
    if new:
        print(f"\nNEW failures ({len(new)}):")
        for n in new:
            print(f"  + {n}")
        return 1
    print("\nno new failure names — gate passes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
