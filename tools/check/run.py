#!/usr/bin/env python3
"""check/run — the correctness-analysis CI gate.

Runs the project-invariant linter over ``minio_tpu/`` and exits nonzero
on any violation, mirroring ``tools/tier1_diff.py``'s role for tests:

    python tools/check/run.py              # full gate
    python tools/check/run.py --json -     # machine-readable report
    python tools/check/run.py --rule lock-blocking
    python tools/check/run.py --write-knob-table   # regen README table

Rules (suppress a line with ``# check: allow(<rule>) <reason>``):

  lock-blocking     no disk I/O / RPC / device dispatch / sleeps /
                    future waits inside `with <mutex>:` in hot modules
  metrics-hygiene   families resolved at init scope, Counters end in
                    _total, one kind+help per name, consistent labels
  knob-env          MINIO_TPU_* env reads only via utils/knobs.py;
                    getter names must be registered; README table fresh
  hook-coverage     engine mutation verbs fire on_namespace_change and
                    on_degraded_write
  error-map         every api_errors class mapped in s3errors (or
                    INTERNAL_ONLY); every referenced code in ERROR_TABLE
  admission         SlowDown sheds + requests_shed_total live ONLY in
                    s3/edge/admission.py (the unified admission plane)
  crashpoint        multi-file commits in the designated commit modules
                    declare a registered crashpoint; hit() names are
                    registered literals; README crashpoint table fresh
  deadline          hot-path shard fan-outs / internode waits carry an
                    explicit deadline or ride the hedged reader /
                    quorum-ack lane (bare .result()/recv flagged);
                    streamed RPC body reads arm a per-read deadline
  fencing           epoch-registry save/load/bump sites go through
                    utils/regfence (lineage chain, write quorum,
                    deterministic pick_best) — split-brain safety
  eventlog          journal emits name a registered event class with
                    declared, bounded-cardinality attrs; README
                    event-class table fresh
"""

from __future__ import annotations

import argparse
import json
import os
import sys

if __package__ in (None, ""):                     # `python tools/check/run.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from check import (core, crashtable, eventtable, knobtable,
                       metricstable, rules_ast, rules_project)
else:
    from . import (core, crashtable, eventtable, knobtable,
                   metricstable, rules_ast, rules_project)


def _group_by_path(violations):
    groups = {}
    for v in violations:
        groups.setdefault(v.path, []).append(v)
    return groups


def run_checks(rules=None):
    """All violations after suppression filtering, plus the sources."""
    sources = core.load_sources()
    by_rel = {s.rel: s for s in sources}
    selected = set(rules or core.RULES)
    vs = []
    if "lock-blocking" in selected:
        vs += rules_ast.check_lock_blocking(sources)
    if "metrics-hygiene" in selected:
        vs += rules_ast.check_metrics_hygiene(sources)
        vs += rules_ast.check_label_cardinality(sources)
        vs += metricstable.check_drift(sources)
    if "knob-env" in selected:
        registered = set(knobtable.load_knobs().KNOBS)
        vs += rules_ast.check_knob_env(sources, registered)
        vs += knobtable.check_drift()
    if "hook-coverage" in selected:
        vs += rules_project.check_hook_coverage(sources)
    if "error-map" in selected:
        vs += rules_project.check_error_map(sources)
    if "admission" in selected:
        vs += rules_ast.check_admission(sources)
    if "crashpoint" in selected:
        points = set(crashtable.load_crashpoints().CRASHPOINTS)
        vs += rules_project.check_crashpoint(sources, points)
        vs += crashtable.check_drift()
    if "deadline" in selected:
        vs += rules_ast.check_deadline(sources)
    if "fencing" in selected:
        vs += rules_project.check_fencing(sources)
    if "crypto-hygiene" in selected:
        vs += rules_project.check_crypto_hygiene(sources)
    if "eventlog" in selected:
        ev_mod = eventtable.load_events()
        classes = {name: ec.attrs for name, ec in ev_mod.EVENTS.items()}
        vs += rules_project.check_eventlog(sources, classes)
        vs += eventtable.check_drift()
    out = []
    for rel, group in _group_by_path(vs).items():
        src = by_rel.get(rel)
        out.extend(core.filter_allowed(src, group) if src else group)
    # a suppression with no stated reason is itself a violation — the
    # comment IS the inline argument a suppression must make
    for src in sources:
        for ln in src.bare_allows:
            rule = sorted(src.allowed.get(ln, {"lock-blocking"}))[0]
            if rule in selected:
                out.append(core.Violation(
                    rule, src.rel, ln,
                    "check: allow() without a reason — state the "
                    "argument inline after the closing paren"))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out, sources


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="check/run")
    ap.add_argument("--json", metavar="PATH",
                    help="write a machine-readable report to PATH "
                    "('-' = stdout) — mirrors tier1_diff.py --json")
    ap.add_argument("--rule", action="append", choices=core.RULES,
                    help="run only this rule (repeatable)")
    ap.add_argument("--write-knob-table", action="store_true",
                    help="regenerate the README knob table from the "
                    "registry and exit")
    ap.add_argument("--write-metrics-table", action="store_true",
                    help="regenerate the README metrics reference "
                    "table from the registry's registration sites and "
                    "exit")
    ap.add_argument("--write-crashpoint-table", action="store_true",
                    help="regenerate the README crashpoint table from "
                    "the registry and exit")
    ap.add_argument("--write-event-table", action="store_true",
                    help="regenerate the README event-class table "
                    "from the registry and exit")
    args = ap.parse_args(argv)

    if args.write_knob_table:
        changed = knobtable.write_table()
        print("README knob table "
              + ("updated" if changed else "already fresh"))
        return 0
    if args.write_metrics_table:
        changed = metricstable.write_table()
        print("README metrics table "
              + ("updated" if changed else "already fresh"))
        return 0
    if args.write_crashpoint_table:
        changed = crashtable.write_table()
        print("README crashpoint table "
              + ("updated" if changed else "already fresh"))
        return 0
    if args.write_event_table:
        changed = eventtable.write_table()
        print("README event-class table "
              + ("updated" if changed else "already fresh"))
        return 0

    violations, sources = run_checks(args.rule)
    per_rule: dict = {}
    for v in violations:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    if args.json:
        report = json.dumps({
            "files_scanned": len(sources),
            "violations": [v.to_dict() for v in violations],
            "per_rule": per_rule,
            "gate": "fail" if violations else "pass",
        }, indent=2)
        if args.json == "-":
            print(report)
        else:
            with open(args.json, "w") as f:
                f.write(report + "\n")
    for v in violations:
        print(v)
    print(f"check: {len(sources)} files, {len(violations)} "
          f"violation(s)"
          + (f" ({', '.join(f'{r}={n}' for r, n in sorted(per_rule.items()))})"
             if per_rule else ""))
    if violations:
        return 1
    print("gate passes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
