"""Per-file AST rules: lock discipline, metrics hygiene, knob reads.

Each rule returns Violations; `core.filter_allowed` applies the
``# check: allow(rule)`` suppressions afterwards.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (Source, Violation, dotted, enclosing_functions,
                   str_const)

# ---------------------------------------------------------------------------
# rule: lock-blocking
# ---------------------------------------------------------------------------

# Hot-path modules: their mutexes sit under per-request traffic, so a
# blocking call inside a `with <lock>:` body convoys every concurrent
# request behind one caller's I/O. Namespace RW locks
# (`ns.new_lock(...).write_locked()`) are exempt by construction — they
# are per-object leases that intentionally span I/O.
LOCK_HOT_MODULES = (
    "minio_tpu/object/metacache.py",
    "minio_tpu/object/cache.py",
    "minio_tpu/object/engine.py",
    "minio_tpu/object/multipart.py",
    "minio_tpu/object/sets.py",
    "minio_tpu/object/server_sets.py",
    "minio_tpu/object/background.py",
    "minio_tpu/parallel/scheduler.py",
    "minio_tpu/parallel/pipeline.py",
    "minio_tpu/parallel/bpool.py",
    "minio_tpu/utils/telemetry.py",
    "minio_tpu/s3/trace.py",
    "minio_tpu/distributed/transport.py",
    "minio_tpu/scan/engine.py",
    "minio_tpu/scan/kernels.py",
)

# a with-context whose final name component looks like a mutex
_LOCK_NAME = re.compile(r"(?i)^_?(?:[a-z0-9]+_)*(?:mu|lock|cond|kick)$")

_OS_BANNED = {
    "replace", "rename", "remove", "unlink", "makedirs", "mkdir",
    "rmdir", "listdir", "scandir", "walk", "stat", "utime", "fsync",
    "open", "close",
}
_OS_PATH_BANNED = {"getsize", "getmtime", "getatime", "exists",
                   "isdir", "isfile"}
_BANNED_PREFIXES = ("shutil.", "socket.", "requests.", "urllib.",
                    "subprocess.")
# blocking calls into the object/storage layer — the metacache bug
# class: a quorum metadata read or erasure write while holding the
# journal lock stalls record(), the PUT hot path
_OBJECT_LAYER = {
    "get_object", "put_object", "delete_object", "delete_objects",
    "get_object_info", "object_versions", "list_objects",
    "list_object_versions", "get_bucket_info", "make_bucket",
    "delete_bucket", "write_metadata", "read_metadata",
    "delete_version", "rename_data", "read_file_stream",
    "for_each_disk", "heal_object",
}
# device dispatch — the PR 6 deadlock class: a mesh/jit launch under a
# lock serializes the backend behind the lock's waiters
_DEVICE = {
    "encode_and_hash_batch", "verify_and_decode_batch",
    "verify_and_recover_batch", "mesh_put_batch", "mesh_get_batch",
    "mesh_heal_batch", "run_batch", "block_until_ready",
}


def _lock_names(with_node: ast.With) -> List[str]:
    names = []
    for item in with_node.items:
        d = dotted(item.context_expr)
        if d and _LOCK_NAME.match(d.split(".")[-1]):
            names.append(d)
    return names


def _banned_of_call(call: ast.Call) -> Optional[str]:
    """Description of the banned operation this call performs, else
    None (the single home of the banned-call table)."""
    d = dotted(call.func)
    if d == "time.sleep":
        return "time.sleep"
    root, _, rest = d.partition(".")
    if root == "os" and rest in _OS_BANNED:
        return f"os.{rest} (disk I/O)"
    if d.startswith("os.path.") and d.split(".")[-1] in _OS_PATH_BANNED:
        return f"{d} (disk stat)"
    if d.startswith(_BANNED_PREFIXES):
        return f"{d} (I/O)"
    if d in ("json.dump", "json.load"):
        return f"{d} (file I/O)"
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "open() (disk I/O)"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = dotted(call.func.value)
        if attr == "result":
            return "future .result()"
        if attr == "wait" and not (
                recv and _LOCK_NAME.match(recv.split(".")[-1])):
            # cond.wait releases the lock it guards — fine; any OTHER
            # .wait (events, futures) blocks while holding
            return f"{recv or '?'}.wait()"
        if attr in _OBJECT_LAYER:
            return f"object/storage-layer call .{attr}()"
        if attr in _DEVICE:
            return f"device dispatch .{attr}()"
    return None


def _helper_banned_map(src: Source) -> Dict[str, str]:
    """method/function name -> banned-op description, for every def in
    this file whose DIRECT body performs a banned call. One level of
    indirection: `with self._mu: self._write_meta(...)` is the same
    hazard as inlining the open() itself."""
    out: Dict[str, str] = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack: List[ast.AST] = list(node.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue                    # nested defs run later
            if isinstance(sub, ast.Call):
                what = _banned_of_call(sub)
                if what is not None:
                    out.setdefault(node.name, what)
                    stack.clear()
                    continue
            stack.extend(ast.iter_child_nodes(sub))
    return out


def _scan_lock_body(src: Source, lock: str, body: List[ast.stmt],
                    helpers: Dict[str, str],
                    out: List[Violation]) -> None:
    def flag(node: ast.AST, what: str) -> None:
        out.append(Violation(
            "lock-blocking", src.rel, node.lineno,
            f"{what} inside `with {lock}:` — blocking work under a "
            "hot lock convoys every waiter; move it outside the "
            "critical section"))

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue            # runs later, not under this hold
            if isinstance(child, ast.Call):
                _check_call(child)
            visit(child)

    def _check_call(call: ast.Call) -> None:
        what = _banned_of_call(call)
        if what is not None:
            flag(call, what)
            return
        # one level of same-file helper indirection
        if isinstance(call.func, ast.Attribute) and \
                dotted(call.func.value) == "self":
            hb = helpers.get(call.func.attr)
            if hb is not None:
                flag(call, f"self.{call.func.attr}() which performs "
                     f"{hb}")

    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue                    # defined under the lock, runs later
        visit(stmt)


def check_lock_blocking(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    hot = set(LOCK_HOT_MODULES)
    for src in sources:
        if src.rel not in hot:
            continue
        helpers = _helper_banned_map(src)
        # manual lock management sidesteps the with-body scan entirely
        # (`x.acquire(); try: ... finally: x.release()` holds the lock
        # across anything) — flag the spelling itself; a deliberate
        # non-blocking try-acquire argues its suppression inline
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                recv = dotted(node.func.value)
                if recv and _LOCK_NAME.match(recv.split(".")[-1]):
                    out.append(Violation(
                        "lock-blocking", src.rel, node.lineno,
                        f"manual {recv}.acquire() — the with-body lint "
                        "cannot see what runs under this hold; use "
                        "`with` or argue a suppression"))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            locks = _lock_names(node)
            if not locks:
                continue
            vs: List[Violation] = []
            _scan_lock_body(src, locks[0], node.body, helpers, vs)
            # suppression on the `with` line (or directly above it)
            # covers the whole body; is_allowed already looks one line
            # up, so no extra offset here
            if src.is_allowed("lock-blocking", node.lineno):
                continue
            out.extend(vs)
    return out


# ---------------------------------------------------------------------------
# rule: metrics-hygiene
# ---------------------------------------------------------------------------

_GETTERS = {"counter", "gauge", "histogram"}
# function names allowed to resolve metric families: init scope and
# the documented resolver conventions (collectors run at exposition
# time; *_metrics/*_counter helpers are called once and cached by
# their callers; `global`-memoized resolvers are the one-time pattern)
_SCOPE_OK = re.compile(r"^(?:__init__|__new__|_?metrics|_?collect\w*|"
                       r"_?register\w*)$")
_SCOPE_OK_SUFFIX = ("_metrics", "_counter", "_gauge", "_histogram",
                    "_families")


def _has_global(fn: ast.AST) -> bool:
    return any(isinstance(n, ast.Global) for n in ast.walk(fn))


def check_metrics_hygiene(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    # family name -> (kind, src.rel, line, help)
    registry: Dict[str, Tuple[str, str, int, Optional[str]]] = {}
    # family name -> {frozenset(labels): (rel, line)}
    labels: Dict[str, Dict[frozenset, Tuple[str, int]]] = {}

    for src in sources:
        encl = enclosing_functions(src.tree)
        # var name (scoped by enclosing fn or None) -> family name
        var_family: Dict[Tuple[Optional[ast.AST], str], str] = {}

        def record_labels(fam: str, call: ast.Call) -> None:
            lbls = frozenset(k.arg for k in call.keywords
                             if k.arg is not None)
            labels.setdefault(fam, {}).setdefault(
                lbls, (src.rel, call.lineno))

        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr in _GETTERS:
                name = str_const(node.args[0]) if node.args else None
                if name is None or not name.startswith("minio_tpu_"):
                    continue
                line = node.lineno
                kind = func.attr
                help_ = str_const(node.args[1]) \
                    if len(node.args) > 1 else None
                if kind == "counter" and not name.endswith("_total"):
                    out.append(Violation(
                        "metrics-hygiene", src.rel, line,
                        f"Counter {name!r} must end in `_total` "
                        "(Prometheus counter naming)"))
                if kind != "counter" and name.endswith("_total"):
                    out.append(Violation(
                        "metrics-hygiene", src.rel, line,
                        f"{kind} {name!r} ends in `_total` but is not "
                        "a Counter"))
                seen = registry.get(name)
                if seen is None:
                    registry[name] = (kind, src.rel, line, help_)
                else:
                    if seen[0] != kind:
                        out.append(Violation(
                            "metrics-hygiene", src.rel, line,
                            f"metric {name!r} registered as {kind} "
                            f"here but {seen[0]} at {seen[1]}:"
                            f"{seen[2]} — one family, one kind"))
                    elif (help_ and seen[3] and help_ != seen[3]):
                        out.append(Violation(
                            "metrics-hygiene", src.rel, line,
                            f"metric {name!r} registered with a "
                            f"different help string than {seen[1]}:"
                            f"{seen[2]} — two subsystems think they "
                            "own this name"))
                # scope discipline: resolving a family takes the
                # registry mutex — never per call on a hot path
                fn = encl.get(node)
                if fn is not None:
                    fname = fn.name
                    ok = (_SCOPE_OK.match(fname)
                          or fname.endswith(_SCOPE_OK_SUFFIX)
                          or _has_global(fn))
                    if not ok:
                        out.append(Violation(
                            "metrics-hygiene", src.rel, line,
                            f"metric family {name!r} resolved inside "
                            f"{fname}() — resolve at init scope (or a "
                            "*_metrics/_collect*/global-memoized "
                            "resolver); registry lookups take the "
                            "global metrics mutex"))
                # direct chain: REGISTRY.counter("n").inc(labels...)
                # handled below via parent scan
            elif func.attr in ("inc", "set", "observe"):
                recv = func.value
                fam: Optional[str] = None
                if isinstance(recv, ast.Call) and \
                        isinstance(recv.func, ast.Attribute) and \
                        recv.func.attr in _GETTERS and recv.args:
                    fam = str_const(recv.args[0])
                elif isinstance(recv, ast.Name):
                    fn = encl.get(node)
                    fam = var_family.get((fn, recv.id)) or \
                        var_family.get((None, recv.id))
                if fam:
                    record_labels(fam, node)

        # second pass: var assignments from registry getters (module
        # and function scope), then re-scan inc/set/observe on them
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in _GETTERS \
                    and node.value.args:
                fam = str_const(node.value.args[0])
                if fam and fam.startswith("minio_tpu_"):
                    var_family[(encl.get(node), node.targets[0].id)] = fam
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("inc", "set", "observe") and \
                    isinstance(node.func.value, ast.Name):
                fn = encl.get(node)
                fam = var_family.get((fn, node.func.value.id)) or \
                    var_family.get((None, node.func.value.id))
                if fam:
                    record_labels(fam, node)

    # label-set consistency per family across the whole tree
    for fam, sets_ in labels.items():
        if len(sets_) > 1:
            items = sorted(sets_.items(), key=lambda kv: kv[1])
            first_lbls, (rel0, ln0) = items[0]
            for lbls, (rel, ln) in items[1:]:
                out.append(Violation(
                    "metrics-hygiene", rel, ln,
                    f"metric {fam!r} used with labels "
                    f"{sorted(lbls) or '(none)'} here but "
                    f"{sorted(first_lbls) or '(none)'} at {rel0}:{ln0} "
                    "— label sets must be consistent per family"))
    return out


# ---------------------------------------------------------------------------
# rule: metrics-hygiene / label cardinality
# ---------------------------------------------------------------------------

# Hot-path modules whose metric label VALUES must stay bounded: a
# per-request metric labelled by a raw bucket/object/key name grows one
# series per distinct name — unbounded registry memory, an exposition
# whose size scales with the namespace, and a Prometheus server that
# falls over on the scrape. Bounded labels (verb, api, reason, stage,
# target, node, kind, source, consumer, tier, pool, loop, path-as-enum)
# come from small closed vocabularies and stay clean.
CARDINALITY_HOT_MODULES = LOCK_HOT_MODULES + (
    "minio_tpu/s3/handlers.py",
    "minio_tpu/s3/edge/dispatch.py",
    "minio_tpu/s3/edge/server.py",
    "minio_tpu/s3/edge/admission.py",
    "minio_tpu/s3/qos.py",
    "minio_tpu/object/codec.py",
    "minio_tpu/object/healing.py",
)
# label KEYS that name request-derived identifiers: always unbounded,
# regardless of what expression feeds them
_UNBOUNDED_LABEL_KEYS = {
    "bucket", "object", "key", "obj", "etag", "version_id",
    "upload_id", "prefix", "trace_id", "request_id", "caller",
}
# non-constant label VALUE expressions whose terminal name screams
# request-derived (counter.inc(verb=bucket) is the same bug with a
# clean key)
_UNBOUNDED_VALUE_NAMES = _UNBOUNDED_LABEL_KEYS | {"path", "name"}

_METRIC_METHODS = {"inc", "set", "observe"}


def check_label_cardinality(sources: List[Source]) -> List[Violation]:
    """metrics-hygiene sub-rule: in hot-path modules, metric label
    values must come from bounded vocabularies — raw bucket/object/key
    names (or any request-derived value) as a label value fails."""
    out: List[Violation] = []
    hot = set(CARDINALITY_HOT_MODULES)
    for src in sources:
        if src.rel not in hot:
            continue
        encl = enclosing_functions(src.tree)
        # getter aliases (`g = telemetry.REGISTRY.gauge; g("n").set(…)`)
        # — the attribute-only scan's blind spot; ONE scanner shared
        # with the metrics table so the lint and the README can never
        # disagree on which registration sites exist
        from .metricstable import getter_aliases
        aliases = getter_aliases(src.tree)
        # var name (scoped like the hygiene rule) -> metric family
        var_family: Dict[Tuple[Optional[ast.AST], str], str] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in _GETTERS \
                    and node.value.args:
                fam = str_const(node.value.args[0])
                if fam and fam.startswith("minio_"):
                    var_family[(encl.get(node), node.targets[0].id)] = fam
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS):
                continue
            recv = node.func.value
            fam: Optional[str] = None
            if isinstance(recv, ast.Call) and recv.args and (
                    (isinstance(recv.func, ast.Attribute)
                     and recv.func.attr in _GETTERS)
                    or (isinstance(recv.func, ast.Name)
                        and recv.func.id in aliases)):
                fam = str_const(recv.args[0])
            elif isinstance(recv, ast.Name):
                fn = encl.get(node)
                fam = var_family.get((fn, recv.id)) or \
                    var_family.get((None, recv.id))
            if not fam:
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                if kw.arg in _UNBOUNDED_LABEL_KEYS:
                    out.append(Violation(
                        "metrics-hygiene", src.rel, node.lineno,
                        f"metric {fam!r} labelled by request-derived "
                        f"{kw.arg!r} — one series per distinct "
                        "bucket/object/key is unbounded cardinality; "
                        "aggregate or drop the label"))
                    continue
                if isinstance(kw.value, ast.Constant):
                    continue            # literal value: bounded
                d = dotted(kw.value)
                if d and d.split(".")[-1] in _UNBOUNDED_VALUE_NAMES:
                    out.append(Violation(
                        "metrics-hygiene", src.rel, node.lineno,
                        f"metric {fam!r} label {kw.arg!r} fed by "
                        f"request-derived value `{d}` — unbounded "
                        "cardinality in a hot-path module"))
    return out


# ---------------------------------------------------------------------------
# rule: knob-env
# ---------------------------------------------------------------------------

_KNOB_GETTERS = {"get_str", "get_int", "get_float", "get_bool",
                 "get_raw", "is_set", "get"}


def check_knob_env(sources: List[Source],
                   registered: Set[str]) -> List[Violation]:
    """All MINIO_TPU_* environment access goes through utils/knobs.py;
    knob getter calls must name a registered knob."""
    out: List[Violation] = []
    for src in sources:
        is_knobs = src.rel.endswith("utils/knobs.py")
        for node in ast.walk(src.tree):
            # os.environ.get("MINIO_TPU_...") / os.getenv(...)
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in ("os.environ.get", "os.getenv", "os.environ.pop",
                         "os.environ.setdefault") and not is_knobs:
                    name = str_const(node.args[0]) if node.args else None
                    if name and name.startswith("MINIO_TPU_"):
                        out.append(Violation(
                            "knob-env", src.rel, node.lineno,
                            f"raw environ access for {name!r} — go "
                            "through minio_tpu/utils/knobs.py "
                            "(declare the knob there)"))
                elif d.split(".")[-1] in _KNOB_GETTERS and \
                        d.split(".")[0] in ("knobs",) and node.args:
                    name = str_const(node.args[0])
                    if name and name not in registered:
                        out.append(Violation(
                            "knob-env", src.rel, node.lineno,
                            f"knobs getter names unregistered knob "
                            f"{name!r} — declare it in utils/knobs.py"))
            # os.environ["MINIO_TPU_..."] (read or write)
            elif isinstance(node, ast.Subscript) and not is_knobs:
                if dotted(node.value) == "os.environ":
                    name = str_const(node.slice)
                    if name and name.startswith("MINIO_TPU_"):
                        out.append(Violation(
                            "knob-env", src.rel, node.lineno,
                            f"raw os.environ[{name!r}] — go through "
                            "minio_tpu/utils/knobs.py"))
            # "MINIO_TPU_X" in os.environ
            elif isinstance(node, ast.Compare) and not is_knobs:
                if len(node.comparators) == 1 and \
                        dotted(node.comparators[0]) == "os.environ":
                    name = str_const(node.left)
                    if name and name.startswith("MINIO_TPU_"):
                        out.append(Violation(
                            "knob-env", src.rel, node.lineno,
                            f"raw `{name} in os.environ` — use "
                            "knobs.is_set()"))
    return out


# ---------------------------------------------------------------------------
# rule: admission
# ---------------------------------------------------------------------------

# The ONE module allowed to make shed decisions: every SlowDown
# construction and every requests_shed_total reference lives here, so
# the edge, the threaded frontend and the handlers cannot each grow a
# private shed path that diverges in counters or Retry-After/close
# semantics (migrating the handlers' original shed window into the
# controller is what proved this rule fires).
ADMISSION_MODULE = "minio_tpu/s3/edge/admission.py"
SHED_COUNTER = "minio_tpu_requests_shed_total"

# The refusal probes of the tenant QoS plane: TokenBucket.try_take /
# TokenBucket.peek answer "would this request fit the budget RIGHT
# NOW" — the only legitimate consumers are the AdmissionController and
# the QoS plane it consults (plus the bucket implementation itself).
# A try_take/peek anywhere else is a private shed path in the making:
# the caller has a refusal in hand and nowhere to route it but its own
# 503. (Blocking `take()` stays free — pacing is not a refusal.)
QOS_PROBE_MODULES = (
    ADMISSION_MODULE,
    "minio_tpu/s3/qos.py",
    "minio_tpu/utils/bandwidth.py",
)
_QOS_PROBE_ATTRS = ("try_take", "peek")


def check_admission(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    probe_free = set(QOS_PROBE_MODULES)
    for src in sources:
        if src.rel not in probe_free:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Call) and node.args and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _QOS_PROBE_ATTRS:
                    out.append(Violation(
                        "admission", src.rel, node.lineno,
                        f".{node.func.attr}() budget probe outside the "
                        "admission/QoS plane — a tenant-budget refusal "
                        "must shed through "
                        f"{ADMISSION_MODULE}, never a private 503 path"))
        if src.rel == ADMISSION_MODULE:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and node.args and \
                    dotted(node.func).split(".")[-1] == "S3Error" and \
                    str_const(node.args[0]) == "SlowDown":
                out.append(Violation(
                    "admission", src.rel, node.lineno,
                    "S3Error(\"SlowDown\") constructed outside the "
                    "AdmissionController — every shed decision must go "
                    f"through {ADMISSION_MODULE}"))
            elif isinstance(node, ast.Constant) and \
                    node.value == SHED_COUNTER:
                out.append(Violation(
                    "admission", src.rel, node.lineno,
                    f"{SHED_COUNTER} referenced outside the "
                    "AdmissionController — shed accounting has ONE "
                    f"home, {ADMISSION_MODULE}"))
    return out


# ---------------------------------------------------------------------------
# rule: deadline
# ---------------------------------------------------------------------------

# Hot-path fan-out modules: every shard fan-out / internode wait here
# sits under per-request traffic, so a bare unbounded `.result()` (or
# raw socket `.recv`) lets ONE gray drive or peer hold a whole
# GET/PUT — the exact tail-latency hole the hedged reader and the
# quorum-ack lane exist to close. A wait is clean when it carries a
# timeout argument, rides the hedged reader / for_each_disk_quorum, or
# argues its bound inline via `# check: allow(deadline) <reason>`.
DEADLINE_HOT_MODULES = (
    "minio_tpu/object/engine.py",
    "minio_tpu/object/metadata.py",
    "minio_tpu/object/multipart.py",
    "minio_tpu/object/healing.py",
    "minio_tpu/distributed/transport.py",
    "minio_tpu/distributed/storage_rpc.py",
    "minio_tpu/distributed/peer_rpc.py",
)

_UNBOUNDED_WAIT_ATTRS = {"recv", "recv_into"}

# streamed-RPC body reads: a peer that goes silent after sending its
# headers parks a bare http resp.read()/readline() forever — the
# connection timeout only covers the DIAL. Every such read in a hot
# module must sit in a function that arms a per-read socket deadline
# (settimeout / _arm_read_deadline) or builds the connection with an
# explicit timeout (whole-body reads under the request window).
_STREAM_READ_ATTRS = {"read", "readline"}


def _read_deadline_armed(fn) -> bool:
    if fn is None:
        return False
    for c in ast.walk(fn):
        if not isinstance(c, ast.Call):
            continue
        tail = dotted(c.func).rsplit(".", 1)[-1]
        if tail in ("settimeout", "_arm_read_deadline"):
            return True
        if tail == "HTTPConnection" and any(
                kw.arg == "timeout" for kw in c.keywords):
            return True
    return False


def check_deadline(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    hot = set(DEADLINE_HOT_MODULES)
    for src in sources:
        if src.rel not in hot:
            continue
        enclosing = enclosing_functions(src.tree)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in _STREAM_READ_ATTRS:
                recv = dotted(node.func.value)
                if recv.endswith("resp") and \
                        not _read_deadline_armed(enclosing.get(node)):
                    out.append(Violation(
                        "deadline", src.rel, node.lineno,
                        f"{recv}.{attr}() without a read deadline — a "
                        "peer going silent mid-stream parks this "
                        "forever; arm the socket (settimeout / "
                        "_arm_read_deadline) or bound the connection, "
                        "or argue the bound inline"))
                continue
            if attr == "result":
                bounded = bool(node.args) or any(
                    kw.arg == "timeout" for kw in node.keywords)
                if not bounded:
                    out.append(Violation(
                        "deadline", src.rel, node.lineno,
                        "bare unbounded future .result() on a "
                        "hot-path fan-out — pass a timeout, ride the "
                        "hedged reader / for_each_disk_quorum lane, "
                        "or argue the bound inline"))
            elif attr in _UNBOUNDED_WAIT_ATTRS:
                out.append(Violation(
                    "deadline", src.rel, node.lineno,
                    f"raw socket .{attr}() on a hot-path module — "
                    "set a socket timeout and argue the bound inline"))
    return out
