"""README event-class-table generator + drift check.

The table between the ``EVENT_TABLE`` markers in README.md is
GENERATED from the declarative registry in
``minio_tpu/utils/eventlog.py`` — never hand-edited (the knob-table
pattern). The ``eventlog`` lint rule fails when the committed table
drifts; ``run.py --write-event-table`` regenerates it.

eventlog.py keeps its registry half dependency-free (its pubsub/
atomicfile/knobs imports are lazy) precisely so it loads standalone
here — no jax, no package import, no side effects.
"""

from __future__ import annotations

import importlib.util
import os
from typing import List

from .core import REPO, Violation

EVENTLOG_PATH = os.path.join(REPO, "minio_tpu", "utils", "eventlog.py")
README = os.path.join(REPO, "README.md")


def load_events():
    spec = importlib.util.spec_from_file_location("_check_eventlog",
                                                  EVENTLOG_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)          # type: ignore[union-attr]
    return mod


def generated_block(mod=None) -> str:
    mod = mod or load_events()
    return (mod.TABLE_BEGIN + "\n\n" + mod.render_table() + "\n"
            + mod.TABLE_END)


def _split_readme(text: str, mod) -> tuple:
    b, e = mod.TABLE_BEGIN, mod.TABLE_END
    if b not in text or e not in text:
        return None
    head, rest = text.split(b, 1)
    _, tail = rest.split(e, 1)
    return head, tail


def check_drift() -> List[Violation]:
    mod = load_events()
    try:
        with open(README, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return [Violation("eventlog", "README.md", 1,
                          "README.md not readable")]
    parts = _split_readme(text, mod)
    if parts is None:
        return [Violation(
            "eventlog", "README.md", 1,
            "event-table markers missing — add "
            f"{mod.TABLE_BEGIN!r} … {mod.TABLE_END!r} and run "
            "tools/check/run.py --write-event-table")]
    head, tail = parts
    current = text[len(head):len(text) - len(tail)]
    if current.strip() != generated_block(mod).strip():
        line = head.count("\n") + 1
        return [Violation(
            "eventlog", "README.md", line,
            "event-class table drifted from the registry in "
            "minio_tpu/utils/eventlog.py — regenerate with "
            "`python tools/check/run.py --write-event-table`")]
    return []


def write_table() -> bool:
    """Regenerate the README block in place; returns True on change."""
    mod = load_events()
    with open(README, encoding="utf-8") as f:
        text = f.read()
    parts = _split_readme(text, mod)
    if parts is None:
        raise SystemExit("README.md event-table markers missing — "
                         f"add {mod.TABLE_BEGIN}\n{mod.TABLE_END}")
    head, tail = parts
    new = head + generated_block(mod) + tail
    if new == text:
        return False
    with open(README, "w", encoding="utf-8") as f:
        f.write(new)
    return True
