"""check.core — shared machinery of the project-invariant linter.

The linter is AST-based and project-specific: its rules encode the
invariants review rounds kept re-catching by hand (blocking work under
hot locks, per-call metric-family resolution, raw env knob reads,
mutation verbs that forget their hooks, error codes missing from the
S3 table). Rules live in `rules_ast.py` (per-file) and
`rules_project.py` (cross-file); `run.py` is the CLI gate.

Suppression: a violation is silenced by a ``# check: allow(rule-id)``
comment on the SAME line or the line directly above — the comment is
the inline argument the review would otherwise have to make, so bare
suppressions without a trailing reason are themselves flagged.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG_ROOT = os.path.join(REPO, "minio_tpu")

RULES = ("lock-blocking", "metrics-hygiene", "knob-env",
         "hook-coverage", "error-map", "admission", "crashpoint",
         "deadline", "fencing", "crypto-hygiene", "eventlog")

_ALLOW_RE = re.compile(r"#\s*check:\s*allow\(([a-z\-]+(?:\s*,\s*[a-z\-]+)*)\)"
                       r"(.*)$")


@dataclass
class Violation:
    rule: str
    path: str        # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class Source:
    """One parsed file: text, AST, and the allow()-comment map."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        # line -> set of allowed rule ids on that line
        self.allowed: Dict[int, Set[str]] = {}
        self.bare_allows: List[int] = []
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",")}
            self.allowed[i] = rules
            if not m.group(2).strip():
                # an allow() with no trailing reason is a suppression
                # without an argument — the review the comment replaces
                self.bare_allows.append(i)

    def is_allowed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            if rule in self.allowed.get(ln, ()):
                return True
        return False


def load_sources(root: str = PKG_ROOT) -> List[Source]:
    out: List[Source] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            out.append(Source(path, rel, text))
    return out


def dotted(node: ast.AST) -> str:
    """'os.path.getsize' for an Attribute/Name chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def enclosing_functions(tree: ast.AST) -> Dict[ast.AST, Optional[ast.AST]]:
    """node -> nearest enclosing FunctionDef/AsyncFunctionDef (None at
    module/class scope)."""
    out: Dict[ast.AST, Optional[ast.AST]] = {}

    def walk(node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            out[child] = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, child)
            else:
                walk(child, fn)

    walk(tree, None)
    return out


def filter_allowed(src: Source, vs: Iterable[Violation]) -> List[Violation]:
    return [v for v in vs if not src.is_allowed(v.rule, v.line)]
