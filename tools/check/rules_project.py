"""Cross-file rules: mutation-hook coverage and error-map completeness."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Source, Violation, dotted, str_const

# ---------------------------------------------------------------------------
# rule: hook-coverage
# ---------------------------------------------------------------------------

# Engine files whose classes form THE mutation surface (MultipartMixin
# subclasses ErasureObjects; methods merge into one verb map).
HOOK_FILES = ("minio_tpu/object/engine.py",
              "minio_tpu/object/multipart.py")
HOOK_CLASSES = ("ErasureObjects", "MultipartMixin")

# every successful namespace mutation must reach the metacache/cache
# delta feed
NAMESPACE_VERBS = (
    "put_object", "update_object_metadata", "transition_object",
    "put_stub_version", "delete_object", "put_delete_marker",
    "delete_objects", "complete_multipart_upload",
)
NAMESPACE_HOOK = "_notify_namespace"

# the replication-queue chain: every mutation verb reaches the
# replication plane THROUGH the namespace feed — verb fires
# _notify_namespace (checked above), the dispatcher fans out to
# registered listeners, attach_replication registers the plane's
# on_namespace_change, and cluster boot attaches the plane. Each link
# is pinned here so an ad-hoc enqueue refactor (the pre-plane state,
# which missed bulk delete and multipart commit) can't come back.
REPL_SERVER_SETS = "minio_tpu/object/server_sets.py"
REPL_PLANE = "minio_tpu/replicate/plane.py"
REPL_CLUSTER = "minio_tpu/cluster.py"

# every quorum-successful-but-degraded write must feed the MRF queue
DEGRADED_VERBS = (
    "put_object", "update_object_metadata", "transition_object",
    "put_stub_version", "delete_object", "put_delete_marker",
    "delete_objects", "complete_multipart_upload",
)
DEGRADED_HOOKS = ("_notify_degraded", "_flag_degraded_delete")

_MAX_DEPTH = 3


def _class_methods(sources: List[Source]) -> Dict[str, ast.FunctionDef]:
    methods: Dict[str, ast.FunctionDef] = {}
    by_rel = {s.rel: s for s in sources}
    for rel in HOOK_FILES:
        src = by_rel.get(rel)
        if src is None:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name in HOOK_CLASSES:
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        methods.setdefault(item.name, item)
    return methods


def _self_calls(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                dotted(node.func.value) == "self":
            out.add(node.func.attr)
    return out


def _reaches(methods: Dict[str, ast.FunctionDef], start: str,
             targets: Set[str], depth: int = _MAX_DEPTH) -> bool:
    seen: Set[str] = set()
    frontier = {start}
    for _ in range(depth):
        nxt: Set[str] = set()
        for name in frontier:
            fn = methods.get(name)
            if fn is None or name in seen:
                continue
            seen.add(name)
            calls = _self_calls(fn)
            if calls & targets:
                return True
            nxt |= calls
        frontier = nxt - seen
        if not frontier:
            return False
    return False


def check_hook_coverage(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    methods = _class_methods(sources)
    by_rel = {s.rel: s for s in sources}

    def src_of(fn: ast.FunctionDef) -> str:
        # find which hook file holds this def (line collision is
        # irrelevant — message only)
        for rel in HOOK_FILES:
            src = by_rel.get(rel)
            if src and any(n is fn for n in ast.walk(src.tree)):
                return rel
        return HOOK_FILES[0]

    for verb in NAMESPACE_VERBS:
        fn = methods.get(verb)
        if fn is None:
            out.append(Violation(
                "hook-coverage", HOOK_FILES[0], 1,
                f"configured mutation verb {verb}() not found — "
                "update NAMESPACE_VERBS in tools/check"))
            continue
        if not _reaches(methods, verb, {NAMESPACE_HOOK}):
            out.append(Violation(
                "hook-coverage", src_of(fn), fn.lineno,
                f"mutation verb {verb}() never fires "
                f"{NAMESPACE_HOOK}() — the metacache/cache delta feed "
                "misses this mutation (stale listings + stale cache)"))
    for verb in DEGRADED_VERBS:
        fn = methods.get(verb)
        if fn is None:
            continue            # already reported above
        if not _reaches(methods, verb, set(DEGRADED_HOOKS)):
            out.append(Violation(
                "hook-coverage", src_of(fn), fn.lineno,
                f"write verb {verb}() never fires on_degraded_write "
                f"(via {' / '.join(DEGRADED_HOOKS)}) — a degraded "
                "quorum write waits for the scanner instead of MRF"))
    out.extend(_check_replication_chain(sources))
    return out


def _fn_in_class(src: Source, cls: str, name: str
                 ) -> Optional[ast.FunctionDef]:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and \
                        item.name == name:
                    return item
    return None


def _calls_method(tree: ast.AST, method: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == method:
            return True
    return False


def _check_replication_chain(sources: List[Source]) -> List[Violation]:
    """Prove every mutation verb reaches the replication queue: the
    namespace feed's verb coverage is checked above; these links pin
    feed -> plane. Broken link = replication silently misses verbs."""
    out: List[Violation] = []
    by_rel = {s.rel: s for s in sources}

    ss = by_rel.get(REPL_SERVER_SETS)
    if ss is not None:
        attach = _fn_in_class(ss, "ErasureServerSets",
                              "attach_replication")
        if attach is None:
            out.append(Violation(
                "hook-coverage", REPL_SERVER_SETS, 1,
                "ErasureServerSets.attach_replication() missing — the "
                "replication plane has no way onto the namespace feed"))
        elif not _calls_method(attach, "register_namespace_listener"):
            out.append(Violation(
                "hook-coverage", REPL_SERVER_SETS, attach.lineno,
                "attach_replication() never calls "
                "register_namespace_listener() — mutation verbs would "
                "not reach the replication queue"))

    plane = by_rel.get(REPL_PLANE)
    if plane is not None:
        if _fn_in_class(plane, "ReplicationPlane",
                        "on_namespace_change") is None:
            out.append(Violation(
                "hook-coverage", REPL_PLANE, 1,
                "ReplicationPlane.on_namespace_change() missing — the "
                "feed listener the attach wires is gone"))

    cluster = by_rel.get(REPL_CLUSTER)
    if cluster is not None and plane is not None and ss is not None:
        if not _calls_method(cluster.tree, "attach_replication"):
            out.append(Violation(
                "hook-coverage", REPL_CLUSTER, 1,
                "cluster boot never calls attach_replication() — the "
                "plane exists but no mutation verb would reach it"))
    return out


# ---------------------------------------------------------------------------
# rule: error-map
# ---------------------------------------------------------------------------

API_ERRORS = "minio_tpu/object/api_errors.py"
S3_ERRORS = "minio_tpu/s3/s3errors.py"


def _api_error_classes(src: Source) -> Dict[str, int]:
    """name -> lineno of every (transitive) ObjectApiError subclass."""
    bases: Dict[str, List[str]] = {}
    lines: Dict[str, int] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef):
            bases[node.name] = [dotted(b) for b in node.bases]
            lines[node.name] = node.lineno

    def is_api_err(name: str, seen: Set[str]) -> bool:
        if name == "ObjectApiError":
            return True
        if name in seen:
            return False
        seen.add(name)
        return any(is_api_err(b, seen) for b in bases.get(name, ()))

    return {n: lines[n] for n in bases
            if n != "ObjectApiError" and is_api_err(n, set())}


def check_error_map(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    by_rel = {s.rel: s for s in sources}
    api = by_rel.get(API_ERRORS)
    s3 = by_rel.get(S3_ERRORS)
    if api is None or s3 is None:
        return [Violation("error-map", API_ERRORS, 1,
                          "api_errors.py / s3errors.py not found")]
    classes = _api_error_classes(api)

    table_keys: Set[str] = set()
    mapped: Dict[str, str] = {}       # class name -> code
    internal: Set[str] = set()
    map_line = 1
    for node in ast.walk(s3.tree):
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt, value = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, value = node.targets[0], node.value
        else:
            continue
        tname = tgt.id if isinstance(tgt, ast.Name) else ""
        if tname == "ERROR_TABLE" and isinstance(value, ast.Dict):
            for k in value.keys:
                s = str_const(k)
                if s:
                    table_keys.add(s)
        elif tname == "INTERNAL_ONLY" and \
                isinstance(value, (ast.Tuple, ast.List)):
            for el in value.elts:
                d = dotted(el)
                if d:
                    internal.add(d.split(".")[-1])
        elif tname == "mapping" and \
                isinstance(value, (ast.List, ast.Tuple)):
            map_line = node.lineno
            for el in value.elts:
                if isinstance(el, ast.Tuple) and len(el.elts) == 2:
                    cls = dotted(el.elts[0])
                    code = str_const(el.elts[1])
                    if cls.startswith("oerr.") and code:
                        mapped[cls.split(".")[-1]] = code

    # `mapping` may be a local inside api_error_from
    for name, line in sorted(classes.items()):
        if name not in mapped and name not in internal:
            out.append(Violation(
                "error-map", API_ERRORS, line,
                f"{name} has no api_error_from mapping in s3errors.py "
                "and is not declared INTERNAL_ONLY — it would surface "
                "as a 500 InternalError"))
    for cls, code in sorted(mapped.items()):
        if code not in table_keys:
            out.append(Violation(
                "error-map", S3_ERRORS, map_line,
                f"mapping for {cls} names code {code!r} which is not "
                "in ERROR_TABLE"))
    for name in sorted(internal):
        if name not in classes:
            out.append(Violation(
                "error-map", S3_ERRORS, map_line,
                f"INTERNAL_ONLY names {name!r} which is not an "
                "api_errors class"))

    # every literal S3Error("Code") raised anywhere must be in the table
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and node.args:
                d = dotted(node.func)
                if d.split(".")[-1] == "S3Error":
                    code = str_const(node.args[0])
                    if code and code not in table_keys:
                        out.append(Violation(
                            "error-map", src.rel, node.lineno,
                            f"S3Error({code!r}) — code missing from "
                            "ERROR_TABLE (clients would get a bare "
                            "500 with no usable code)"))
    return out
