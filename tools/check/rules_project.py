"""Cross-file rules: mutation-hook coverage and error-map completeness."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Source, Violation, dotted, str_const

# ---------------------------------------------------------------------------
# rule: hook-coverage
# ---------------------------------------------------------------------------

# Engine files whose classes form THE mutation surface (MultipartMixin
# subclasses ErasureObjects; methods merge into one verb map).
HOOK_FILES = ("minio_tpu/object/engine.py",
              "minio_tpu/object/multipart.py")
HOOK_CLASSES = ("ErasureObjects", "MultipartMixin")

# every successful namespace mutation must reach the metacache/cache
# delta feed
NAMESPACE_VERBS = (
    "put_object", "update_object_metadata", "transition_object",
    "put_stub_version", "delete_object", "put_delete_marker",
    "delete_objects", "complete_multipart_upload",
)
NAMESPACE_HOOK = "_notify_namespace"

# the replication-queue chain: every mutation verb reaches the
# replication plane THROUGH the namespace feed — verb fires
# _notify_namespace (checked above), the dispatcher fans out to
# registered listeners, attach_replication registers the plane's
# on_namespace_change, and cluster boot attaches the plane. Each link
# is pinned here so an ad-hoc enqueue refactor (the pre-plane state,
# which missed bulk delete and multipart commit) can't come back.
REPL_SERVER_SETS = "minio_tpu/object/server_sets.py"
REPL_PLANE = "minio_tpu/replicate/plane.py"
REPL_CLUSTER = "minio_tpu/cluster.py"

# the notification plane rides the same feed — same chain, same rule
NOTIFY_PLANE = "minio_tpu/notify/plane.py"

# every quorum-successful-but-degraded write must feed the MRF queue
DEGRADED_VERBS = (
    "put_object", "update_object_metadata", "transition_object",
    "put_stub_version", "delete_object", "put_delete_marker",
    "delete_objects", "complete_multipart_upload",
)
DEGRADED_HOOKS = ("_notify_degraded", "_flag_degraded_delete")

_MAX_DEPTH = 3


def _class_methods(sources: List[Source]) -> Dict[str, ast.FunctionDef]:
    methods: Dict[str, ast.FunctionDef] = {}
    by_rel = {s.rel: s for s in sources}
    for rel in HOOK_FILES:
        src = by_rel.get(rel)
        if src is None:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name in HOOK_CLASSES:
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        methods.setdefault(item.name, item)
    return methods


def _self_calls(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                dotted(node.func.value) == "self":
            out.add(node.func.attr)
    return out


def _reaches(methods: Dict[str, ast.FunctionDef], start: str,
             targets: Set[str], depth: int = _MAX_DEPTH) -> bool:
    seen: Set[str] = set()
    frontier = {start}
    for _ in range(depth):
        nxt: Set[str] = set()
        for name in frontier:
            fn = methods.get(name)
            if fn is None or name in seen:
                continue
            seen.add(name)
            calls = _self_calls(fn)
            if calls & targets:
                return True
            nxt |= calls
        frontier = nxt - seen
        if not frontier:
            return False
    return False


def check_hook_coverage(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    methods = _class_methods(sources)
    by_rel = {s.rel: s for s in sources}

    def src_of(fn: ast.FunctionDef) -> str:
        # find which hook file holds this def (line collision is
        # irrelevant — message only)
        for rel in HOOK_FILES:
            src = by_rel.get(rel)
            if src and any(n is fn for n in ast.walk(src.tree)):
                return rel
        return HOOK_FILES[0]

    for verb in NAMESPACE_VERBS:
        fn = methods.get(verb)
        if fn is None:
            out.append(Violation(
                "hook-coverage", HOOK_FILES[0], 1,
                f"configured mutation verb {verb}() not found — "
                "update NAMESPACE_VERBS in tools/check"))
            continue
        if not _reaches(methods, verb, {NAMESPACE_HOOK}):
            out.append(Violation(
                "hook-coverage", src_of(fn), fn.lineno,
                f"mutation verb {verb}() never fires "
                f"{NAMESPACE_HOOK}() — the metacache/cache delta feed "
                "misses this mutation (stale listings + stale cache)"))
    for verb in DEGRADED_VERBS:
        fn = methods.get(verb)
        if fn is None:
            continue            # already reported above
        if not _reaches(methods, verb, set(DEGRADED_HOOKS)):
            out.append(Violation(
                "hook-coverage", src_of(fn), fn.lineno,
                f"write verb {verb}() never fires on_degraded_write "
                f"(via {' / '.join(DEGRADED_HOOKS)}) — a degraded "
                "quorum write waits for the scanner instead of MRF"))
    out.extend(_check_replication_chain(sources))
    out.extend(_check_notify_chain(sources))
    return out


def _fn_in_class(src: Source, cls: str, name: str
                 ) -> Optional[ast.FunctionDef]:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and \
                        item.name == name:
                    return item
    return None


def _calls_method(tree: ast.AST, method: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == method:
            return True
    return False


def _check_replication_chain(sources: List[Source]) -> List[Violation]:
    """Prove every mutation verb reaches the replication queue: the
    namespace feed's verb coverage is checked above; these links pin
    feed -> plane. Broken link = replication silently misses verbs."""
    out: List[Violation] = []
    by_rel = {s.rel: s for s in sources}

    ss = by_rel.get(REPL_SERVER_SETS)
    if ss is not None:
        attach = _fn_in_class(ss, "ErasureServerSets",
                              "attach_replication")
        if attach is None:
            out.append(Violation(
                "hook-coverage", REPL_SERVER_SETS, 1,
                "ErasureServerSets.attach_replication() missing — the "
                "replication plane has no way onto the namespace feed"))
        elif not _calls_method(attach, "register_namespace_listener"):
            out.append(Violation(
                "hook-coverage", REPL_SERVER_SETS, attach.lineno,
                "attach_replication() never calls "
                "register_namespace_listener() — mutation verbs would "
                "not reach the replication queue"))

    plane = by_rel.get(REPL_PLANE)
    if plane is not None:
        if _fn_in_class(plane, "ReplicationPlane",
                        "on_namespace_change") is None:
            out.append(Violation(
                "hook-coverage", REPL_PLANE, 1,
                "ReplicationPlane.on_namespace_change() missing — the "
                "feed listener the attach wires is gone"))

    cluster = by_rel.get(REPL_CLUSTER)
    if cluster is not None and plane is not None and ss is not None:
        if not _calls_method(cluster.tree, "attach_replication"):
            out.append(Violation(
                "hook-coverage", REPL_CLUSTER, 1,
                "cluster boot never calls attach_replication() — the "
                "plane exists but no mutation verb would reach it"))
    return out


def _check_notify_chain(sources: List[Source]) -> List[Violation]:
    """Prove every mutation verb reaches bucket event notification:
    verb coverage of the feed is checked above; these links pin
    feed -> NotificationPlane. Broken link = events silently stop
    for some (or all) mutation verbs. The chain is only enforced when
    the scanned set carries the plane module (fixture trees that never
    mention notifications stay out of scope; deleting the real module
    breaks cluster boot imports long before this rule matters)."""
    out: List[Violation] = []
    by_rel = {s.rel: s for s in sources}
    plane = by_rel.get(NOTIFY_PLANE)
    if plane is None:
        return out

    ss = by_rel.get(REPL_SERVER_SETS)
    if ss is not None:
        attach = _fn_in_class(ss, "ErasureServerSets",
                              "attach_notifications")
        if attach is None:
            out.append(Violation(
                "hook-coverage", REPL_SERVER_SETS, 1,
                "ErasureServerSets.attach_notifications() missing — "
                "the notification plane has no way onto the namespace "
                "feed"))
        elif not _calls_method(attach, "register_namespace_listener"):
            out.append(Violation(
                "hook-coverage", REPL_SERVER_SETS, attach.lineno,
                "attach_notifications() never calls "
                "register_namespace_listener() — mutation verbs would "
                "not reach the notification queue"))

    if _fn_in_class(plane, "NotificationPlane",
                    "on_namespace_change") is None:
        out.append(Violation(
            "hook-coverage", NOTIFY_PLANE, 1,
            "NotificationPlane.on_namespace_change() missing — the "
            "feed listener the attach wires is gone"))

    cluster = by_rel.get(REPL_CLUSTER)
    if cluster is not None and ss is not None:
        if not _calls_method(cluster.tree, "attach_notifications"):
            out.append(Violation(
                "hook-coverage", REPL_CLUSTER, 1,
                "cluster boot never calls attach_notifications() — "
                "the plane exists but no mutation verb would reach "
                "it"))
    return out


# ---------------------------------------------------------------------------
# rule: error-map
# ---------------------------------------------------------------------------

API_ERRORS = "minio_tpu/object/api_errors.py"
S3_ERRORS = "minio_tpu/s3/s3errors.py"


def _api_error_classes(src: Source) -> Dict[str, int]:
    """name -> lineno of every (transitive) ObjectApiError subclass."""
    bases: Dict[str, List[str]] = {}
    lines: Dict[str, int] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef):
            bases[node.name] = [dotted(b) for b in node.bases]
            lines[node.name] = node.lineno

    def is_api_err(name: str, seen: Set[str]) -> bool:
        if name == "ObjectApiError":
            return True
        if name in seen:
            return False
        seen.add(name)
        return any(is_api_err(b, seen) for b in bases.get(name, ()))

    return {n: lines[n] for n in bases
            if n != "ObjectApiError" and is_api_err(n, set())}


def check_error_map(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    by_rel = {s.rel: s for s in sources}
    api = by_rel.get(API_ERRORS)
    s3 = by_rel.get(S3_ERRORS)
    if api is None or s3 is None:
        return [Violation("error-map", API_ERRORS, 1,
                          "api_errors.py / s3errors.py not found")]
    classes = _api_error_classes(api)

    table_keys: Set[str] = set()
    mapped: Dict[str, str] = {}       # class name -> code
    internal: Set[str] = set()
    map_line = 1
    for node in ast.walk(s3.tree):
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt, value = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, value = node.targets[0], node.value
        else:
            continue
        tname = tgt.id if isinstance(tgt, ast.Name) else ""
        if tname == "ERROR_TABLE" and isinstance(value, ast.Dict):
            for k in value.keys:
                s = str_const(k)
                if s:
                    table_keys.add(s)
        elif tname == "INTERNAL_ONLY" and \
                isinstance(value, (ast.Tuple, ast.List)):
            for el in value.elts:
                d = dotted(el)
                if d:
                    internal.add(d.split(".")[-1])
        elif tname == "mapping" and \
                isinstance(value, (ast.List, ast.Tuple)):
            map_line = node.lineno
            for el in value.elts:
                if isinstance(el, ast.Tuple) and len(el.elts) == 2:
                    cls = dotted(el.elts[0])
                    code = str_const(el.elts[1])
                    if cls.startswith("oerr.") and code:
                        mapped[cls.split(".")[-1]] = code

    # `mapping` may be a local inside api_error_from
    for name, line in sorted(classes.items()):
        if name not in mapped and name not in internal:
            out.append(Violation(
                "error-map", API_ERRORS, line,
                f"{name} has no api_error_from mapping in s3errors.py "
                "and is not declared INTERNAL_ONLY — it would surface "
                "as a 500 InternalError"))
    for cls, code in sorted(mapped.items()):
        if code not in table_keys:
            out.append(Violation(
                "error-map", S3_ERRORS, map_line,
                f"mapping for {cls} names code {code!r} which is not "
                "in ERROR_TABLE"))
    for name in sorted(internal):
        if name not in classes:
            out.append(Violation(
                "error-map", S3_ERRORS, map_line,
                f"INTERNAL_ONLY names {name!r} which is not an "
                "api_errors class"))

    # every literal S3Error("Code") raised anywhere must be in the table
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and node.args:
                d = dotted(node.func)
                if d.split(".")[-1] == "S3Error":
                    code = str_const(node.args[0])
                    if code and code not in table_keys:
                        out.append(Violation(
                            "error-map", src.rel, node.lineno,
                            f"S3Error({code!r}) — code missing from "
                            "ERROR_TABLE (clients would get a bare "
                            "500 with no usable code)"))
    return out


# ---------------------------------------------------------------------------
# rule: crashpoint
# ---------------------------------------------------------------------------

# Modules whose functions perform multi-file commits (the designated
# commit modules): any function here that writes AND renames — or
# persists more than one document — is a crash window and must declare
# a registered crashpoint (utils/crashpoint.py) inside it, or argue
# its exemption with an inline `# check: allow(crashpoint) reason`.
CRASHPOINT_MODULES = (
    "minio_tpu/object/engine.py",
    "minio_tpu/object/multipart.py",
    "minio_tpu/object/metacache.py",
    "minio_tpu/object/topology.py",
    "minio_tpu/object/rebalance.py",
    "minio_tpu/object/background.py",
    "minio_tpu/storage/xl_storage.py",
    "minio_tpu/tier/config.py",
    "minio_tpu/replicate/targets.py",
    "minio_tpu/replicate/resync.py",
    "minio_tpu/replicate/plane.py",
    "minio_tpu/notify/targets.py",
    "minio_tpu/notify/plane.py",
)

# terminal call names that MOVE a file into its committed place…
_RENAMEISH = {"rename_data", "rename_file", "replace"}
# …and that persist a document/shard
_WRITEISH = {"write_all", "write_unique_file_info", "put_object",
             "write_metadata", "create_file"}


def _terminal(node: ast.Call) -> str:
    name = dotted(node.func)
    return name.rsplit(".", 1)[-1] if name else ""


def _commit_shape(fn: ast.AST) -> Optional[str]:
    """Classify a function as a multi-file commit window. Returns a
    human-readable reason, or None."""
    renames: Set[str] = set()
    writes = 0
    write_in_loop = False
    loops = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.While)):
            loops.append(node)
    loop_nodes: Set[ast.AST] = set()
    for lp in loops:
        for sub in ast.walk(lp):
            loop_nodes.add(sub)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        t = _terminal(node)
        if t in _RENAMEISH:
            renames.add(t)
        elif t in _WRITEISH:
            writes += 1
            if node in loop_nodes:
                write_in_loop = True
    if renames and writes:
        return (f"write ({writes} call(s)) + rename "
                f"({'/'.join(sorted(renames))})")
    if writes >= 2:
        return f"{writes} persistence calls"
    if write_in_loop:
        return "persistence call inside a loop"
    return None


def check_crashpoint(sources: List[Source],
                     registered: Set[str]) -> List[Violation]:
    """(1) every `crashpoint.hit(<name>)` anywhere names a registered
    point with a constant string; (2) in the designated commit
    modules, every function with a multi-file-commit shape contains a
    hit (or an allow comment)."""
    out: List[Violation] = []
    for src in sources:
        # (1) hit-site hygiene, tree-wide
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func).rsplit(".", 1)[-1] != "hit":
                continue
            if not dotted(node.func).endswith("crashpoint.hit"):
                continue
            name = str_const(node.args[0]) if node.args else None
            if name is None:
                out.append(Violation(
                    "crashpoint", src.rel, node.lineno,
                    "crashpoint.hit() needs a constant name — the "
                    "registry/table/harness all key on literals"))
            elif name not in registered:
                out.append(Violation(
                    "crashpoint", src.rel, node.lineno,
                    f"crashpoint.hit({name!r}) names an unregistered "
                    "point — declare it in "
                    "minio_tpu/utils/crashpoint.py"))
        if src.rel not in CRASHPOINT_MODULES:
            continue
        # (2) commit windows must declare a point
        from .core import enclosing_functions
        enclosing = enclosing_functions(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if enclosing.get(node) is not None:
                continue        # nested defs audit with their parent
            shape = _commit_shape(node)
            if shape is None:
                continue
            has_hit = any(
                isinstance(c, ast.Call)
                and dotted(c.func).endswith("crashpoint.hit")
                for c in ast.walk(node))
            if not has_hit:
                out.append(Violation(
                    "crashpoint", src.rel, node.lineno,
                    f"{node.name}() is a multi-file commit ({shape}) "
                    "with no crashpoint.hit() — thread a registered "
                    "point through the window or argue the exemption "
                    "inline"))
    return out


# ---------------------------------------------------------------------------
# rule: fencing
# ---------------------------------------------------------------------------

# The epoch-versioned registries: one doc written to every pool,
# recovered highest-wins. Without lineage fencing that recovery is a
# coin flip under a partition (two sides committing "the same" epoch).
# Every save/load/merge site in these modules must go through
# utils/regfence (advance the hash chain on bump, quorum-gate the
# write, pick_best on load) — or argue the exemption inline via
# `# check: allow(fencing) <reason>`.
REGFENCE_MODULES = (
    "minio_tpu/object/topology.py",
    "minio_tpu/tier/config.py",
    "minio_tpu/replicate/targets.py",
    "minio_tpu/s3/qos.py",
    "minio_tpu/notify/targets.py",
)

_REGFENCE_GATE_FNS = ("save", "load")


def _calls_regfence(fn: ast.AST) -> bool:
    for c in ast.walk(fn):
        if not isinstance(c, ast.Call):
            continue
        d = dotted(c.func)
        if "regfence." in d or d.rsplit(".", 1)[-1] == \
                "_advance_lineage":
            return True
    return False


def check_fencing(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    targeted = set(REGFENCE_MODULES)
    for src in sources:
        if src.rel not in targeted:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            # (1) registry persistence/recovery goes through regfence
            if node.name in _REGFENCE_GATE_FNS:
                if not _calls_regfence(node):
                    out.append(Violation(
                        "fencing", src.rel, node.lineno,
                        f"{node.name}() persists/recovers an epoch "
                        "registry without utils/regfence — quorum-gate "
                        "the write (write_quorum) / rank the copies "
                        "(pick_best), or argue the exemption inline"))
                continue
            # (2) every epoch bump advances the lineage hash chain
            bumps = any(
                isinstance(c, ast.AugAssign)
                and isinstance(c.op, ast.Add)
                and dotted(c.target).endswith(".epoch")
                for c in ast.walk(node))
            if bumps and not _calls_regfence(node):
                out.append(Violation(
                    "fencing", src.rel, node.lineno,
                    f"{node.name}() bumps a registry epoch without "
                    "advancing the lineage chain — equal epochs from "
                    "divergent histories become an undetectable "
                    "split-brain; call _advance_lineage() under the "
                    "same lock or argue the exemption inline"))
    return out


# ---------------------------------------------------------------------------
# rule: crypto-hygiene
# ---------------------------------------------------------------------------

# SSE package nonces and AEAD primitives have ONE owner. features/crypto.py
# derives every per-package nonce (_pkg_nonce: base words XOR seq) and is
# the only module that drives the scalar AEAD reference; a second
# derivation site is how nonce-reuse bugs are born (two modules disagree
# on the seq mixing and a keystream repeats under one key). Everything
# else consumes the high-level transforms crypto.py exports (Encryptor,
# ChaChaEncryptor, DeviceSSE, chacha_decrypt_ranged, seal/unseal).
CRYPTO_OWNER = "minio_tpu/features/crypto.py"

# AEAD / nonce-construction primitives nobody else may touch
CRYPTO_PRIMS = frozenset({
    "_pkg_nonce", "_pkg_aad", "tag_detached", "seal_detached",
    "open_detached", "poly1305_mac", "poly1305_key_gen", "xor_stream",
    "chacha20_block",
})

# primitive modules and who may import them: the scalar reference is
# crypto.py-only; the device kernels additionally feed the fused
# put/get programs in models/pipeline.py (keystream generation over
# nonce ARRAYS crypto.py already derived — no derivation happens there)
CHACHA_IMPORTERS = {
    "chacha20_ref": (CRYPTO_OWNER,),
    "chacha20_jax": (CRYPTO_OWNER, "minio_tpu/models/pipeline.py"),
}

# the primitive modules themselves (definitions, not use)
_CRYPTO_PRIM_FILES = ("minio_tpu/ops/chacha20_ref.py",
                      "minio_tpu/ops/chacha20_jax.py")


def check_crypto_hygiene(sources: List[Source]) -> List[Violation]:
    out: List[Violation] = []
    exempt = {CRYPTO_OWNER, *_CRYPTO_PRIM_FILES}
    for src in sources:
        if src.rel in exempt:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                for prim_mod, allowed in CHACHA_IMPORTERS.items():
                    if (mod.endswith(prim_mod) or prim_mod in names) \
                            and src.rel not in allowed:
                        out.append(Violation(
                            "crypto-hygiene", src.rel, node.lineno,
                            f"import of {prim_mod} outside its owner"
                            f" ({', '.join(allowed)}) — consume the "
                            "high-level transforms features/crypto.py "
                            "exports instead of the raw primitives"))
                hit = names & CRYPTO_PRIMS
                if hit:
                    out.append(Violation(
                        "crypto-hygiene", src.rel, node.lineno,
                        f"direct import of AEAD/nonce primitive "
                        f"{sorted(hit)[0]}() — package nonces are "
                        "derived ONLY inside features/crypto.py; a "
                        "second derivation site risks nonce reuse"))
            elif isinstance(node, ast.Call):
                name = dotted(node.func)
                leaf = name.rsplit(".", 1)[-1]
                if leaf in CRYPTO_PRIMS:
                    out.append(Violation(
                        "crypto-hygiene", src.rel, node.lineno,
                        f"call to {leaf}() outside features/crypto.py "
                        "— SSE nonce construction and AEAD primitives "
                        "have one owner; use the crypto-module "
                        "transforms (or argue the exemption inline)"))
    return out


# ---------------------------------------------------------------------------
# rule: eventlog
# ---------------------------------------------------------------------------

# attr keys that name per-request / per-object identities (the same
# vocabulary the label-cardinality sub-rule bans on metric labels): a
# bounded journal must never carry unbounded attr KEYS
EVENT_UNBOUNDED_ATTRS = {
    "bucket", "object", "key", "obj", "etag", "version_id",
    "upload_id", "prefix", "trace_id", "request_id", "caller",
}


def check_eventlog(sources: List[Source],
                   registered: Dict[str, tuple]) -> List[Violation]:
    """Every journal emit — `eventlog.emit(...)` or `JOURNAL.emit(...)`
    — names a registered event class with a constant string, passes
    only that class's declared attr keys, and never spreads **kwargs
    (the registry/table/lint all key on what is visible statically).
    `registered` maps class name -> declared attr tuple (from
    eventtable.load_events)."""
    out: List[Violation] = []
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not (d.endswith("eventlog.emit")
                    or d.endswith("eventlog.emit_once")
                    or d.endswith("JOURNAL.emit")):
                continue
            name = str_const(node.args[0]) if node.args else None
            if name is None:
                out.append(Violation(
                    "eventlog", src.rel, node.lineno,
                    "eventlog.emit() needs a constant event-class "
                    "name — the registry/table/tests all key on "
                    "literals"))
                continue
            if name not in registered:
                out.append(Violation(
                    "eventlog", src.rel, node.lineno,
                    f"eventlog.emit({name!r}) names an unregistered "
                    "event class — declare it in "
                    "minio_tpu/utils/eventlog.py"))
                continue
            declared = set(registered[name])
            for kw in node.keywords:
                if kw.arg is None:
                    out.append(Violation(
                        "eventlog", src.rel, node.lineno,
                        f"eventlog.emit({name!r}, **kwargs) — attr "
                        "keys must be visible statically; pass them "
                        "as explicit keywords"))
                    continue
                if kw.arg in EVENT_UNBOUNDED_ATTRS:
                    out.append(Violation(
                        "eventlog", src.rel, node.lineno,
                        f"eventlog.emit({name!r}) attr {kw.arg!r} is "
                        "in the unbounded label vocabulary — journal "
                        "attrs must stay bounded"))
                elif kw.arg not in declared:
                    out.append(Violation(
                        "eventlog", src.rel, node.lineno,
                        f"eventlog.emit({name!r}) passes undeclared "
                        f"attr {kw.arg!r} — declare it on the event "
                        "class in minio_tpu/utils/eventlog.py"))
    return out
