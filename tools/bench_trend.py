#!/usr/bin/env python3
"""bench_trend — A/B diff of two BENCH_r*.json snapshots.

Each bench round persists one ``BENCH_r<NN>.json`` (``{n, cmd, rc,
tail, parsed}``); this tool flattens both snapshots' ``parsed`` trees
to dotted numeric keys and prints a trajectory table, so a perf
regression between rounds is one command to see and one exit code to
gate on:

    python tools/bench_trend.py BENCH_r04.json BENCH_r05.json
    python tools/bench_trend.py --threshold 10 old.json new.json
    python tools/bench_trend.py --smoke        # self-test, no files

Direction is inferred per key: ``*_ms`` / ``*_s`` / ``*_overhead_x``
/ ``*_iqr*`` are lower-better (latency, overhead, jitter); everything
else numeric (``gibs``, ``value``, ``vs_baseline``, counts) is
higher-better. Exit 1 when any key regresses past ``--threshold``
percent (default 5); keys present on only one side are listed but
never gate — a new bench section must not fail the trend check that
predates it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterator, Tuple

LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_overhead_x", "_us")
LOWER_BETTER_TOKENS = ("iqr", "latency", "p50", "p99", "overhead")


def flatten(doc: object, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a parsed tree as dotted keys; lists index
    numerically. Booleans and strings are skipped — the trend is about
    magnitudes, not flags."""
    out: Dict[str, float] = {}

    def walk(node: object, key: str) -> None:
        if isinstance(node, bool):
            return
        if isinstance(node, (int, float)):
            out[key] = float(node)
            return
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{key}.{k}" if key else str(k))
            return
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{key}.{i}" if key else str(i))

    walk(doc, prefix)
    return out


def lower_is_better(key: str) -> bool:
    leaf = key.rsplit(".", 1)[-1]
    if leaf.endswith(LOWER_BETTER_SUFFIXES):
        return True
    return any(t in leaf for t in LOWER_BETTER_TOKENS)


def compare(old: Dict[str, float], new: Dict[str, float]
            ) -> Iterator[Tuple[str, float, float, float, bool]]:
    """(key, old, new, signed % change where positive = improvement,
    regressed?) for every shared key — plus one-sided keys with change
    NaN, never regressed."""
    for key in sorted(set(old) | set(new)):
        if key not in old or key not in new:
            yield key, old.get(key, float("nan")), \
                new.get(key, float("nan")), float("nan"), False
            continue
        a, b = old[key], new[key]
        if a == 0:
            yield key, a, b, float("nan"), False
            continue
        raw = (b - a) / abs(a) * 100.0
        gain = -raw if lower_is_better(key) else raw
        yield key, a, b, gain, gain < 0


def run_diff(old_path: str, new_path: str, threshold: float,
             out=sys.stdout) -> int:
    with open(old_path) as f:
        old_doc = json.load(f)
    with open(new_path) as f:
        new_doc = json.load(f)
    old = flatten(old_doc.get("parsed") or {})
    new = flatten(new_doc.get("parsed") or {})
    rows = list(compare(old, new))
    name_w = max([len(k) for k, *_ in rows] + [6])
    print(f"{'key'.ljust(name_w)}  {'old':>12}  {'new':>12}  "
          f"{'change':>9}", file=out)
    failures = []
    for key, a, b, gain, regressed in rows:
        if gain != gain:                               # NaN: one-sided
            mark = "  (one-sided)" if (a != a or b != b) else ""
            ch = "-"
        else:
            ch = f"{gain:+.1f}%"
            mark = ""
            if regressed and -gain > threshold:
                failures.append((key, gain))
                mark = "  << REGRESSED"
        fa = "-" if a != a else f"{a:.4g}"
        fb = "-" if b != b else f"{b:.4g}"
        print(f"{key.ljust(name_w)}  {fa:>12}  {fb:>12}  {ch:>9}"
              f"{mark}", file=out)
    if failures:
        print(f"\n{len(failures)} key(s) regressed past "
              f"{threshold:.1f}%:", file=out)
        for key, gain in failures:
            print(f"  {key}: {gain:+.1f}%", file=out)
        return 1
    print(f"\nno regression past {threshold:.1f}% "
          f"({len(rows)} keys compared)", file=out)
    return 0


def smoke() -> int:
    """Self-test on synthetic snapshots (pinned by the fast test
    suite): an improvement, a regression past threshold, a
    lower-better key, and a one-sided key."""
    old = {"parsed": {"value": 10.0, "put_p99_ms": 8.0,
                      "overhead_x": 1.01, "old_only": 3}}
    new = {"parsed": {"value": 12.0, "put_p99_ms": 16.0,
                      "overhead_x": 1.0, "new_only": 4}}
    o = flatten(old["parsed"])
    n = flatten(new["parsed"])
    rows = {k: (a, b, g, r) for k, a, b, g, r in compare(o, n)}
    assert rows["value"][2] > 0 and not rows["value"][3], rows["value"]
    assert rows["put_p99_ms"][2] == -100.0 and rows["put_p99_ms"][3]
    assert rows["overhead_x"][2] > 0 and not rows["overhead_x"][3]
    assert rows["old_only"][3] is False
    assert lower_is_better("kernels_ms.put.median_ms")
    assert lower_is_better("bench.put_p99_overhead_x")
    assert not lower_is_better("device_info.put_gibs_min_window")
    print("bench_trend smoke: ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_trend")
    ap.add_argument("snapshots", nargs="*",
                    help="OLD.json NEW.json (two BENCH_r*.json files)")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="regression percent that fails the gate "
                    "(default 5)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the built-in self-test and exit")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    if len(args.snapshots) != 2:
        ap.error("need exactly two snapshot paths (or --smoke)")
    return run_diff(args.snapshots[0], args.snapshots[1],
                    args.threshold)


if __name__ == "__main__":
    sys.exit(main())
