"""Typed KV configuration system (reference cmd/config/ + config-*.go)."""

from .kv import ConfigSys, SUBSYSTEMS  # noqa: F401
