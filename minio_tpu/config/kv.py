"""Typed KV config: subsystems, env overrides, encrypted persistence,
history + rollback.

The reference's cmd/config system (cmd/config/config.go:101-127 subsystem
enumeration; cmd/config-encrypted.go stores the blob encrypted with the
root credentials; cmd/admin-handlers-config-kv.go history/rollback;
lookupConfigs applies values at startup). Same architecture here:

  * a registry of subsystems with typed default keys,
  * `MINIO_<SUBSYS>_<KEY>` environment variables override stored values,
  * the blob persists AES-GCM-encrypted under the root secret at
    .minio.sys/config/config.json through the ObjectLayer,
  * every set() snapshots the previous blob into config/history/,
  * apply() pushes live values into the running server (compression,
    region, audit webhook, event webhook targets, API limits).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import threading
import time
from typing import Optional

CONFIG_OBJECT = "config/config.json"
HISTORY_PREFIX = "config/history"
MINIO_META_BUCKET = ".minio.sys"

# subsystem -> {key: default} (reference cmd/config/config.go:101-127)
SUBSYSTEMS: dict[str, dict[str, str]] = {
    "api": {"requests_max": "0", "cors_allow_origin": "*"},
    "region": {"name": "us-east-1"},
    "compression": {"enable": "off",
                    "algorithm": "s2",
                    "extensions": ".txt,.log,.csv,.json,.tar,.xml,.bin",
                    "mime_types": "text/*,application/json"},
    "storage_class": {"standard": "", "rrs": ""},
    "heal": {"interval": "10s", "max_io": "4"},
    "scanner": {"interval": "60s"},
    "etcd": {"endpoints": "", "domain": ""},
    "identity_openid": {"config_url": "", "client_id": "",
                        "jwks": "", "jwks_file": "",
                        "claim_name": "policy", "claim_prefix": ""},
    "identity_ldap": {"server_addr": "", "user_dn_format": ""},
    "kms_secret_key": {"key": ""},
    "kms_kes": {"enable": "off", "endpoint": "", "key_name": "",
                "api_key": ""},
    "logger_webhook": {"enable": "off", "endpoint": ""},
    "audit_webhook": {"enable": "off", "endpoint": ""},
    "notify_webhook": {"enable": "off", "endpoint": "",
                       "queue_limit": "10000"},
    "notify_redis": {"enable": "off", "address": "", "key": "minioevents",
                     "format": "namespace", "password": ""},
    "notify_kafka": {"enable": "off", "brokers": "", "topic": ""},
    "notify_mqtt": {"enable": "off", "broker": "", "topic": ""},
    "notify_nats": {"enable": "off", "address": "",
                    "subject": "minioevents"},
    "notify_nsq": {"enable": "off", "address": "",
                   "topic": "minioevents"},
    "notify_amqp": {"enable": "off", "address": "", "exchange": "",
                    "routing_key": "minioevents", "user": "guest",
                    "password": "guest", "vhost": "/"},
    "notify_elasticsearch": {"enable": "off", "url": "",
                             "index": "minioevents",
                             "format": "namespace"},
    "notify_postgres": {"enable": "off", "address": "",
                        "database": "", "table": "minioevents",
                        "user": "postgres", "password": "",
                        "format": "namespace"},
    "notify_mysql": {"enable": "off", "address": "",
                     "database": "", "table": "minioevents",
                     "user": "root", "password": "",
                     "format": "namespace"},
}


class ConfigError(Exception):
    pass


def _derive_key(secret: str) -> bytes:
    return hashlib.sha256(b"minio-tpu-config:" + secret.encode()).digest()


def _encrypt(secret: str, plain: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    nonce = secrets.token_bytes(12)
    return nonce + AESGCM(_derive_key(secret)).encrypt(nonce, plain, b"")


def _decrypt(secret: str, blob: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    return AESGCM(_derive_key(secret)).decrypt(blob[:12], blob[12:], b"")


# keys whose values must parse as non-negative integers
_INT_KEYS = {("api", "requests_max"), ("heal", "max_io"),
             ("notify_webhook", "queue_limit")}
# keys restricted to on/off
_BOOL_KEYS = {("compression", "enable"), ("logger_webhook", "enable"),
              ("audit_webhook", "enable"), ("notify_webhook", "enable")}

HISTORY_KEEP = 50


def _validate(subsys: str, key: str, value: str) -> None:
    if (subsys, key) in _INT_KEYS:
        try:
            if int(value) < 0:
                raise ValueError
        except ValueError:
            raise ConfigError(
                f"{subsys}/{key} must be a non-negative integer, "
                f"got {value!r}") from None
    if (subsys, key) in _BOOL_KEYS and value.lower() not in (
            "on", "off", "true", "false", "1", "0", ""):
        raise ConfigError(f"{subsys}/{key} must be on or off")


class ConfigSys:
    def __init__(self, object_layer=None, secret: str = ""):
        self.obj = object_layer
        self.secret = secret
        self._mu = threading.RLock()
        self._kv: dict[str, dict[str, str]] = {
            s: dict(defaults) for s, defaults in SUBSYSTEMS.items()}
        # env overlay, consulted by get() with highest precedence but
        # NEVER persisted (set_kv writes only the stored layer)
        self._env: dict[tuple[str, str], str] = {}
        if self.obj is not None:
            self.load()
        else:
            self._apply_env()

    # -- persistence -------------------------------------------------------

    def load(self) -> None:
        from ..object import api_errors
        try:
            _, stream = self.obj.get_object(MINIO_META_BUCKET,
                                            CONFIG_OBJECT)
            blob = b"".join(stream)
        except api_errors.ObjectApiError:
            return
        try:
            plain = _decrypt(self.secret, blob) if self.secret else blob
            stored = json.loads(plain.decode())
        except Exception as e:  # noqa: BLE001
            # an unreadable stored config is a hard error: silently
            # falling back to defaults would drop security-relevant
            # settings (the reference also refuses to start)
            raise ConfigError(f"config undecryptable: {e}") from e
        with self._mu:
            for subsys, kv in stored.items():
                if subsys in self._kv and isinstance(kv, dict):
                    self._kv[subsys].update(
                        {k: str(v) for k, v in kv.items()})
        self._apply_env()

    def _persist(self) -> None:
        if self.obj is None:
            return
        from ..object import api_errors
        # the whole read-snapshot-write cycle runs under the lock so two
        # concurrent set_kv calls cannot store a stale blob
        with self._mu:
            plain = json.dumps(self._kv, sort_keys=True).encode()
            try:
                _, stream = self.obj.get_object(MINIO_META_BUCKET,
                                                CONFIG_OBJECT)
                prev = b"".join(stream)
                # microsecond-resolution name keeps history lexically
                # ordered even for rapid successive writes
                now = time.time()
                ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
                ts += f"{int(now * 1e6) % 1_000_000:06d}Z"
                self.obj.put_object(
                    MINIO_META_BUCKET,
                    f"{HISTORY_PREFIX}/{ts}-{secrets.token_hex(4)}.json",
                    prev)
            except api_errors.ObjectApiError:
                pass
            blob = _encrypt(self.secret, plain) if self.secret else plain
            self.obj.put_object(MINIO_META_BUCKET, CONFIG_OBJECT, blob)
            self._prune_history()

    def _prune_history(self) -> None:
        """Cap history at HISTORY_KEEP newest snapshots."""
        from ..object import api_errors
        try:
            entries = self.history()
        except api_errors.ObjectApiError:
            return
        for entry in entries[:-HISTORY_KEEP]:
            try:
                self.obj.delete_object(MINIO_META_BUCKET,
                                       f"{HISTORY_PREFIX}/{entry}")
            except api_errors.ObjectApiError:
                pass

    def _apply_env(self) -> None:
        """MINIO_<SUBSYS>_<KEY> env overrides: an overlay with highest
        read precedence, never merged into the persisted layer."""
        with self._mu:
            self._env = {}
            for subsys, kv in self._kv.items():
                for key in kv:
                    env = f"MINIO_{subsys.upper()}_{key.upper()}"
                    if env in os.environ:
                        self._env[(subsys, key)] = os.environ[env]

    # -- KV surface --------------------------------------------------------

    def get(self, subsys: str, key: str) -> str:
        with self._mu:
            if (subsys, key) in self._env:
                return self._env[(subsys, key)]
            try:
                return self._kv[subsys][key]
            except KeyError:
                raise ConfigError(
                    f"unknown config key {subsys}/{key}") from None

    def get_subsys(self, subsys: str) -> dict[str, str]:
        with self._mu:
            if subsys not in self._kv:
                raise ConfigError(f"unknown subsystem {subsys}")
            out = dict(self._kv[subsys])
            for (s2, k), v in self._env.items():
                if s2 == subsys:
                    out[k] = v
            return out

    def dump(self) -> dict:
        with self._mu:
            out = {s: dict(kv) for s, kv in self._kv.items()}
            for (s2, k), v in self._env.items():
                out[s2][k] = v
            return out

    def set_kv(self, subsys: str, **kv: str) -> None:
        with self._mu:
            if subsys not in self._kv:
                raise ConfigError(f"unknown subsystem {subsys}")
            for k, v in kv.items():
                if k not in SUBSYSTEMS[subsys]:
                    raise ConfigError(f"unknown key {subsys}/{k}")
                _validate(subsys, k, str(v))
            self._kv[subsys].update({k: str(v) for k, v in kv.items()})
        self._persist()

    # -- history / rollback ------------------------------------------------

    def history(self) -> list[str]:
        from ..object import api_errors
        if self.obj is None:
            return []
        try:
            objs, _, _ = self.obj.list_objects(
                MINIO_META_BUCKET, prefix=HISTORY_PREFIX + "/",
                max_keys=1000)
        except api_errors.ObjectApiError:
            return []
        return [o.name[len(HISTORY_PREFIX) + 1:] for o in objs]

    def restore(self, entry: str) -> None:
        from ..object import api_errors
        try:
            _, stream = self.obj.get_object(
                MINIO_META_BUCKET, f"{HISTORY_PREFIX}/{entry}")
            blob = b"".join(stream)
        except api_errors.ObjectApiError:
            raise ConfigError(f"no history entry {entry}") from None
        plain = _decrypt(self.secret, blob) if self.secret else blob
        stored = json.loads(plain.decode())
        with self._mu:
            for subsys, kv in stored.items():
                if subsys in self._kv and isinstance(kv, dict):
                    self._kv[subsys] = dict(SUBSYSTEMS[subsys])
                    self._kv[subsys].update(
                        {k: str(v) for k, v in kv.items()})
        self._persist()

    # -- live application (lookupConfigs, cmd/config-current.go:323) -------

    CONFIG_WEBHOOK_ARN = "arn:minio:sqs::_:webhook"
    CONFIG_REDIS_ARN = "arn:minio:sqs::_:redis"
    CONFIG_KAFKA_ARN = "arn:minio:sqs::_:kafka"
    CONFIG_MQTT_ARN = "arn:minio:sqs::_:mqtt"
    CONFIG_NATS_ARN = "arn:minio:sqs::_:nats"
    CONFIG_NSQ_ARN = "arn:minio:sqs::_:nsq"
    CONFIG_AMQP_ARN = "arn:minio:sqs::_:amqp"
    CONFIG_POSTGRES_ARN = "arn:minio:sqs::_:postgresql"
    CONFIG_MYSQL_ARN = "arn:minio:sqs::_:mysql"
    CONFIG_ELASTIC_ARN = "arn:minio:sqs::_:elasticsearch"

    def apply(self, api, events=None, trace=None) -> None:
        """Push config into a running S3ApiHandlers + subsystems.
        Off-transitions are applied too: disabling a webhook or resetting
        requests_max actually stops the live behavior."""
        api.region = self.get("region", "name")
        api.cors_allow_origin = self.get("api", "cors_allow_origin")
        api.compression_enabled = \
            self.get("compression", "enable").lower() in ("on", "true", "1")
        # "s2" = snappy framing, readable by the reference binary;
        # "zstd" = better ratio, this framework only
        api.compression_algorithm = \
            self.get("compression", "algorithm").lower() or "s2"
        try:
            reqs = int(self.get("api", "requests_max") or 0)
        except ValueError:
            reqs = 0
        api.set_max_clients(reqs if reqs > 0 else 256)
        # KMS precedence: a configured KES endpoint (the production
        # SSE-S3 shape, cmd/crypto/kes.go) wins over a static key
        if self.get("kms_kes", "enable").lower() in ("on", "true", "1"):
            from ..features.kms import KESClient
            try:
                api.kms = KESClient(
                    self.get("kms_kes", "endpoint"),
                    self.get("kms_kes", "key_name"),
                    api_key=self.get("kms_kes", "api_key"))
            except ValueError:
                pass                     # bad endpoint: keep prior KMS
        else:
            kms = self.get("kms_secret_key", "key")
            if kms:
                from ..features.kms import StaticKMS
                try:
                    key = bytes.fromhex(kms)
                    if len(key) == 32:
                        api.kms = StaticKMS(key)
                except ValueError:
                    pass
        if trace is not None:
            if self.get("audit_webhook", "enable").lower() in ("on",
                                                               "true", "1"):
                trace.audit_webhook = self.get("audit_webhook", "endpoint")
            else:
                trace.audit_webhook = ""
        if events is not None:
            def _on(subsys: str) -> bool:
                return self.get(subsys, "enable").lower() in ("on",
                                                              "true", "1")

            def _register(target_factory) -> None:
                # a malformed notify config (e.g. bad NATS subject)
                # must not crash boot/apply: log and leave the target
                # unregistered
                try:
                    events.register_target(target_factory())
                except Exception as e:  # noqa: BLE001
                    from ..utils.console import get_console
                    get_console().log_line(
                        "ERROR", f"notify target rejected: {e}")
            from ..features.events import (KafkaTarget, MQTTTarget,
                                           RedisTarget, WebhookTarget)
            if _on("notify_webhook"):
                _register(lambda: WebhookTarget(
                    self.CONFIG_WEBHOOK_ARN,
                    self.get("notify_webhook", "endpoint")))
            else:
                events.unregister_target(self.CONFIG_WEBHOOK_ARN)
            if _on("notify_redis"):
                _register(lambda: RedisTarget(
                    self.CONFIG_REDIS_ARN,
                    self.get("notify_redis", "address"),
                    self.get("notify_redis", "key"),
                    format=self.get("notify_redis", "format"),
                    password=self.get("notify_redis", "password")))
            else:
                events.unregister_target(self.CONFIG_REDIS_ARN)
            if _on("notify_kafka"):
                _register(lambda: KafkaTarget(
                    self.CONFIG_KAFKA_ARN,
                    [b.strip() for b in
                     self.get("notify_kafka", "brokers").split(",")
                     if b.strip()],
                    self.get("notify_kafka", "topic")))
            else:
                events.unregister_target(self.CONFIG_KAFKA_ARN)
            if _on("notify_mqtt"):
                _register(lambda: MQTTTarget(
                    self.CONFIG_MQTT_ARN,
                    self.get("notify_mqtt", "broker"),
                    self.get("notify_mqtt", "topic")))
            else:
                events.unregister_target(self.CONFIG_MQTT_ARN)
            from ..features.events import (ElasticsearchTarget,
                                           NATSTarget)
            if _on("notify_nats"):
                _register(lambda: NATSTarget(
                    self.CONFIG_NATS_ARN,
                    self.get("notify_nats", "address"),
                    self.get("notify_nats", "subject")))
            else:
                events.unregister_target(self.CONFIG_NATS_ARN)
            from ..features.events import AMQPTarget, NSQTarget
            if _on("notify_amqp"):
                _register(lambda: AMQPTarget(
                    self.CONFIG_AMQP_ARN,
                    self.get("notify_amqp", "address"),
                    exchange=self.get("notify_amqp", "exchange"),
                    routing_key=self.get("notify_amqp", "routing_key"),
                    user=self.get("notify_amqp", "user"),
                    password=self.get("notify_amqp", "password"),
                    vhost=self.get("notify_amqp", "vhost")))
            else:
                events.unregister_target(self.CONFIG_AMQP_ARN)
            if _on("notify_nsq"):
                _register(lambda: NSQTarget(
                    self.CONFIG_NSQ_ARN,
                    self.get("notify_nsq", "address"),
                    self.get("notify_nsq", "topic")))
            else:
                events.unregister_target(self.CONFIG_NSQ_ARN)
            from ..features.events import MySQLTarget, PostgresTarget
            if _on("notify_mysql"):
                _register(lambda: MySQLTarget(
                    self.CONFIG_MYSQL_ARN,
                    self.get("notify_mysql", "address"),
                    self.get("notify_mysql", "database"),
                    self.get("notify_mysql", "table"),
                    user=self.get("notify_mysql", "user"),
                    password=self.get("notify_mysql", "password"),
                    format=self.get("notify_mysql", "format")))
            else:
                events.unregister_target(self.CONFIG_MYSQL_ARN)
            if _on("notify_postgres"):
                _register(lambda: PostgresTarget(
                    self.CONFIG_POSTGRES_ARN,
                    self.get("notify_postgres", "address"),
                    self.get("notify_postgres", "database"),
                    self.get("notify_postgres", "table"),
                    user=self.get("notify_postgres", "user"),
                    password=self.get("notify_postgres", "password"),
                    format=self.get("notify_postgres", "format")))
            else:
                events.unregister_target(self.CONFIG_POSTGRES_ARN)
            if _on("notify_elasticsearch"):
                _register(lambda: ElasticsearchTarget(
                    self.CONFIG_ELASTIC_ARN,
                    self.get("notify_elasticsearch", "url"),
                    self.get("notify_elasticsearch", "index"),
                    format=self.get("notify_elasticsearch", "format")))
            else:
                events.unregister_target(self.CONFIG_ELASTIC_ARN)
