"""Page former: tokenized records -> fixed-shape typed column pages.

The host-side half of the scan plane: the decoded object's records
(the SAME row dicts the CPU evaluator iterates — produced by
``s3select.select._rows_csv`` / ``_rows_json``) are tokenized into
padded, fixed-shape buffers the kernels consume:

  per referenced column slot, per row:
    num    f8   the cell's float value (CPU ``_num`` semantics)
    ok     bool the cell parses as a number
    null   bool the cell is missing / JSON null
    sbytes u8[W] the cell's ``str(value)`` form, UTF-8, zero-padded
    slen   i4   real byte length of sbytes

Pages are (page_rows, ...) blocks padded to a fixed row count and a
fixed string width (rounded up through _WIDTHS) so concurrent requests
with the same plan signature and page shape land in the same scheduler
bucket and coalesce into one device launch.

Data the kernels cannot type exactly — nested JSON values, booleans,
strings wider than the cap or containing NUL (zero is the pad byte and
the lexicographic sentinel) — raises :class:`~.plan.Decline`; the
request falls back to the CPU evaluator mid-flight with identical
output, because nothing has been emitted yet.
"""

from __future__ import annotations


import numpy as np

from ..utils import knobs
from .plan import Decline, ScanPlan

#: rows per page (fixed shape -> stable jit cache, coalesçable pages)
PAGE_ROWS = max(64, knobs.get_int("MINIO_TPU_SCAN_PAGE_ROWS"))
#: string width buckets; cells wider than the last decline
_WIDTHS = (8, 16, 32, 64,
           max(64, knobs.get_int("MINIO_TPU_SCAN_MAX_STR")))


def resolve_cell(row: dict, name: str):
    """Mirror of ``sql.evaluate``'s Col lookup: exact key, then
    case-insensitive, then positional ``_N``; missing -> None."""
    if name in row:
        return row[name]
    low = name.lower()
    for k, v in row.items():
        if k.lower() == low:
            return v
    if low.startswith("_") and low[1:].isdigit():
        idx = int(low[1:]) - 1
        vals = list(row.values())
        return vals[idx] if 0 <= idx < len(vals) else None
    return None


def _num_of(v):
    """CPU ``sql._num`` verbatim (bool is NOT numeric there)."""
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


class Pages:
    """One request's typed pages, ready for the batch former."""

    __slots__ = ("plan", "n_rows", "n_pages", "width", "arrays")

    def __init__(self, plan: ScanPlan, n_rows: int, n_pages: int,
                 width: int, arrays: dict):
        self.plan = plan
        self.n_rows = n_rows          # real (unpadded) rows
        self.n_pages = n_pages
        self.width = width
        # arrays: num f8[B,R,C]  ok/null bool[B,R,C]  sb u8[B,R,C,W]
        #         slen i4[B,R,C]  rowvalid bool[B,R]
        self.arrays = arrays

    def shape_key(self) -> tuple:
        """Everything shape-relevant for the scheduler bucket (the
        page count B varies per request and is NOT part of the key —
        pages from different requests stack along B)."""
        return (PAGE_ROWS, max(1, len(self.plan.columns)), self.width)


def build_pages(rows: list, plan: ScanPlan) -> Pages:
    """Tokenize `rows` into fixed-shape pages for `plan`. Raises
    Decline when any referenced cell can't be typed exactly."""
    R = PAGE_ROWS
    n = len(rows)
    B = max(1, -(-n // R))
    C = max(1, len(plan.columns))

    # first pass: resolve + type every referenced cell, find the width
    cells = []                    # (null, ok, num, sbytes) per row/col
    max_w = 1
    for row in rows:
        rcells = []
        for name in plan.columns:
            v = resolve_cell(row, name)
            if v is None:
                rcells.append((True, False, 0.0, b""))
                continue
            if isinstance(v, bool) or isinstance(v, (dict, list)):
                raise Decline("nested" if isinstance(v, (dict, list))
                              else "cell-type")
            nv = _num_of(v)
            sb = str(v).encode("utf-8")
            if b"\x00" in sb:
                raise Decline("cell-nul")
            if b"\n" in sb and len(rcells) in plan.like_cols:
                # the CPU LIKE is a ^..$-anchored re.match: '.' stops
                # at a newline and '$' matches before a trailing one —
                # the kernel's byte compares reproduce neither
                raise Decline("like-newline")
            if len(sb) > max_w:
                max_w = len(sb)
            rcells.append((False, nv is not None,
                           nv if nv is not None else 0.0, sb))
        cells.append(rcells)

    width = next((w for w in _WIDTHS if w >= max_w), None)
    if width is None:
        raise Decline("wide-string")

    # arithmetic comparisons are numeric-only on device: every cell of
    # a column they touch must be numeric or null, else the CPU would
    # take the string-compare path the kernel doesn't implement
    for j in plan.arith_cols:
        for rcells in cells:
            null, ok, _nv, _sb = rcells[j]
            if not (null or ok):
                raise Decline("mixed-arith")

    num = np.zeros((B, R, C), np.float64)
    ok = np.zeros((B, R, C), bool)
    null = np.ones((B, R, C), bool)      # pad rows read as null
    sb = np.zeros((B, R, C, width), np.uint8)
    slen = np.zeros((B, R, C), np.int32)
    rowvalid = np.zeros((B, R), bool)
    for i, rcells in enumerate(cells):
        b, r = divmod(i, R)
        rowvalid[b, r] = True
        for j, (cnull, cok, cnum, csb) in enumerate(rcells):
            null[b, r, j] = cnull
            ok[b, r, j] = cok
            num[b, r, j] = cnum
            if csb:
                sb[b, r, j, :len(csb)] = np.frombuffer(csb, np.uint8)
                slen[b, r, j] = len(csb)
    return Pages(plan, n, B, width,
                 {"num": num, "ok": ok, "null": null, "sb": sb,
                  "slen": slen, "rowvalid": rowvalid})
