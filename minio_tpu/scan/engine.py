"""ScanEngine: the SelectObjectContent device/CPU routing seam.

``event_stream(req, data)`` is a drop-in replacement for
``s3select.select.event_stream``: it tries the device plan first —
compile the predicate (:mod:`.plan`), tokenize pages (:mod:`.pager`),
ride the batch former's ``scan`` verb (or run the kernels inline when
no scheduler is attached) — and on ANY decline falls back to the CPU
evaluator with byte-identical output (the erasure kernels' oracle
discipline: the fallback IS the oracle).

The device computes the row mask (and COUNT reductions); the passing
rows are then serialized by the SAME ``_emit``/framing helpers the CPU
path uses, over the SAME row dicts the CPU readers produce — so the
framed response (Records chunk boundaries, Stats, End) is identical by
construction, which the randomized property suite pins.

Metrics:
  minio_tpu_scan_requests_total{path=device|fallback}
  minio_tpu_scan_fallbacks_total{reason=...}
  minio_tpu_scan_pages_total / minio_tpu_scan_rows_total
  minio_tpu_scan_seconds{path=...}
"""

from __future__ import annotations

import time
from typing import Iterator, Optional

from ..s3select import select as sel
from ..s3select import sql as _sql
from ..utils import eventlog, knobs, telemetry
from . import kernels, pager
from .plan import Decline, compile_plan

#: device-path input cap: the kernels materialize the decompressed
#: object as row dicts + padded column pages (~10-40x the raw bytes),
#: so very large objects stream through the CPU evaluator instead
MAX_SCAN_BYTES = knobs.get_int("MINIO_TPU_SCAN_MAX_BYTES")


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_scan_requests_total",
                    "SelectObjectContent requests by serving path"),
        reg.counter("minio_tpu_scan_fallbacks_total",
                    "Device-scan declines by reason (request fell back "
                    "to the CPU evaluator, output identical)"),
        reg.counter("minio_tpu_scan_pages_total",
                    "Tokenized pages submitted to the scan verb"),
        reg.counter("minio_tpu_scan_rows_total",
                    "Records scanned through the device path"),
        reg.histogram("minio_tpu_scan_seconds",
                      "SelectObjectContent wall time by serving path"),
    )


class ScanEngine:
    """Routes Select requests between the device plan and the CPU
    evaluator. One per server; `scheduler` is the shared multi-verb
    batch former (None = run kernels inline, still device-batched
    within the request)."""

    def __init__(self, scheduler=None):
        self.scheduler = scheduler
        self._m = _metrics()
        # stats (tests/bench)
        self.device_serves = 0
        self.fallbacks = 0
        self.fallback_reasons: dict[str, int] = {}

    # -- public seam -------------------------------------------------------

    def event_stream(self, req, data: bytes) -> Iterator[bytes]:
        """Full SelectObjectContent response body (generator)."""
        t0 = time.monotonic()
        try:
            frames = self._try_device(req, data)
        except Decline as d:
            frames = None
            self._count_fallback(d.reason)
        except Exception:  # noqa: BLE001 — any device-prep failure
            # falls back; the CPU path reproduces real input errors
            # (bad JSON, bad SQL) with their proper S3 error codes
            frames = None
            self._count_fallback("error")
        if frames is None:
            yield from sel.event_stream(req, data)
            self._m[0].inc(path="fallback")
            self._m[4].observe(time.monotonic() - t0, path="fallback")
            return
        yield from frames
        self.device_serves += 1
        self._m[0].inc(path="device")
        self._m[4].observe(time.monotonic() - t0, path="device")

    def stats(self) -> dict:
        return {"device_serves": self.device_serves,
                "fallbacks": self.fallbacks,
                "fallback_reasons": dict(self.fallback_reasons)}

    # -- device path -------------------------------------------------------

    def _count_fallback(self, reason: str) -> None:
        self.fallbacks += 1
        self.fallback_reasons[reason] = \
            self.fallback_reasons.get(reason, 0) + 1
        self._m[1].inc(reason=reason)
        eventlog.emit_once("device.decline", stage="scan",
                           reason=reason)

    def _try_device(self, req, data: bytes):
        """Returns the device-served frame iterator, or raises Decline.
        Everything that could change the response happens BEFORE the
        first frame is yielded, so a decline is always clean."""
        if not kernels.device_allowed():
            # gate BEFORE the decompress/tokenize work: on a host with
            # no device every Select would otherwise pay the full page
            # build only to decline at submit time and re-parse on CPU
            raise Decline("no-device")
        try:
            q = _sql.parse(req.expression)
        except _sql.SQLError:
            raise Decline("sql-error") from None   # CPU raises properly
        plan = compile_plan(q, req.input_format, req.json_type)
        with telemetry.span("scan.page", fmt=req.input_format):
            payload = sel._decompress(data, req.compression)
            if len(payload) > MAX_SCAN_BYTES:
                raise Decline("too-large")
            if req.input_format == "JSON":
                rows = list(sel._rows_json(payload, req))
            else:
                rows = list(sel._rows_csv(payload, req))
            pages = pager.build_pages(rows, plan)
        mask = self._run_pages(pages)
        self._m[2].inc(pages.n_pages)
        self._m[3].inc(pages.n_rows)
        rowmask = mask.reshape(-1)[:pages.n_rows]
        return self._frames(req, q, plan, rows, rowmask, pages, data)

    def _run_pages(self, pages) -> "pager.np.ndarray":
        """One boolean mask [B, R] via the batch former (coalescing
        with concurrent requests) or inline kernels."""
        if self.scheduler is not None:
            fut = self.scheduler.submit_scan(pages)
            try:
                out = fut.result()
            except Exception:  # noqa: BLE001 — dispatch failed
                raise Decline("dispatch-error") from None
            if out is None:
                raise Decline("declined")
            return out
        if not kernels.device_allowed():
            raise Decline("no-device")
        return kernels.run_batch(pages.plan, pages.arrays)

    # -- byte-identical emission -------------------------------------------

    def _records(self, req, q, plan, rows, rowmask, pages
                 ) -> Iterator[bytes]:
        """Serialized output records — the run_select loop with the
        WHERE decision replaced by the device mask."""
        from ..s3.s3errors import S3Error
        try:
            if plan.counts is not None:
                yield sel._emit(self._count_result(q, plan, rowmask,
                                                   pages), req)
                return
            emitted = 0
            for i, passed in enumerate(rowmask):
                if not passed:
                    continue
                row = rows[i]
                if q.star:
                    out = dict(row)
                else:
                    out = {}
                    for j, (e, alias) in enumerate(q.projections):
                        name = alias or (e.name
                                         if isinstance(e, _sql.Col)
                                         else f"_{j + 1}")
                        out[name] = _sql.evaluate(e, row, q.alias)
                yield sel._emit(out, req)
                emitted += 1
                if q.limit is not None and emitted >= q.limit:
                    return
        except _sql.SQLError as e:
            raise S3Error("InvalidArgument", f"SQL: {e}") from None

    def _count_result(self, q, plan, rowmask, pages) -> dict:
        """The Aggregator.result() dict for COUNT-only aggregates,
        computed from the device mask (exact integer reductions)."""
        import numpy as np
        nulls = pages.arrays["null"].reshape(
            -1, pages.arrays["null"].shape[-1])[:pages.n_rows]
        out = {}
        for i, ((_e, alias), spec) in enumerate(
                zip(q.projections, plan.counts)):
            name = alias or f"_{i + 1}"
            if spec is None:
                out[name] = None
            elif spec == "star":
                out[name] = int(np.count_nonzero(rowmask))
            else:
                out[name] = int(np.count_nonzero(
                    rowmask & ~nulls[:, spec]))
        return out

    def _frames(self, req, q, plan, rows, rowmask, pages, data: bytes
                ) -> Iterator[bytes]:
        """The CPU path's own framing loop over the device-masked
        records — shared code, so the framed stream cannot drift."""
        yield from sel.frame_records(
            self._records(req, q, plan, rows, rowmask, pages),
            len(data))
