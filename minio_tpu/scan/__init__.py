"""Device scan plane: TPU-offloaded S3 Select.

The paper's delta — offload the data path's byte-crunching to an
accelerator and overlap it with I/O — applied to the analytics read
path: a parsed S3 Select query's predicate (and COUNT aggregates) is
compiled into vectorized JAX kernels over batched fixed-shape pages of
tokenized CSV/JSON-LINES records, dispatched through the multi-verb
batch former (``parallel/scheduler.py`` verb ``scan``) so concurrent
SelectObjectContent requests coalesce into single device launches.

The row-by-row CPU evaluator (``s3select/select.py``) stays the oracle
AND the fallback: every construct the kernel plan declines — nested
JSON, unsupported LIKE patterns, SUM/AVG/MIN/MAX aggregates, scalar
functions in predicates — falls back silently (counted in
``minio_tpu_scan_fallbacks_total{reason}``), and the framed
event-stream response is byte-identical either way: selected rows are
serialized by the SAME ``_emit``/framing code the CPU path uses; the
device only decides WHICH rows (the scan itself).
"""

from .engine import ScanEngine
from .plan import Decline, compile_plan

__all__ = ["ScanEngine", "Decline", "compile_plan"]
