"""Scan-plan compiler: SQL AST -> a device-executable predicate program.

Compiles the WHERE tree of a parsed :class:`~..s3select.sql.Query` into
a small typed program the kernel builder (:mod:`.kernels`) can trace
into one jitted JAX function, with

  * column references resolved to page SLOTS (the pager materializes
    one typed column buffer per slot),
  * literals baked into the program as constants — a numeric literal
    needs its STRING form too (the evaluator string-compares it
    against non-numeric cells), so literal values are part of the
    bucket signature: concurrent IDENTICAL queries coalesce into one
    device launch, differing literals compile separate kernels.

Anything outside the supported subset raises :class:`Decline` with a
stable reason label; the caller falls back to the CPU evaluator, which
is also the byte-identity oracle. The compiler is deliberately
conservative: a construct is supported only when the kernel can
reproduce the CPU evaluator's semantics EXACTLY (the per-row
numeric-else-string coercion of ``sql._coerce_pair`` included).

Supported predicate grammar:
    cmp        := side (=|!=|<>|<|<=|>|>=) side
    side       := column | literal | arithmetic over columns/literals
    membership := column/literal [NOT] IN (literals)
                | column/literal [NOT] BETWEEN literal AND literal
    null test  := column IS [NOT] NULL
    pattern    := column [NOT] LIKE 'lit' | 'lit%' | '%lit' | '%lit%'
    boolean    := AND / OR / NOT over the above

Aggregates: COUNT(*) and COUNT(column) map to mask reductions; every
other aggregate declines (reason ``aggregate``).
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from ..s3select import sql as _sql

#: comparison operators in CPU-evaluator semantics
_CMP_OPS = ("=", "!=", "<>", "<", "<=", ">", ">=")
_ARITH_OPS = ("+", "-", "*", "/", "%")


class Decline(Exception):
    """The plan (or a page of data) cannot ride the device path; the
    caller must fall back to the CPU evaluator. ``reason`` is a stable
    low-cardinality label for minio_tpu_scan_fallbacks_total."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason


# -- program nodes ----------------------------------------------------------
# Plain tuples keep the program trivially serializable for the bucket
# signature: ("and", a, b) / ("or", a, b) / ("not", x)
# ("cmp", op, side_a, side_b)
# ("in", side, (literal_side, ...), negate)
# ("between", side, lo_side, hi_side, negate)
# ("isnull", slot, negate)
# ("like", slot, kind, needle_bytes, negate)  kind: exact|prefix|suffix|
#                                             contains|any
# sides: ("col", slot) | ("nlit", float_value, str_form_bytes)
#        | ("slit", bytes) | ("arith", op, side, side)
# ("true",) — no WHERE clause: every (real) row passes.


class ScanPlan:
    """Compiled device plan for one query shape."""

    def __init__(self):
        self.columns: list[str] = []     # referenced column names (slots)
        self.prog: tuple = ("true",)
        # columns referenced by a comparison that has an arithmetic
        # side: every cell of these must be numeric-or-null, or the
        # page former declines (CPU would string-compare the formatted
        # arithmetic result — not worth reproducing on device)
        self.arith_cols: set[int] = set()
        # columns referenced by any LIKE: the page former declines
        # their cells containing '\n' — the CPU pattern is a
        # ^..$-anchored re.match where '.' stops at a newline and '$'
        # matches before a trailing one, neither of which the kernel's
        # byte compares reproduce
        self.like_cols: set[int] = set()
        # aggregate surface: None = row query; else a list mirroring
        # q.projections where each entry is "star" (COUNT(*)),
        # a slot index (COUNT(col)) or None (non-aggregate projection,
        # which the CPU Aggregator reports as None)
        self.counts: Optional[list] = None
        self.signature: str = ""

    def slot(self, name: str) -> int:
        try:
            return self.columns.index(name)
        except ValueError:
            self.columns.append(name)
            return len(self.columns) - 1

    def seal(self) -> "ScanPlan":
        """Freeze the bucket signature: everything trace-relevant —
        program shape, literal constants, column count, aggregate
        layout."""
        def enc(o):
            if isinstance(o, bytes):
                return ["b", o.hex()]
            if isinstance(o, tuple):
                return [enc(x) for x in o]
            return o
        blob = json.dumps({
            "prog": enc(self.prog), "ncols": len(self.columns),
            "arith": sorted(self.arith_cols),
            "counts": [c if c is None else str(c)
                       for c in (self.counts or [])] or None,
        }, separators=(",", ":"))
        self.signature = hashlib.sha1(blob.encode()).hexdigest()[:16]
        return self


# -- LIKE pattern recovery --------------------------------------------------

def _like_shape(pat) -> tuple[str, bytes]:
    """Recover (kind, needle) from the parser's compiled LIKE regex
    (sql._like_regex builds '^' + parts + '$' where '%' -> '.*',
    '_' -> '.', other chars re.escape'd). Declines '_' wildcards and
    '%' anywhere but the ends."""
    src = pat.pattern
    if not (src.startswith("^") and src.endswith("$")):
        raise Decline("like-pattern")
    body = src[1:-1]
    toks: list[str] = []         # "%" or one literal char
    i = 0
    while i < len(body):
        c = body[i]
        if body.startswith(".*", i):
            toks.append("%")
            i += 2
        elif c == ".":
            raise Decline("like-pattern")        # '_' wildcard
        elif c == "\\" and i + 1 < len(body):
            toks.append(body[i + 1])
            i += 2
        else:
            toks.append(c)
            i += 1
    lead = bool(toks) and toks[0] == "%"
    trail = len(toks) > (1 if lead else 0) and toks[-1] == "%"
    mid = toks[1 if lead else 0:len(toks) - (1 if trail else 0)]
    if "%" in mid:
        raise Decline("like-pattern")            # inner wildcard
    needle = "".join(mid).encode("utf-8")
    if b"\x00" in needle:
        raise Decline("like-pattern")
    if not needle:
        if not toks:
            # LIKE '' is regex ^$: only the empty cell matches —
            # mapping it to "any" matched every non-null row
            return "exact", b""
        return "any", b""                        # '%', '%%'
    if lead and trail:
        return "contains", needle
    if lead:
        return "suffix", needle
    if trail:
        return "prefix", needle
    return "exact", needle


# -- compilation ------------------------------------------------------------

def _compile_side(plan: ScanPlan, node, alias: str,
                  cols_touched: set[int]) -> tuple:
    """A comparison side: column, literal, or arithmetic over both."""
    if isinstance(node, _sql.Col):
        name = node.name
        if name.lower() == alias:
            raise Decline("row-ref")     # whole-row reference
        slot = plan.slot(name)
        cols_touched.add(slot)
        return ("col", slot)
    if isinstance(node, _sql.Lit):
        v = node.v
        if isinstance(v, bool) or v is None:
            # CPU compares via str(True)/None-propagation corner
            # cases; not worth reproducing for a construct this rare
            raise Decline("literal-type")
        if isinstance(v, (int, float)):
            # the string form is what the evaluator compares against
            # non-numeric cells (str(5) = "5", str(5.5) = "5.5")
            return ("nlit", float(v), str(v).encode("utf-8"))
        if isinstance(v, str):
            b = v.encode("utf-8")
            if b"\x00" in b:
                raise Decline("literal-type")
            return ("slit", b)
        raise Decline("literal-type")
    if isinstance(node, _sql.Unary) and node.op == "neg":
        v = node.x.v if isinstance(node.x, _sql.Lit) else None
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            # constant-fold a negated numeric literal: the evaluator's
            # neg returns -float(v), whose str() form is what a mixed
            # compare sees — folding keeps '-1' usable as an IN item
            # or BETWEEN bound instead of declining as arithmetic
            nv = -float(v)
            return ("nlit", nv, str(nv).encode("utf-8"))
        inner = _compile_side(plan, node.x, alias, cols_touched)
        # -x == 0 - x under the evaluator's float arithmetic
        return ("arith", "-", ("nlit", 0.0, b"0"), inner)
    if isinstance(node, _sql.Bin) and node.op in _ARITH_OPS:
        a = _compile_side(plan, node.a, alias, cols_touched)
        b = _compile_side(plan, node.b, alias, cols_touched)
        return ("arith", node.op, a, b)
    raise Decline("term")


def _has_arith(side: tuple) -> bool:
    return side[0] == "arith"


def _compile_bool(plan: ScanPlan, node, alias: str) -> tuple:
    """A boolean predicate node. Only nodes whose CPU evaluation is a
    real bool are supported (bare columns/literals would go through
    ``_truthy`` on arbitrary values — decline)."""
    if isinstance(node, _sql.Bin) and node.op in ("and", "or"):
        return (node.op, _compile_bool(plan, node.a, alias),
                _compile_bool(plan, node.b, alias))
    if isinstance(node, _sql.Unary) and node.op == "not":
        return ("not", _compile_bool(plan, node.x, alias))
    if isinstance(node, _sql.Bin) and node.op in _CMP_OPS:
        touched: set[int] = set()
        a = _compile_side(plan, node.a, alias, touched)
        b = _compile_side(plan, node.b, alias, touched)
        if _has_arith(a) or _has_arith(b):
            if a[0] == "slit" or b[0] == "slit":
                # CPU string-compares the FORMATTED arithmetic result
                # against the literal — not reproduced on device
                raise Decline("term")
            plan.arith_cols |= touched
        return ("cmp", node.op, a, b)
    if isinstance(node, _sql.In):
        touched: set[int] = set()
        x = _compile_side(plan, node.x, alias, touched)
        if _has_arith(x):
            raise Decline("term")
        items = []
        for item in node.items:
            s = _compile_side(plan, item, alias, touched)
            if s[0] not in ("nlit", "slit"):
                raise Decline("term")    # IN over columns: decline
            items.append(s)
        return ("in", x, tuple(items), bool(node.negate))
    if isinstance(node, _sql.Between):
        touched: set[int] = set()
        x = _compile_side(plan, node.x, alias, touched)
        lo = _compile_side(plan, node.lo, alias, touched)
        hi = _compile_side(plan, node.hi, alias, touched)
        if _has_arith(x) or lo[0] not in ("nlit", "slit") \
                or hi[0] not in ("nlit", "slit"):
            raise Decline("term")
        return ("between", x, lo, hi, bool(node.negate))
    if isinstance(node, _sql.IsNull):
        if not isinstance(node.x, _sql.Col) \
                or node.x.name.lower() == alias:
            raise Decline("term")
        return ("isnull", plan.slot(node.x.name), bool(node.negate))
    if isinstance(node, _sql.Like):
        if not isinstance(node.x, _sql.Col) \
                or node.x.name.lower() == alias:
            raise Decline("term")
        kind, needle = _like_shape(node.pat)
        slot = plan.slot(node.x.name)
        plan.like_cols.add(slot)
        return ("like", slot, kind, needle, bool(node.negate))
    raise Decline("predicate")


def compile_plan(q: "_sql.Query", input_format: str,
                 json_type: str = "LINES") -> ScanPlan:
    """Compile one parsed query for `input_format` ("CSV"|"JSON").
    Raises Decline for anything the kernel path cannot reproduce."""
    if input_format == "JSON":
        if json_type != "LINES":
            raise Decline("json-document")
    elif input_format != "CSV":
        raise Decline("input-format")    # Parquet etc.
    plan = ScanPlan()
    if q.is_aggregate:
        counts: list = []
        for e, _alias in q.projections:
            if not isinstance(e, _sql.Agg):
                counts.append(None)       # CPU Aggregator reports None
            elif e.name != "count":
                raise Decline("aggregate")
            elif e.arg is None:
                counts.append("star")
            elif isinstance(e.arg, _sql.Col) \
                    and e.arg.name.lower() != q.alias:
                counts.append(plan.slot(e.arg.name))
            else:
                raise Decline("aggregate")
        plan.counts = counts
    if q.where is not None:
        plan.prog = _compile_bool(plan, q.where, q.alias)
    return plan.seal()
