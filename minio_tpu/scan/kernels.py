"""Vectorized JAX predicate kernels over typed column pages.

One jitted function per (plan signature, page shape): the compiled
program tree (:mod:`.plan`) is traced into element-wise jnp ops over
the pager's fixed-shape buffers, producing a boolean row mask
``[B, R]`` — WHICH rows pass the WHERE clause. Emission of the passing
rows stays on host through the CPU evaluator's own serializer, so the
response bytes are identical by construction; the device does the
O(rows) byte-crunching (the paper's offload delta applied to the
analytics read path).

Semantics reproduce ``s3select.sql`` exactly:

  * comparisons take the evaluator's per-row coercion: numeric when
    BOTH sides parse as numbers (IEEE float64 — the kernels run under
    a local ``enable_x64`` scope so 1.1 means the same 64-bit value
    the CPU compares), False when either side is null, else
    lexicographic compare of the ``str()`` forms (UTF-8 bytes order ==
    code-point order; the zero pad byte sorts below every real byte,
    which is why the pager declines cells containing NUL);
  * arithmetic propagates "None" (non-numeric operand, division by
    zero) into a False comparison, like the evaluator;
  * LIKE supports exact / prefix / suffix / contains shapes on the
    ``str()`` form with per-row lengths.

Batches pad to the next power of two along the page axis so the jit
cache sees a handful of shapes, not one per request size.

Env:
  MINIO_TPU_SCAN_DEVICE=on|off|force   "on" (default) rides the device
      only when a TPU (or forced mesh) is present — the erasure verbs'
      discipline; "force" runs the kernels on any XLA backend (tests,
      benches); "off" disables the device path entirely.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

import numpy as np

from ..utils import knobs

_COMPILE_MU = threading.Lock()
# (signature, shape) -> jitted fn. Bounded LRU: the signature bakes in
# query literals, so per-request values (timestamps, uuids) would grow
# the trace cache without bound on a long-running server.
_KERNELS: collections.OrderedDict = collections.OrderedDict()
_KERNEL_CACHE_CAP = knobs.get_int("MINIO_TPU_SCAN_KERNEL_CACHE")


def device_allowed() -> bool:
    """Same decline discipline as the erasure verbs: no device, no
    reason to pay the dispatch seam — unless forced (tests/bench)."""
    mode = knobs.get_str("MINIO_TPU_SCAN_DEVICE").lower()
    if mode in ("off", "0", "false", "no"):
        return False
    try:
        from jax.experimental import enable_x64  # noqa: F401
    except Exception:  # noqa: BLE001 — no x64 scope, no exact floats
        return False
    if mode == "force":
        return True
    from ..object.codec import _device_is_tpu, _mesh_active
    return _device_is_tpu() or _mesh_active() is not None


def _x64():
    from jax.experimental import enable_x64
    return enable_x64()


# -- trace-time helpers -----------------------------------------------------

class _Val:
    """One comparison side evaluated over the page: float value +
    numeric/null flags, plus the str() form as (bytes[B,R,W], len) —
    None for arithmetic results (their string path is declined
    upstream)."""

    __slots__ = ("num", "ok", "null", "sb", "slen")

    def __init__(self, num, ok, null, sb=None, slen=None):
        self.num, self.ok, self.null = num, ok, null
        self.sb, self.slen = sb, slen


def _const_str(jnp, shape, needle: bytes, width: int):
    """A literal's str() form broadcast to [B,R,width]."""
    w = max(width, len(needle), 1)
    buf = np.zeros(w, np.uint8)
    if needle:
        buf[:len(needle)] = np.frombuffer(needle, np.uint8)
    sb = jnp.broadcast_to(jnp.asarray(buf), (*shape, w))
    slen = jnp.full(shape, len(needle), np.int32)
    return sb, slen


def _pad_w(jnp, sb, w):
    """Zero-pad the byte axis to width w (trace-time static)."""
    have = sb.shape[-1]
    if have >= w:
        return sb
    pad = [(0, 0)] * (sb.ndim - 1) + [(0, w - have)]
    return jnp.pad(sb, pad)


def _str_cmp(jnp, op: str, a: _Val, b: _Val):
    """Lexicographic compare of the str() forms (zero-padded byte
    arrays: pad < every real byte, so prefix-shorter sorts first,
    exactly like Python str compare on the code points)."""
    w = max(a.sb.shape[-1], b.sb.shape[-1])
    ab = _pad_w(jnp, a.sb, w)
    bb = _pad_w(jnp, b.sb, w)
    diff = ab != bb
    any_diff = jnp.any(diff, axis=-1)
    first = jnp.argmax(diff, axis=-1)
    av = jnp.take_along_axis(ab, first[..., None], axis=-1)[..., 0]
    bv = jnp.take_along_axis(bb, first[..., None], axis=-1)[..., 0]
    lt = any_diff & (av < bv)
    eq = ~any_diff
    if op == "=":
        return eq
    if op in ("!=", "<>"):
        return ~eq
    if op == "<":
        return lt
    if op == "<=":
        return lt | eq
    if op == ">":
        return ~(lt | eq)
    return ~lt                                   # ">="


def _num_cmp(jnp, op: str, a, b):
    if op == "=":
        return a == b
    if op in ("!=", "<>"):
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _eval_side(jnp, side: tuple, arrs: dict, shape, width: int) -> _Val:
    kind = side[0]
    if kind == "col":
        j = side[1]
        return _Val(arrs["num"][:, :, j], arrs["ok"][:, :, j],
                    arrs["null"][:, :, j], arrs["sb"][:, :, j, :],
                    arrs["slen"][:, :, j])
    if kind == "nlit":
        _k, value, sform = side
        sb, slen = _const_str(jnp, shape, sform, width)
        f = jnp.bool_(False)
        return _Val(jnp.full(shape, value, jnp.float64),
                    jnp.broadcast_to(~f, shape),
                    jnp.broadcast_to(f, shape), sb, slen)
    if kind == "slit":
        b = side[1]
        sb, slen = _const_str(jnp, shape, b, width)
        nv = None
        try:
            nv = float(b.decode("utf-8"))
        except ValueError:
            pass
        f = jnp.bool_(False)
        return _Val(jnp.full(shape, nv if nv is not None else 0.0,
                             jnp.float64),
                    jnp.broadcast_to(jnp.bool_(nv is not None), shape),
                    jnp.broadcast_to(f, shape), sb, slen)
    # arithmetic: numeric-only; invalid (non-numeric operand or
    # division/modulo by zero) behaves like the evaluator's None
    _k, op, sa, sb_ = side
    a = _eval_side(jnp, sa, arrs, shape, width)
    b = _eval_side(jnp, sb_, arrs, shape, width)
    valid = a.ok & b.ok
    if op == "+":
        v = a.num + b.num
    elif op == "-":
        v = a.num - b.num
    elif op == "*":
        v = a.num * b.num
    elif op == "/":
        valid = valid & (b.num != 0)
        v = a.num / jnp.where(b.num == 0, 1.0, b.num)
    else:                                        # "%" — Python floor-mod
        valid = valid & (b.num != 0)
        v = jnp.mod(a.num, jnp.where(b.num == 0, 1.0, b.num))
    return _Val(v, valid, ~valid)


def _eval_cmp(jnp, op: str, a: _Val, b: _Val):
    both_num = a.ok & b.ok
    either_null = a.null | b.null
    rnum = _num_cmp(jnp, op, a.num, b.num)
    if a.sb is None or b.sb is None:
        # an arithmetic side: its string path was declined upstream,
        # and the columns it compares against are numeric-or-null
        return both_num & rnum
    rstr = _str_cmp(jnp, op, a, b)
    return jnp.where(both_num, rnum, (~either_null) & rstr)


def _eval_like(jnp, arrs, slot: int, kind: str, needle: bytes,
               negate: bool):
    sb = arrs["sb"][:, :, slot, :]
    slen = arrs["slen"][:, :, slot]
    null = arrs["null"][:, :, slot]
    W = sb.shape[-1]
    L = len(needle)
    if kind == "any":
        ok = jnp.broadcast_to(jnp.bool_(True), null.shape)
    elif L > W:
        ok = jnp.broadcast_to(jnp.bool_(False), null.shape)
    else:
        nd = jnp.asarray(np.frombuffer(needle, np.uint8))
        if kind == "exact":
            ok = (slen == L) & jnp.all(sb[..., :L] == nd, axis=-1)
        elif kind == "prefix":
            ok = (slen >= L) & jnp.all(sb[..., :L] == nd, axis=-1)
        elif kind == "suffix":
            idx = jnp.clip(slen[..., None] - L, 0, W - 1) \
                + jnp.arange(L)
            tail = jnp.take_along_axis(sb, idx, axis=-1)
            ok = (slen >= L) & jnp.all(tail == nd, axis=-1)
        else:                                    # contains
            hits = []
            for off in range(W - L + 1):
                hits.append(jnp.all(sb[..., off:off + L] == nd,
                                    axis=-1)
                            & (slen >= off + L))
            ok = jnp.any(jnp.stack(hits, axis=-1), axis=-1)
    ok = ok & ~null                              # NULL never matches
    return ok != negate if negate else ok


def _eval_prog(jnp, prog: tuple, arrs: dict, shape, width: int):
    kind = prog[0]
    if kind == "true":
        return jnp.broadcast_to(jnp.bool_(True), shape)
    if kind == "and":
        return _eval_prog(jnp, prog[1], arrs, shape, width) \
            & _eval_prog(jnp, prog[2], arrs, shape, width)
    if kind == "or":
        return _eval_prog(jnp, prog[1], arrs, shape, width) \
            | _eval_prog(jnp, prog[2], arrs, shape, width)
    if kind == "not":
        return ~_eval_prog(jnp, prog[1], arrs, shape, width)
    if kind == "cmp":
        _k, op, sa, sb = prog
        return _eval_cmp(jnp, op,
                         _eval_side(jnp, sa, arrs, shape, width),
                         _eval_side(jnp, sb, arrs, shape, width))
    if kind == "in":
        _k, sx, items, negate = prog
        x = _eval_side(jnp, sx, arrs, shape, width)
        hit = jnp.broadcast_to(jnp.bool_(False), shape)
        for item in items:
            iv = _eval_side(jnp, item, arrs, shape, width)
            hit = hit | _eval_cmp(jnp, "=", x, iv)
        return ~hit if negate else hit
    if kind == "between":
        _k, sx, slo, shi, negate = prog
        x = _eval_side(jnp, sx, arrs, shape, width)
        lo = _eval_side(jnp, slo, arrs, shape, width)
        hi = _eval_side(jnp, shi, arrs, shape, width)
        ok = (~x.null) & _eval_cmp(jnp, ">=", x, lo) \
            & _eval_cmp(jnp, "<=", x, hi)
        return ~ok if negate else ok
    if kind == "isnull":
        _k, slot, negate = prog
        null = arrs["null"][:, :, slot]
        return ~null if negate else null
    if kind == "like":
        _k, slot, lkind, needle, negate = prog
        return _eval_like(jnp, arrs, slot, lkind, needle, negate)
    raise ValueError(f"bad scan program node {kind!r}")


# -- entry points -----------------------------------------------------------

_ARRAY_ORDER = ("num", "ok", "null", "sb", "slen", "rowvalid")


def _kernel_for(plan, shape: tuple):
    key = (plan.signature, shape)
    with _COMPILE_MU:
        fn = _KERNELS.get(key)
        if fn is not None:
            _KERNELS.move_to_end(key)
            return fn
        import jax
        import jax.numpy as jnp
        prog = plan.prog

        def run(num, ok, null, sb, slen, rowvalid):
            arrs = {"num": num, "ok": ok, "null": null, "sb": sb,
                    "slen": slen, "rowvalid": rowvalid}
            mask = _eval_prog(jnp, prog, arrs, num.shape[:2],
                              sb.shape[-1])
            return mask & rowvalid

        fn = jax.jit(run)
        _KERNELS[key] = fn
        while len(_KERNELS) > _KERNEL_CACHE_CAP:
            _KERNELS.popitem(last=False)
        return fn


def _pad_batch(arrays: dict, b: int) -> dict:
    """Pad the page axis to b (power-of-two cap) so the jit cache sees
    a handful of batch shapes; pad pages carry rowvalid=False."""
    have = next(iter(arrays.values())).shape[0]
    if have == b:
        return arrays
    out = {}
    for k, v in arrays.items():
        pad = np.zeros((b - have, *v.shape[1:]), v.dtype)
        if k == "null":
            pad[:] = True
        out[k] = np.concatenate([v, pad], axis=0)
    return out


def run_batch(plan, arrays: dict) -> np.ndarray:
    """Evaluate the plan's predicate over one (possibly coalesced)
    page batch; returns the boolean row mask [B, R]. Raises on any
    backend failure — callers treat that as a decline and CPU-route."""
    b = next(iter(arrays.values())).shape[0]
    cap = 1
    while cap < b:
        cap *= 2
    padded = _pad_batch(arrays, cap)
    shape = tuple(padded["num"].shape) + (padded["sb"].shape[-1],)
    with _x64():
        fn = _kernel_for(plan, shape)
        mask = fn(*[padded[k] for k in _ARRAY_ORDER])
        out = np.asarray(mask)
    return out[:b]
