"""Per-bucket notification configuration: the S3
`NotificationConfiguration` XML surface (PUT/GET ``?notification``),
parsed into prefix/suffix/event-type rules that gate which namespace
events reach which targets (pkg/event/rules.go + config.go semantics,
namespace-tolerant parsing like the legacy features/events.py)."""

from __future__ import annotations

import dataclasses
import fnmatch
import xml.etree.ElementTree as ET

# every event name the plane can classify from object state; rule
# patterns must match at least one of these (reference: unknown event
# names are rejected at PutBucketNotification time)
EVENT_NAMES = (
    "s3:ObjectCreated:Put",
    "s3:ObjectCreated:CompleteMultipartUpload",
    "s3:ObjectRemoved:Delete",
    "s3:ObjectRemoved:DeleteMarkerCreated",
    "s3:ObjectRestore:Completed",
    "s3:ObjectTransition:Complete",
)


class NotifyRuleError(ValueError):
    """Malformed notification configuration (bad XML, empty rule,
    unsupported event pattern)."""


def _strip(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _findall(el, name: str) -> list:
    return [c for c in el if _strip(c.tag) == name]


def _text(el, name: str, default: str = "") -> str:
    for c in _findall(el, name):
        return (c.text or "").strip()
    return default


@dataclasses.dataclass
class NotifyRule:
    """One Queue/Topic/CloudFunction configuration entry."""
    arn: str
    events: list[str]                  # e.g. ["s3:ObjectCreated:*"]
    prefix: str = ""
    suffix: str = ""

    def matches(self, event_name: str, key: str) -> bool:
        if not any(fnmatch.fnmatchcase(event_name, pat)
                   for pat in self.events):
            return False
        if self.prefix and not key.startswith(self.prefix):
            return False
        if self.suffix and not key.endswith(self.suffix):
            return False
        return True

    def unknown_events(self) -> list[str]:
        """Event patterns that can never fire (match no known name)."""
        return [pat for pat in self.events
                if not any(fnmatch.fnmatchcase(n, pat)
                           for n in EVENT_NAMES)]


class BucketNotifyConfig:
    """The parsed per-bucket rule set."""

    def __init__(self, rules: list[NotifyRule]):
        self.rules = rules

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "BucketNotifyConfig":
        try:
            root = ET.fromstring(raw)
        except ET.ParseError as e:
            raise NotifyRuleError(f"malformed notification XML: {e}") \
                from None
        rules = []
        for qel in (_findall(root, "QueueConfiguration")
                    + _findall(root, "TopicConfiguration")
                    + _findall(root, "CloudFunctionConfiguration")):
            arn = (_text(qel, "Queue") or _text(qel, "Topic")
                   or _text(qel, "CloudFunction"))
            if not arn:
                raise NotifyRuleError(
                    "a notification configuration entry names no "
                    "target ARN")
            events = [(e.text or "").strip()
                      for e in _findall(qel, "Event")]
            if not any(events):
                raise NotifyRuleError(
                    f"rule for {arn!r} subscribes to no events")
            prefix = suffix = ""
            for fel in _findall(qel, "Filter"):
                for kel in _findall(fel, "S3Key"):
                    for frel in _findall(kel, "FilterRule"):
                        name = _text(frel, "Name").lower()
                        value = _text(frel, "Value")
                        if name == "prefix":
                            prefix = value
                        elif name == "suffix":
                            suffix = value
            rules.append(NotifyRule(arn=arn, events=events,
                                    prefix=prefix, suffix=suffix))
        return cls(rules)

    def arns(self) -> set[str]:
        return {r.arn for r in self.rules}

    def match(self, event_name: str, key: str) -> set[str]:
        """The target ARNs this (event, key) fans out to."""
        return {r.arn for r in self.rules
                if r.matches(event_name, key)}

    def unknown_events(self) -> list[str]:
        out: list[str] = []
        for r in self.rules:
            out.extend(r.unknown_events())
        return out
