"""The bucket event notification plane: the N-th consumer of the ONE
namespace feed.

One listener on the engines' namespace-change feed (wired by
``ErasureServerSets.attach_notifications`` — the lint gate's
hook-coverage chain proves every mutation verb reaches this queue), a
bounded dedup queue of ``(bucket, key)`` events, and a worker pool
that:

  * **classifies** each touched key by reading its CURRENT state (the
    feed carries no verb — like replication, the plane converges from
    what is actually on disk): latest version a delete marker →
    ``s3:ObjectRemoved:DeleteMarkerCreated``; key gone →
    ``s3:ObjectRemoved:Delete``; a transitioned stub →
    ``s3:ObjectTransition:Complete``; a restored copy →
    ``s3:ObjectRestore:Completed``; multipart parts →
    ``s3:ObjectCreated:CompleteMultipartUpload``; else
    ``s3:ObjectCreated:Put``;
  * **filters** through the bucket's `NotificationConfiguration` rules
    (prefix/suffix/event patterns) against the registered target map;
  * **suppresses replica applies** by default (reference parity:
    replication does not re-fire source events at the replica site) —
    the event JSON's ``responseElements`` carries the ORIGIN site id
    and tier name so downstream consumers can tell local writes from
    replica applies when suppression is off;
  * **delivers at-least-once** per target through a durable (or
    in-memory) per-target queue: the record persists BEFORE the send
    (crashpoint ``notify.queue.persist`` pins the kill/replay window),
    failures open a per-target offline window and feed an MRF-style
    retry queue with capped exponential backoff, and a periodic
    redrive sweep guarantees a bounded outage drains with zero loss;
  * **yields to the foreground**: workers throttle off the shared
    foreground-pressure probe — a dead webhook never backs up the PUT
    hot path (``bench.py --ab-notify`` pins the p99 bound);
  * on multi-node clusters, only the bucket's OWNER node (rendezvous
    hash over the membership set) delivers: non-owners forward the
    event over the peer control plane (falling back to local delivery
    when the owner is unreachable — a duplicate beats a lost event).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import urllib.parse
import uuid as _uuid
from collections import OrderedDict, deque
from typing import Optional

from ..object import api_errors
from ..object.background import MRFHealer
from ..replicate.targets import is_replica, origin_of
from ..storage.datatypes import (TRANSITION_TIER_KEY, is_restored,
                                 is_transitioned)
from ..utils import crashpoint, eventlog, knobs, telemetry
from ..utils.pressure import ForegroundPressure
from .rules import BucketNotifyConfig, NotifyRuleError
from .targets import NotifyTargetRegistry

WORKERS = knobs.get_int("MINIO_TPU_NOTIFY_WORKERS")
QUEUE_SIZE = knobs.get_int("MINIO_TPU_NOTIFY_QUEUE")
BACKOFF_S = knobs.get_float("MINIO_TPU_NOTIFY_BACKOFF_S")
BACKOFF_MAX_S = knobs.get_float("MINIO_TPU_NOTIFY_BACKOFF_MAX_S")
BACKOFF_TRIES = knobs.get_int("MINIO_TPU_NOTIFY_BACKOFF_TRIES")

_LAG_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_notify_sent_total",
                    "Event records delivered to notification targets"),
        reg.counter("minio_tpu_notify_failed_total",
                    "Event deliveries that failed (kept in the "
                    "per-target queue, retried with backoff)"),
        reg.counter("minio_tpu_notify_dropped_total",
                    "Event records dropped at a full per-target queue "
                    "(bounded backlog: overflow drops, never blocks)"),
        reg.histogram("minio_tpu_notify_lag_seconds",
                      "Delivery lag: send completion minus the "
                      "namespace event's enqueue time",
                      buckets=_LAG_BUCKETS),
    )


def render_record(event_name: str, bucket: str, key: str, *,
                  region: str = "us-east-1", size: int = 0,
                  etag: str = "", version_id: str = "",
                  mod_time: float = 0.0, origin_site: str = "",
                  tier: str = "", node: str = "") -> dict:
    """The reference S3 event record (pkg/event/event.go shape), plus
    ``responseElements`` origin metadata: ``x-minio-origin-site`` (the
    site the version was originally written at) and ``x-minio-tier``
    (the remote tier of a transitioned/restored version)."""
    t = mod_time or time.time()
    now = time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime())
    return {"Records": [{
        "eventVersion": "2.0", "eventSource": "minio:s3",
        "awsRegion": region, "eventTime": now, "eventName": event_name,
        "userIdentity": {"principalId": "minio"},
        "requestParameters": {"sourceIPAddress": node or "127.0.0.1"},
        "responseElements": {
            "x-amz-request-id": _uuid.uuid4().hex[:16].upper(),
            "x-minio-origin-node": node,
            "x-minio-origin-site": origin_site,
            "x-minio-tier": tier},
        "s3": {"s3SchemaVersion": "1.0", "configurationId": "Config",
               "bucket": {"name": bucket,
                          "ownerIdentity": {"principalId": "minio"},
                          "arn": f"arn:aws:s3:::{bucket}"},
               "object": {"key": urllib.parse.quote(key),
                          "size": size, "eTag": etag,
                          "versionId": version_id,
                          "sequencer": format(int(t * 1e9), "016X")}},
    }]}


class _MemoryStore:
    """The in-memory twin of the durable per-target queue (same API:
    put/get/delete/keys) for embedders without a queue directory."""

    def __init__(self, limit: int):
        self.limit = limit
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, dict]" = OrderedDict()

    def put(self, record: dict) -> Optional[str]:
        with self._mu:
            if len(self._entries) >= self.limit:
                return None
            key = f"{time.time_ns():020d}-{_uuid.uuid4().hex[:8]}"
            self._entries[key] = record
            return key

    def get(self, key: str) -> Optional[dict]:
        with self._mu:
            return self._entries.get(key)

    def delete(self, key: str) -> None:
        with self._mu:
            self._entries.pop(key, None)

    def keys(self) -> list[str]:
        with self._mu:
            return sorted(self._entries)


def _owner_of(bucket: str, nodes: list[str]) -> str:
    """Rendezvous (highest-random-weight) hash: every node computes the
    same owner from the same membership set, and a membership change
    only moves the buckets that hashed to the lost/added node."""
    return max(nodes, key=lambda n: hashlib.sha1(
        f"{bucket}\x00{n}".encode()).digest())


class NotificationPlane:
    """One node's notification engine (queue + workers + retry)."""

    def __init__(self, object_layer, registry: NotifyTargetRegistry,
                 bucket_meta=None, region: str = "us-east-1",
                 queue_dir: Optional[str] = None,
                 node: str = "", nodes: Optional[list[str]] = None,
                 site_id: str = "",
                 workers: Optional[int] = None,
                 queue_size: Optional[int] = None,
                 busy_fn=None, throttle_s: Optional[float] = None):
        self.obj = object_layer
        self.registry = registry
        # bucket metadata system carrying notification_xml; embedders
        # without one (bench, unit tests) use set_config() instead
        self.bucket_meta = bucket_meta
        self.region = region
        self.queue_dir = queue_dir
        self.node = node
        self.nodes = sorted(nodes or [])
        self.site_id = site_id
        # injected by the cluster: forward one event to the bucket's
        # owner node over the peer control plane; returns True when the
        # owner accepted it
        self.forward_fn = None
        # injected by the cluster: broadcast a registry reload to every
        # peer after an admin target mutation (their boot-time loads
        # would otherwise serve a stale target map)
        self.reload_peers = None
        self._pressure = ForegroundPressure(object_layer, busy_fn=busy_fn)
        self._throttle_base = BACKOFF_S if throttle_s is None \
            else throttle_s
        self.queue_size = QUEUE_SIZE if queue_size is None else queue_size
        self.store_limit = knobs.get_int("MINIO_TPU_NOTIFY_STORE_LIMIT")
        self.offline_s = knobs.get_float("MINIO_TPU_NOTIFY_OFFLINE_S")
        self.replica_events = knobs.get_bool(
            "MINIO_TPU_NOTIFY_REPLICA_EVENTS")
        self._cond = threading.Condition()
        self._queue: deque = deque()   # (bucket, key, enq_t, owned)
        self._pending: set[tuple[str, str]] = set()
        self._inflight = 0
        self._stores: dict[str, object] = {}
        self._offline_until: dict[str, float] = {}
        self._local_xml: dict[str, str] = {}
        self._cfg_cache: dict[str, tuple[str, BucketNotifyConfig]] = {}
        self._target_stats: dict[str, dict] = {}
        self._stop = threading.Event()
        # stats (admin surface / tests)
        self.queued = 0
        self.delivered = 0
        self.failed_sends = 0
        self.dropped = 0
        self.suppressed = 0            # replica applies (default off)
        self.forwarded = 0             # handed to the owner node
        self.fallback_local = 0        # owner unreachable: sent here
        # failed deliveries retry here with capped exponential backoff
        # — the fault plane's queue, the backlog redrive as its heal fn
        self.mrf = MRFHealer(heal_fn=self._mrf_retry)
        self._threads = []
        for i in range(WORKERS if workers is None else workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"notify-{i}")
            t.start()
            self._threads.append(t)
        self._redrive_thread = threading.Thread(
            target=self._redrive_loop, daemon=True, name="notify-redrive")
        self._redrive_thread.start()
        # replay whatever the last process left in the durable queues
        self.redrive()

    # -- the namespace-feed listener ------------------------------------

    def on_namespace_change(self, bucket: str, key: str) -> None:
        """Enqueue one namespace event; never blocks (bounded queue,
        overflow drops + counts)."""
        if bucket.startswith(".") or not key:
            return
        if self._config(bucket) is None:
            return
        self._enqueue(bucket, key, owned=False)

    def ingest(self, bucket: str, key: str) -> None:
        """Peer-forwarded event (this node owns the bucket): enqueue
        for local delivery, no ownership re-resolution (divergent
        membership views must not ping-pong an event)."""
        if bucket.startswith(".") or not key:
            return
        self._enqueue(bucket, key, owned=True)

    def _enqueue(self, bucket: str, key: str, owned: bool) -> None:
        with self._cond:
            if self._stop.is_set() or (bucket, key) in self._pending:
                return
            if len(self._queue) >= self.queue_size:
                self.dropped += 1
                return
            self._pending.add((bucket, key))
            self._queue.append((bucket, key, time.time(), owned))
            self.queued += 1
            self._cond.notify_all()

    # -- per-bucket configuration ---------------------------------------

    def set_config(self, bucket: str, xml: str) -> None:
        """Static rule injection for embedders without a bucket
        metadata system (bench, unit tests)."""
        self._local_xml[bucket] = xml

    def _config(self, bucket: str) -> Optional[BucketNotifyConfig]:
        xml = None
        if self.bucket_meta is not None:
            try:
                xml = self.bucket_meta.get(bucket).notification_xml
            except Exception:  # noqa: BLE001 — meta unavailable: no rules
                return None
        else:
            xml = self._local_xml.get(bucket)
        if not xml:
            return None
        cached = self._cfg_cache.get(bucket)
        if cached is not None and cached[0] == xml:
            return cached[1]
        try:
            cfg = BucketNotifyConfig.from_xml(xml)
        except NotifyRuleError:
            return None
        self._cfg_cache[bucket] = (xml, cfg)
        return cfg

    # -- ownership -------------------------------------------------------

    def owner_of(self, bucket: str) -> str:
        if len(self.nodes) <= 1:
            return self.node
        return _owner_of(bucket, self.nodes)

    # -- lifecycle / observability ---------------------------------------

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self.mrf.close()

    def stats(self) -> dict:
        with self._cond:
            out = {"pending": len(self._queue) + self._inflight,
                   "queued": self.queued, "delivered": self.delivered,
                   "failed": self.failed_sends, "dropped": self.dropped,
                   "suppressed": self.suppressed,
                   "forwarded": self.forwarded,
                   "fallback_local": self.fallback_local}
        out["backlog"] = sum(len(self._store(a).keys())
                             for a in self.registry.arns())
        out["retry"] = self.mrf.stats()
        return out

    def _target_entry(self, arn: str) -> dict:
        # caller holds self._cond
        entry = self._target_stats.get(arn)
        if entry is None:
            entry = self._target_stats[arn] = {
                "delivered": 0, "failed": 0,
                "last_delivery": 0.0, "last_lag_s": None}
        return entry

    def target_status(self) -> dict:
        """Per-target delivery health for the admin plane: durable
        backlog depth, offline-window state, last delivery timestamp,
        last observed lag, cumulative delivered/failed — the JSON twin
        of ``minio_tpu_notify_lag_seconds{target}``."""
        now = time.monotonic()
        with self._cond:
            entries = {arn: dict(st)
                       for arn, st in self._target_stats.items()}
            offline = dict(self._offline_until)
        out: dict = {}
        for arn in sorted(self.registry.arns()):
            st = entries.get(arn) or {
                "delivered": 0, "failed": 0,
                "last_delivery": 0.0, "last_lag_s": None}
            st["backlog"] = len(self._store(arn).keys())
            st["offline"] = offline.get(arn, 0.0) > now
            out[arn] = st
        return out

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until the event queue, the retry queue AND every
        per-target backlog are empty. Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return False
                self._cond.wait(remaining)
        while time.monotonic() < deadline and not self._stop.is_set():
            self.mrf.drain(max(
                min(1.0, deadline - time.monotonic()), 0.001))
            if not any(self._store(a).keys()
                       for a in self.registry.arns()):
                return True
            self.redrive()
            time.sleep(0.02)
        return not any(self._store(a).keys()
                       for a in self.registry.arns())

    # -- workers ----------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stop.is_set() and not self._queue:
                    self._cond.wait()
                if self._stop.is_set():
                    return
                bucket, key, enq_t, owned = self._queue.popleft()
                self._pending.discard((bucket, key))
                self._inflight += 1
            try:
                self._pressure.throttle(self._stop, self._throttle_base,
                                        BACKOFF_MAX_S, BACKOFF_TRIES)
                if not self._stop.is_set():
                    self._route(bucket, key, enq_t, owned)
            except Exception:  # noqa: BLE001 — feed is best-effort;
                pass           # per-target failures already queued
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _route(self, bucket: str, key: str, enq_t: float,
               owned: bool) -> None:
        if not owned:
            owner = self.owner_of(bucket)
            if owner and owner != self.node:
                if self.forward_fn is not None \
                        and self.forward_fn(owner, bucket, key):
                    with self._cond:
                        self.forwarded += 1
                    return
                # owner unreachable: deliver here — a duplicate at the
                # consumer beats an event lost to a dead peer
                with self._cond:
                    self.fallback_local += 1
        self._process(bucket, key, enq_t)

    # -- classification ----------------------------------------------------

    def classify(self, bucket: str, key: str):
        """Derive the S3 event name from the key's CURRENT state (the
        feed carries no verb). Returns (event_name, latest ObjectInfo
        or None when the key is gone)."""
        try:
            versions = self.obj.object_versions(bucket, key)
        except api_errors.ObjectApiError:
            versions = []
        if not versions:
            return "s3:ObjectRemoved:Delete", None
        latest = max(versions, key=lambda o: (o.mod_time or 0,
                                              o.version_id or ""))
        if latest.delete_marker:
            return "s3:ObjectRemoved:DeleteMarkerCreated", latest
        md = latest.user_defined or {}
        if is_transitioned(md):
            if is_restored(md):
                return "s3:ObjectRestore:Completed", latest
            return "s3:ObjectTransition:Complete", latest
        if len(latest.parts or []) > 1:
            return "s3:ObjectCreated:CompleteMultipartUpload", latest
        return "s3:ObjectCreated:Put", latest

    def _process(self, bucket: str, key: str, enq_t: float) -> None:
        event_name, info = self.classify(bucket, key)
        md = (info.user_defined or {}) if info is not None else {}
        if is_replica(md) and not self.replica_events:
            # reference parity: a replica apply never re-fires the
            # source event at the replica site
            with self._cond:
                self.suppressed += 1
            return
        cfg = self._config(bucket)
        if cfg is None:
            return
        arns = cfg.match(event_name, key) & self.registry.arns()
        if not arns:
            return
        record = render_record(
            event_name, bucket, key, region=self.region,
            size=(info.size or 0) if info is not None else 0,
            etag=(info.etag or "") if info is not None else "",
            version_id=(info.version_id or "")
            if info is not None else "",
            mod_time=(info.mod_time or 0.0)
            if info is not None else 0.0,
            origin_site=origin_of(md, self.site_id),
            tier=md.get(TRANSITION_TIER_KEY, ""), node=self.node)
        for arn in sorted(arns):
            self._deliver(arn, record, enq_t)

    # -- delivery ----------------------------------------------------------

    def _store(self, arn: str):
        with self._cond:
            store = self._stores.get(arn)
            if store is not None:
                return store
        if self.queue_dir is not None:
            from ..features.events import QueueStore
            safe = "".join(c if c.isalnum() or c in "-_." else "_"
                           for c in arn)
            store = QueueStore(os.path.join(self.queue_dir, safe),
                               limit=self.store_limit)
        else:
            store = _MemoryStore(self.store_limit)
        with self._cond:
            return self._stores.setdefault(arn, store)

    def _deliver(self, arn: str, record: dict, enq_t: float) -> None:
        _sent_c, _failed_c, dropped_c, _lag_h = _metrics()
        store = self._store(arn)
        ekey = store.put({"record": record, "t": enq_t})
        if ekey is None:
            # bounded backlog: overflow drops (and counts) rather than
            # growing without bound against a dead target
            with self._cond:
                self.dropped += 1
            dropped_c.inc(target=arn)
            eventlog.emit("notify.drop", target=arn)
            return
        # the record is durable and the target has not seen it: a kill
        # here must redrive exactly this entry after restart
        crashpoint.hit("notify.queue.persist")
        if self._offline_until.get(arn, 0.0) > time.monotonic():
            # offline window: don't burn a timeout per event against a
            # target that just failed — the retry queue probes it
            self.mrf.enqueue("notify", arn)
            return
        self._send_entry(arn, store, ekey)

    def _send_entry(self, arn: str, store, ekey: str) -> bool:
        entry = store.get(ekey)
        if entry is None:
            store.delete(ekey)          # torn/corrupt entry
            return True
        try:
            self.registry.sender(arn).send(entry["record"])
        except Exception:  # noqa: BLE001 — per-target isolation; the
            # durable entry stays put and the retry queue re-drives
            self._note_failure(arn)
            self.mrf.enqueue("notify", arn)
            return False
        store.delete(ekey)
        self._note_sent(arn, entry.get("t", 0.0))
        return True

    def _note_sent(self, arn: str, enq_t: float) -> None:
        sent_c, _failed_c, _dropped_c, lag_h = _metrics()
        lag = max(time.time() - (enq_t or time.time()), 0.0)
        with self._cond:
            self.delivered += 1
            entry = self._target_entry(arn)
            entry["delivered"] += 1
            entry["last_delivery"] = time.time()
            entry["last_lag_s"] = round(lag, 3)
            self._offline_until.pop(arn, None)
        sent_c.inc(target=arn)
        lag_h.observe(lag, target=arn)

    def _note_failure(self, arn: str) -> None:
        _sent_c, failed_c, _dropped_c, _lag_h = _metrics()
        with self._cond:
            self.failed_sends += 1
            self._target_entry(arn)["failed"] += 1
            was_online = self._offline_until.get(arn, 0.0) \
                <= time.monotonic()
            self._offline_until[arn] = time.monotonic() + self.offline_s
        failed_c.inc(target=arn)
        if was_online:
            eventlog.emit("notify.offline", target=arn)

    # -- retry / redrive ---------------------------------------------------

    def _mrf_retry(self, _bucket: str, arn: str, _version: str) -> None:
        """The retry queue's heal fn: redrive one target's WHOLE
        backlog, oldest first; a failure re-raises so the queue backs
        off, MRF-style."""
        try:
            self.registry.get(arn)
        except api_errors.ObjectApiError:
            return                      # target removed: converged
        store = self._store(arn)
        delivered = 0
        for ekey in store.keys():
            entry = store.get(ekey)
            if entry is None:
                store.delete(ekey)
                continue
            try:
                self.registry.sender(arn).send(entry["record"])
            except Exception:
                self._note_failure(arn)
                raise
            store.delete(ekey)
            self._note_sent(arn, entry.get("t", 0.0))
            delivered += 1
        if delivered:
            eventlog.emit("notify.redrive", target=arn,
                          delivered=delivered)

    def redrive(self) -> int:
        """Queue a retry for every target with persisted backlog
        (startup replay + the periodic sweep). Returns how many targets
        were queued."""
        n = 0
        for arn in self.registry.arns():
            if self._store(arn).keys():
                if self.mrf.enqueue("notify", arn):
                    n += 1
        return n

    def _redrive_loop(self) -> None:
        interval = knobs.get_float("MINIO_TPU_NOTIFY_REDRIVE_S")
        while not self._stop.wait(interval):
            try:
                self.redrive()
            except Exception:  # noqa: BLE001 — sweep is best-effort
                pass
