"""Notification-target registry: where bucket events go.

The reference wires targets from server config (cmd/config/notify/);
this registry promotes them to a first-class persisted document —
``.minio.sys/notify/targets.json`` written to EVERY pool and recovered
deterministic-winner, exactly the durability rule of the topology /
tier / replicate / qos registries: any surviving subset of pools
recovers the newest target map, and a same-epoch fork is an fsck
finding, never a coin flip.

Three target types cover the delivery matrix without external brokers:

* ``webhook`` — POST the event JSON to an HTTP endpoint (the reference
  webhook target; params: ``endpoint``, ``timeout``, optional
  ``auth_token`` sent as a Bearer header and redacted in listings);
* ``queue``   — an in-process bounded record sink (tests, the admin
  event tail, ListenBucketNotification-style consumers);
* ``log``     — append one JSON line per event to a local file.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import urllib.request
import uuid as _uuid
from typing import Optional

from ..object import api_errors
from ..storage.xl_storage import MINIO_META_BUCKET
from ..utils import atomicfile, crashpoint, eventlog, regfence

NOTIFY_PREFIX = "notify/"
TARGETS_OBJECT = NOTIFY_PREFIX + "targets.json"

TARGET_TYPES = ("webhook", "queue", "log")

_SECRET_PARAMS = ("auth_token", "secret_key")


class NotifyTargetError(api_errors.ObjectApiError):
    """Invalid notification-target operation (duplicate ARN, unknown
    ARN, bad spec)."""


def new_arn(name: str, type_: str) -> str:
    """Mint a reference-shape notification ARN
    (``arn:minio:sqs::<id>:<type>`` — pkg/event/arn.go)."""
    return f"arn:minio:sqs::{name or _uuid.uuid4().hex[:12]}:{type_}"


@dataclasses.dataclass
class NotifyTarget:
    """One registered event destination."""
    arn: str
    type: str = "webhook"          # "webhook" | "queue" | "log"
    params: dict = dataclasses.field(default_factory=dict)

    def to_dict(self, redact: bool = False) -> dict:
        params = dict(self.params)
        if redact:
            for k in _SECRET_PARAMS:
                if params.get(k):
                    params[k] = "REDACTED"
        return {"arn": self.arn, "type": self.type, "params": params}

    @classmethod
    def from_dict(cls, d: dict) -> "NotifyTarget":
        arn = str(d.get("arn", "")).strip()
        type_ = str(d.get("type", "webhook")).strip()
        if not arn:
            raise NotifyTargetError("target needs an arn")
        if type_ not in TARGET_TYPES:
            raise NotifyTargetError(
                f"unknown target type {type_!r} "
                f"(expected one of {TARGET_TYPES})")
        t = cls(arn=arn, type=type_, params=dict(d.get("params") or {}))
        t.validate()
        return t

    def validate(self) -> None:
        if self.type == "webhook" and not self.params.get("endpoint"):
            raise NotifyTargetError(
                "webhook targets need params.endpoint")
        if self.type == "log" and not self.params.get("path"):
            raise NotifyTargetError("log targets need params.path")


# ---------------------------------------------------------------------------
# senders (the live delivery side of a registered target)
# ---------------------------------------------------------------------------

class WebhookSender:
    """POST the event JSON to an endpoint (pkg/event/target/webhook)."""

    def __init__(self, arn: str, endpoint: str, timeout: float = 2.0,
                 auth_token: str = ""):
        self.arn = arn
        self.endpoint = endpoint
        self.timeout = timeout
        self.auth_token = auth_token

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        headers = {"Content-Type": "application/json"}
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        req = urllib.request.Request(self.endpoint, data=body,
                                     method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()


class QueueSender:
    """In-process bounded record sink (tests / event tails)."""

    def __init__(self, arn: str, limit: int = 10000):
        self.arn = arn
        self.limit = limit
        self.records: list[dict] = []
        self._cond = threading.Condition()

    def send(self, record: dict) -> None:
        with self._cond:
            if len(self.records) >= self.limit:
                raise NotifyTargetError(
                    f"queue target {self.arn!r} is full "
                    f"({self.limit} records)")
            self.records.append(record)
            self._cond.notify_all()

    def wait_for(self, n: int, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.records) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return False
            return True


class LogSender:
    """Append one JSON line per event to a local file."""

    def __init__(self, arn: str, path: str):
        self.arn = arn
        self.path = path
        self._mu = threading.Lock()

    def send(self, record: dict) -> None:
        line = json.dumps(record) + "\n"
        with self._mu:
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            with open(self.path, "a") as f:
                f.write(line)


def make_sender(target: NotifyTarget):
    p = target.params
    if target.type == "webhook":
        return WebhookSender(target.arn, str(p.get("endpoint", "")),
                             timeout=float(p.get("timeout", 2.0) or 2.0),
                             auth_token=str(p.get("auth_token", "")))
    if target.type == "queue":
        return QueueSender(target.arn,
                           limit=int(p.get("limit", 10000) or 10000))
    if target.type == "log":
        return LogSender(target.arn, str(p.get("path", "")))
    raise NotifyTargetError(f"unknown target type {target.type!r}")


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

class NotifyTargetRegistry:
    """The live target map + sender cache. Every mutation bumps
    ``epoch`` and persists BEFORE it takes effect (the TierManager
    discipline: a crash mid-add replays, never forgets a target a
    bucket rule already references)."""

    def __init__(self, object_layer=None):
        self.obj = object_layer
        self._mu = threading.Lock()
        self.epoch = 0
        self.updated = time.time()
        self.targets: dict[str, NotifyTarget] = {}
        self._senders: dict[str, object] = {}
        # lineage fencing: every epoch commit chains a hash of
        # (parent lineage, epoch, writer) — see utils/regfence.py
        self.writer = ""
        self.parent_lineage = ""
        self.lineage = ""

    def _advance_lineage(self) -> None:
        """Chain the fencing hash for the epoch just committed (caller
        holds ``_mu``)."""
        self.parent_lineage = self.lineage
        self.writer = regfence.default_writer()
        self.lineage = regfence.lineage(self.parent_lineage,
                                        self.epoch, self.writer)

    # ------------------------------------------------------------------
    # CRUD
    # ------------------------------------------------------------------

    def add(self, target: NotifyTarget, update: bool = False) -> int:
        """Register (or with `update` replace) a target; the spec
        validates before the registry mutates. Returns the new epoch."""
        target.validate()
        with self._mu:
            if not update and target.arn in self.targets:
                raise NotifyTargetError(
                    f"target {target.arn!r} already exists")
            prev = self.targets.get(target.arn)
            self.targets[target.arn] = target
            self._senders.pop(target.arn, None)
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:              # roll back the in-memory map
                if prev is None:
                    self.targets.pop(target.arn, None)
                else:
                    self.targets[target.arn] = prev
            raise
        self._emit_update(epoch)
        return epoch

    def remove(self, arn: str) -> int:
        with self._mu:
            if arn not in self.targets:
                raise NotifyTargetError(f"unknown target {arn!r}")
            prev = self.targets.pop(arn)
            self._senders.pop(arn, None)
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:
                self.targets[arn] = prev
            raise
        self._emit_update(epoch)
        return epoch

    def get(self, arn: str) -> NotifyTarget:
        with self._mu:
            t = self.targets.get(arn)
        if t is None:
            raise NotifyTargetError(f"unknown target {arn!r}")
        return t

    def arns(self) -> set[str]:
        with self._mu:
            return set(self.targets)

    def list(self, redact: bool = True) -> list[dict]:
        with self._mu:
            return [t.to_dict(redact=redact)
                    for t in sorted(self.targets.values(),
                                    key=lambda t: t.arn)]

    def sender(self, arn: str):
        """The live delivery object of a registered target (built
        lazily; survives re-registration only through set_sender)."""
        with self._mu:
            s = self._senders.get(arn)
            t = self.targets.get(arn)
        if s is not None:
            return s
        if t is None:
            raise NotifyTargetError(f"unknown target {arn!r}")
        s = make_sender(t)
        with self._mu:
            return self._senders.setdefault(arn, s)

    def set_sender(self, arn: str, sender) -> None:
        """Swap the live sender of a registered target (chaos tests
        wrap the real sender in a NaughtyTarget)."""
        self.get(arn)
        with self._mu:
            self._senders[arn] = sender

    def _emit_update(self, epoch: int) -> None:
        with self._mu:
            n = len(self.targets)
        eventlog.emit("notify.update", epoch=epoch, targets=n)

    # ------------------------------------------------------------------
    # persistence (every pool, deterministic winner)
    # ------------------------------------------------------------------

    def to_dict(self) -> dict:
        with self._mu:
            return {"epoch": self.epoch, "updated": self.updated,
                    "targets": [t.to_dict()
                                for t in self.targets.values()],
                    "writer": self.writer,
                    "parent_lineage": self.parent_lineage,
                    "lineage": self.lineage}

    def _pools(self):
        if self.obj is None:
            return []
        return getattr(self.obj, "server_sets", None) or [self.obj]

    def save(self) -> int:
        """Write the registry to every pool; the configured write
        quorum must land or the mutation is rejected (caller rolls
        back)."""
        pools = self._pools()
        if not pools:
            return 0
        payload = json.dumps(self.to_dict()).encode()
        landed = 0
        last: Optional[Exception] = None
        for z in pools:
            try:
                # one hit per pool (arm :<nth>)
                crashpoint.hit("notify.registry.save.pool")
                z.put_object(MINIO_META_BUCKET, TARGETS_OBJECT, payload)
                landed += 1
            except Exception as e:  # noqa: BLE001 — per-pool durability
                last = e
        need = regfence.write_quorum(len(pools))
        if landed < need:
            # refusing a minority-side epoch bump (caller rolls back)
            raise NotifyTargetError(
                f"notify targets epoch {self.epoch} persisted to "
                f"{landed} of {len(pools)} pool(s), need {need}: "
                f"{last!r}")
        return landed

    def load(self) -> bool:
        """Recover the newest persisted registry (deterministic winner
        across pools); returns True when a doc was found. Live senders
        reset and reconstruct lazily."""
        docs: list[dict] = []
        for z in self._pools():
            try:
                _, stream = z.get_object(MINIO_META_BUCKET,
                                         TARGETS_OBJECT)
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:     # torn/truncated copy: other pools win
                continue
            docs.append(doc)
        # deterministic winner; same-epoch/different-lineage copies are
        # a fork fsck surfaces — load never coin-flips between them
        best = regfence.pick_best(docs)
        if best is None:
            return False
        targets = {}
        for d in best.get("targets", []):
            try:
                t = NotifyTarget.from_dict(d)
            except NotifyTargetError:
                continue
            targets[t.arn] = t
        with self._mu:
            self.epoch = int(best.get("epoch", 0))
            self.updated = float(best.get("updated", time.time()))
            self.targets = targets
            self.writer = str(best.get("writer", ""))
            self.parent_lineage = str(best.get("parent_lineage", ""))
            self.lineage = str(best.get("lineage", ""))
            self._senders.clear()
        return True
