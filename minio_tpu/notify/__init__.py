"""Bucket event notification plane (the N-th consumer of the ONE
namespace feed).

The reference ships bucket notifications as a first-class S3 surface
(pkg/event/, cmd/event-notification.go): webhook/queue targets named by
ARN, per-bucket `NotificationConfiguration` rules with prefix/suffix/
event-type filters, and S3 event JSON records delivered at-least-once
through per-target durable queues. This package rebuilds that surface
on top of the engine namespace feed instead of per-handler send calls:

* ``targets.py``  — the epoch-versioned target registry (webhook /
  in-process queue / file-log target types), persisted to every pool
  under ``.minio.sys/notify/`` with regfence lineage — the same
  durability rule as the topology/tier/replicate/qos registries, so
  fsck's registry-fork coverage applies unchanged;
* ``rules.py``    — per-bucket `NotificationConfiguration` XML
  (prefix/suffix/event filters, ARN validation);
* ``plane.py``    — the NotificationPlane: one listener on the
  namespace feed (wired by ``ErasureServerSets.attach_notifications``,
  pinned by the lint gate's hook-coverage chain), state-derived event
  classification, reference-shape event records, bounded dedup queue,
  MRF-style capped-backoff retry, per-target offline windows and
  owner-node delivery on multi-node clusters;
* ``chaos.py``    — the NaughtyTarget deterministic fault wrapper the
  durability tests drive.
"""

from .chaos import NaughtyTarget
from .plane import NotificationPlane, render_record
from .rules import BucketNotifyConfig, NotifyRule, NotifyRuleError
from .targets import (NotifyTarget, NotifyTargetError,
                      NotifyTargetRegistry, new_arn)

__all__ = [
    "BucketNotifyConfig", "NaughtyTarget", "NotificationPlane",
    "NotifyRule", "NotifyRuleError", "NotifyTarget", "NotifyTargetError",
    "NotifyTargetRegistry", "new_arn", "render_record",
]
