"""NaughtyTarget: deterministic fault injection for event delivery.

Wraps a live sender (``registry.set_sender(arn, NaughtyTarget(...))``)
and fails sends by PLAN, not by clock — the chaos matrix replays
bit-identically:

* ``fail_first=n``       — the first n sends raise (a 503 storm);
* ``offline_every=(k,m)``— every k-th send opens an m-send offline
  window (raises for the next m attempts);
* ``die_after_send=n``   — the n-th send DELIVERS, then raises
  (mid-POST death after the body landed: the retry re-sends and the
  consumer sees a duplicate — at-least-once, never lost).
"""

from __future__ import annotations

import threading


class NaughtyTargetError(ConnectionError):
    """The injected delivery failure."""


class NaughtyTarget:
    def __init__(self, inner, fail_first: int = 0,
                 offline_every: tuple[int, int] = (0, 0),
                 die_after_send: int = 0):
        self.inner = inner
        self.arn = getattr(inner, "arn", "")
        self.fail_first = fail_first
        self.offline_every = offline_every
        self.die_after_send = die_after_send
        self._mu = threading.Lock()
        self.attempts = 0
        self.delivered = 0
        self.failures = 0
        self._offline_left = 0

    def send(self, record: dict) -> None:
        with self._mu:
            self.attempts += 1
            attempt = self.attempts
            if attempt <= self.fail_first:
                self.failures += 1
                raise NaughtyTargetError(
                    f"injected 503 ({attempt}/{self.fail_first})")
            if self._offline_left > 0:
                self._offline_left -= 1
                self.failures += 1
                raise NaughtyTargetError("injected offline window")
            every, span = self.offline_every
            if every > 0 and attempt % every == 0:
                self._offline_left = span
            die = (self.die_after_send > 0
                   and attempt == self.die_after_send)
        self.inner.send(record)
        with self._mu:
            self.delivered += 1
        if die:
            # the body landed but the ack never arrived — the caller
            # must retry and the consumer must tolerate the duplicate
            raise NaughtyTargetError("injected mid-POST death "
                                     "(delivered, ack lost)")
