"""Credentials model (reference pkg/auth/credentials.go).

Access/secret pairs with optional session token + expiry, used by both
the root account and IAM-issued users/service-accounts/STS creds.
"""

from __future__ import annotations

import base64
import dataclasses
import os
import secrets
import time
from typing import Optional

ACCESS_KEY_MIN_LEN = 3
ACCESS_KEY_MAX_LEN = 20
SECRET_KEY_MIN_LEN = 8
SECRET_KEY_MAX_LEN = 40

DEFAULT_ACCESS_KEY = "minioadmin"
DEFAULT_SECRET_KEY = "minioadmin"

_ALNUM = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"


@dataclasses.dataclass
class Credentials:
    access_key: str = ""
    secret_key: str = ""
    session_token: str = ""
    expiration: float = 0.0       # unix seconds; 0 = never
    status: str = "on"            # "on" | "off"
    parent_user: str = ""         # set for service accounts / STS creds

    def is_expired(self) -> bool:
        return self.expiration > 0 and time.time() > self.expiration

    def is_temp(self) -> bool:
        return bool(self.session_token)

    def is_service_account(self) -> bool:
        return bool(self.parent_user) and not self.session_token

    def is_valid(self) -> bool:
        return (self.status != "off" and bool(self.access_key)
                and bool(self.secret_key) and not self.is_expired())

    def equal(self, other: "Credentials") -> bool:
        return (self.access_key == other.access_key
                and self.secret_key == other.secret_key
                and self.session_token == other.session_token)


def generate_credentials() -> Credentials:
    """Random access/secret pair (reference GetNewCredentials)."""
    access = "".join(secrets.choice(_ALNUM) for _ in range(20))
    secret = base64.b64encode(os.urandom(30)).decode()[:40].replace("/", "+")
    return Credentials(access_key=access, secret_key=secret)


def global_credentials() -> Credentials:
    """Root credentials from env (MINIO_ACCESS_KEY / MINIO_SECRET_KEY,
    falling back to minioadmin:minioadmin like the reference)."""
    return Credentials(
        access_key=os.environ.get(
            "MINIO_ACCESS_KEY",
            os.environ.get("MINIO_ROOT_USER", DEFAULT_ACCESS_KEY)),
        secret_key=os.environ.get(
            "MINIO_SECRET_KEY",
            os.environ.get("MINIO_ROOT_PASSWORD", DEFAULT_SECRET_KEY)))


def is_access_key_valid(ak: str) -> bool:
    return ACCESS_KEY_MIN_LEN <= len(ak)


def is_secret_key_valid(sk: str) -> bool:
    return SECRET_KEY_MIN_LEN <= len(sk)
