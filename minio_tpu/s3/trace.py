"""HTTP request tracing + audit logging.

The reference wraps every route in httpTraceAll (cmd/http-tracer.go),
publishes trace entries to pkg/pubsub for `mc admin trace` (admin /trace
endpoint + peer fan-out), and ships structured audit entries to webhook
targets (cmd/logger/audit.go). Here: a middleware recording method/path/
status/duration/caller, an in-process hub, an admin streaming endpoint,
and an optional audit webhook.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Optional

from ..utils.pubsub import PubSub


class TraceSys:
    def __init__(self, node_name: str = "", ring_size: int = 200):
        from collections import deque
        self.hub = PubSub()
        self.node = node_name
        self.audit_webhook: str = ""           # POST target for audit
        self.requests_total = 0
        self.errors_total = 0
        # recent-entry ring: peers pull this for cluster-wide trace
        # (the reference streams over peer REST; a pull ring is the
        # polling equivalent)
        self.recent: "deque[dict]" = deque(maxlen=ring_size)
        self._mu = threading.Lock()

    # -- middleware --------------------------------------------------------

    def record(self, method: str, path: str, query: str, status: int,
               duration_s: float, caller: str = "",
               api: str = "") -> None:
        with self._mu:
            self.requests_total += 1
            if status >= 500:
                self.errors_total += 1
        entry = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "node": self.node,
            "api": api,
            "method": method,
            "path": path,
            "query": query,
            "status": status,
            "duration_ms": round(duration_s * 1e3, 3),
            "caller": caller,
        }
        self.recent.append(entry)
        if self.hub.subscriber_count:
            self.hub.publish(entry)
        if self.audit_webhook:
            threading.Thread(target=self._ship_audit, args=(entry,),
                             daemon=True).start()

    def _ship_audit(self, entry: dict) -> None:
        try:
            req = urllib.request.Request(
                self.audit_webhook, data=json.dumps(entry).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=3.0) as r:
                r.read()
        except Exception:  # noqa: BLE001 — audit is best-effort
            pass

    # -- admin streaming endpoint -----------------------------------------

    def stream(self, max_entries: int = 0, idle_timeout: float = 10.0):
        """Yields JSON-line trace entries as they happen (admin /trace);
        ends after idle_timeout with no traffic or max_entries sent."""
        sent = 0
        with self.hub.subscribe() as sub:
            while True:
                entry = sub.get(timeout=idle_timeout)
                if entry is None:
                    return
                yield (json.dumps(entry) + "\n").encode()
                sent += 1
                if max_entries and sent >= max_entries:
                    return
