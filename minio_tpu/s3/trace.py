"""HTTP request tracing + audit logging.

The reference wraps every route in httpTraceAll (cmd/http-tracer.go),
publishes trace entries to pkg/pubsub for `mc admin trace` (admin /trace
endpoint + peer fan-out), and ships structured audit entries to webhook
targets (cmd/logger/audit.go). Here: a middleware recording method/path/
status/duration/caller, an in-process hub, an admin streaming endpoint,
and an optional audit webhook.

Audit shipping runs on ONE bounded-queue worker thread: the old
thread-per-entry model could fork thousands of daemon threads against a
slow webhook; now a full queue drops the entry and counts it
(``minio_tpu_audit_dropped_total``) — audit is best-effort, thread
explosions are not.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request
from typing import Optional

from ..utils import telemetry
from ..utils.pubsub import PubSub

_AUDIT_DROPPED = telemetry.REGISTRY.counter(
    "minio_tpu_audit_dropped_total",
    "Audit entries dropped because the webhook queue was full")


def api_name_of(method: str, path: str, query: dict,
                headers: Optional[dict] = None) -> str:
    """Best-effort S3 API name for one request (the reference tags
    every route with its api name in the router; here the label is
    derived at the HTTP edge so the per-API latency histograms need no
    plumbing through 60 handlers). Unrecognized calls fall into a
    small set of coarse buckets rather than exploding label
    cardinality."""
    headers = headers or {}
    p = path.lstrip("/")
    if path.startswith("/minio/admin"):
        return "Admin"
    if path.startswith("/minio/health"):
        return "Health"
    if path.startswith("/minio/prometheus"):
        return "Metrics"
    if path.startswith("/minio/storage"):
        return "StorageRPC"
    if path.startswith(("/minio/peer", "/minio/lock")):
        return "PeerRPC"
    if path.startswith("/minio/"):
        return "WebUI"
    parts = p.split("/", 1)
    bucket = parts[0]
    key = parts[1] if len(parts) > 1 else ""
    if not bucket:
        return "ListBuckets" if method == "GET" else "STS" \
            if method == "POST" else method
    if key:
        if method == "GET":
            if "uploadId" in query:
                return "ListParts"
            if "tagging" in query:
                return "GetObjectTagging"
            return "GetObject"
        if method == "HEAD":
            return "HeadObject"
        if method == "PUT":
            if "partNumber" in query:
                return "UploadPartCopy" \
                    if "x-amz-copy-source" in headers else "UploadPart"
            if "tagging" in query:
                return "PutObjectTagging"
            if "x-amz-copy-source" in headers:
                return "CopyObject"
            return "PutObject"
        if method == "POST":
            if "uploads" in query:
                return "CreateMultipartUpload"
            if "uploadId" in query:
                return "CompleteMultipartUpload"
            return "PostObject"
        if method == "DELETE":
            if "uploadId" in query:
                return "AbortMultipartUpload"
            return "DeleteObject"
        return method
    # bucket-level
    if method == "GET":
        if "versions" in query:
            return "ListObjectVersions"
        if "uploads" in query:
            return "ListMultipartUploads"
        if query.get("list-type") == ["2"] or \
                query.get("list-type") == "2":
            return "ListObjectsV2"
        sub = next((q for q in ("location", "versioning", "policy",
                                "tagging", "lifecycle", "encryption",
                                "object-lock", "replication",
                                "notification", "events") if q in query),
                   None)
        return f"GetBucket{sub.title().replace('-', '')}" if sub \
            else "ListObjectsV1"
    if method == "PUT":
        return "MakeBucket" if not query else "PutBucketConfig"
    if method == "HEAD":
        return "HeadBucket"
    if method == "DELETE":
        return "DeleteBucket" if not query else "DeleteBucketConfig"
    if method == "POST":
        if "delete" in query:
            return "DeleteMultipleObjects"
        return "PostPolicy"
    return method


class TraceSys:
    def __init__(self, node_name: str = "", ring_size: int = 200,
                 audit_queue_size: int = 512):
        from collections import deque
        self.hub = PubSub()
        self.node = node_name
        self.audit_webhook: str = ""           # POST target for audit
        self.requests_total = 0
        self.errors_total = 0
        self.audit_dropped = 0
        # recent-entry ring: peers pull this for cluster-wide trace
        # (the reference streams over peer REST; a pull ring is the
        # polling equivalent)
        self.recent: "deque[dict]" = deque(maxlen=ring_size)
        self._mu = threading.Lock()
        self._audit_q: "queue.Queue[dict]" = queue.Queue(
            maxsize=audit_queue_size)
        self._audit_worker: Optional[threading.Thread] = None

    # -- middleware --------------------------------------------------------

    def record(self, method: str, path: str, query: str, status: int,
               duration_s: float, caller: str = "",
               api: str = "", trace_id: str = "",
               ttfb_s: Optional[float] = None,
               shed_reason: str = "", tenant: str = "") -> None:
        entry = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "node": self.node,
            "api": api,
            "method": method,
            "path": path,
            "query": query,
            "status": status,
            "duration_ms": round(duration_s * 1e3, 3),
            "caller": caller,
        }
        if ttfb_s is not None:
            entry["ttfb_ms"] = round(ttfb_s * 1e3, 3)
        if shed_reason:
            # which admission signal refused this request (staging /
            # scheduler / admission / conns / deadline) — the trace
            # stream's answer to "why is my client seeing 503s"
            entry["shed_reason"] = shed_reason
        if tenant:
            # the QoS tenant the request resolved to (plane on only) —
            # lets `mc admin trace` split traffic per tenant
            entry["tenant"] = tenant
        if trace_id:
            # the span-tree key: `mc admin trace` output joins to the
            # /minio/admin/v3/spans dump through this id
            entry["trace_id"] = trace_id
        with self._mu:
            self.requests_total += 1
            if status >= 500:
                self.errors_total += 1
            # the ring is read concurrently by the admin trace/cluster
            # pull — mutate it under the same lock as the counters
            self.recent.append(entry)
        if self.hub.subscriber_count:
            self.hub.publish(entry)
        if self.audit_webhook:
            self._enqueue_audit(entry)

    # -- audit worker ------------------------------------------------------

    def _enqueue_audit(self, entry: dict) -> None:
        try:
            self._audit_q.put_nowait(entry)
        except queue.Full:
            with self._mu:
                self.audit_dropped += 1
            _AUDIT_DROPPED.inc()
            return
        if self._audit_worker is None or not self._audit_worker.is_alive():
            with self._mu:
                if self._audit_worker is None or \
                        not self._audit_worker.is_alive():
                    self._audit_worker = threading.Thread(
                        target=self._audit_loop, daemon=True,
                        name="audit-ship")
                    self._audit_worker.start()

    def _audit_loop(self) -> None:
        while True:
            entry = self._audit_q.get()
            try:
                self._ship_audit(entry)
            except Exception:  # noqa: BLE001 — audit is best-effort
                pass

    def _ship_audit(self, entry: dict) -> None:
        try:
            req = urllib.request.Request(
                self.audit_webhook, data=json.dumps(entry).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=3.0) as r:
                r.read()
        except Exception:  # noqa: BLE001 — audit is best-effort
            pass

    # -- admin streaming endpoint -----------------------------------------

    @staticmethod
    def entry_matches(entry: dict, apis: Optional[set] = None,
                      errors_only: bool = False) -> bool:
        """The /trace endpoint's filter semantics (`mc admin trace
        --api ... --errors` analog): `apis` keeps only those API names,
        `errors_only` keeps failed calls (HTTP >= 400)."""
        if apis and entry.get("api") not in apis:
            return False
        if errors_only and int(entry.get("status", 0) or 0) < 400:
            return False
        return True

    @staticmethod
    def _pump_peer(it, q: "queue.Queue", stop: threading.Event) -> None:
        """Reader thread for one peer trace subscription: forwards
        entries into the merge queue until the stream ends or the
        consumer stops. A full queue drops (a slow follow client must
        not apply backpressure to a peer's hub)."""
        try:
            for entry in it:
                if stop.is_set():
                    return
                try:
                    q.put_nowait(entry)
                except queue.Full:
                    pass
        finally:
            it.close()

    def stream(self, max_entries: int = 0, idle_timeout: float = 10.0,
               follow: bool = False, apis: Optional[set] = None,
               errors_only: bool = False, peer_subs=None,
               max_s: float = 3600.0):
        """JSON-line trace entries as they happen (admin /trace).

        Default mode ends after `idle_timeout` with no traffic or
        `max_entries` sent (the PR 3 behavior). `follow` mode is the
        `mc admin trace` analog: a long-lived stream that survives idle
        windows by emitting bare-newline heartbeats — which double as
        the disconnect detector: a dead client's next heartbeat write
        fails, unwinding the whole subscription (peers included)
        instead of leaking a worker. `peer_subs` grafts every node's
        records into this one stream: a CALLABLE returning the peer
        iterators (PeerRPCClient trace_stream) — called lazily at the
        generator's first iteration, so a response abandoned before
        its first chunk (client reset during the head write) never
        opens a peer subscription it could not unwind; each iterator
        gets a daemon pump thread that dies with the stream. `max_s`
        hard-caps a FOLLOW stream's life (non-follow keeps its
        idle/count bounds)."""
        q: "queue.Queue[dict]" = queue.Queue(maxsize=1000)
        stop = threading.Event()

        def gen():
            subs = list(peer_subs() if callable(peer_subs)
                        else peer_subs or [])
            for it in subs:
                threading.Thread(target=self._pump_peer,
                                 args=(it, q, stop), daemon=True,
                                 name="trace-follow-peer").start()
            sent = 0
            now = time.monotonic()
            deadline = now + max_s if follow else float("inf")
            last_entry = now
            last_beat = now
            try:
                with self.hub.subscribe() as sub:
                    while time.monotonic() < deadline:
                        got = []
                        if follow or subs:
                            # heartbeat cadence / peer-queue drain
                            # need sub-second wakeups
                            timeout = 0.25
                        else:
                            # plain bounded stream: block the whole
                            # remaining idle window in ONE get (no
                            # 4 Hz wakeup churn on an idle server)
                            timeout = (last_entry + idle_timeout
                                       - time.monotonic())
                            if timeout <= 0:
                                return
                        entry = sub.get(timeout=timeout)
                        if entry is not None:
                            got.append(entry)
                        while True:
                            try:
                                got.append(q.get_nowait())
                            except queue.Empty:
                                break
                        now = time.monotonic()
                        for e in got:
                            if not self.entry_matches(e, apis,
                                                      errors_only):
                                continue
                            yield (json.dumps(e) + "\n").encode()
                            # idle counts from the last MATCHED entry:
                            # steady non-matching traffic must not
                            # keep a filtered non-follow stream (which
                            # never writes, so never detects a dead
                            # client) alive forever
                            last_entry = now
                            last_beat = now
                            sent += 1
                            if max_entries and sent >= max_entries:
                                return
                        if follow:
                            if now - last_beat >= 1.0:
                                yield b"\n"       # liveness + hangup probe
                                last_beat = now
                        elif now - last_entry >= idle_timeout:
                            return
            finally:
                stop.set()
                for it in subs:
                    it.close()

        return gen()
