"""Admin API + healthcheck + Prometheus metrics routers.

The reference's /minio/admin/v3 surface (cmd/admin-handlers*.go,
cmd/admin-router.go), /minio/health/{live,ready,cluster}
(cmd/healthcheck-*.go) and /minio/prometheus/metrics (cmd/metrics.go),
mounted as extra routers on the S3 server. Admin calls are SigV4-
authenticated: the root credential, or an IAM identity whose policies
allow the admin:* action.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import uuid
from typing import Optional

from . import signature as sig
# imported at module scope so their metric families/collectors are
# registered as soon as the admin plane exists (each registers on
# import: minio_tpu_profiler_running{kind=...}, minio_tpu_sched_*,
# minio_tpu_rpc_*)
from ..distributed import transport as _transport  # noqa: F401
from ..parallel import scheduler as _scheduler  # noqa: F401
from ..utils import knobs, telemetry
from ..utils import profiling as _profiling  # noqa: F401
from .handlers import HTTPResponse, RequestContext
from .s3errors import S3Error

ADMIN_PREFIX = "/minio/admin/v3"
HEALTH_PREFIX = "/minio/health"
METRICS_PREFIX = "/minio/prometheus/metrics"

# federated-scrape degradation accounting: a peer that missed the
# per-peer deadline (or is down) costs its samples, never the scrape —
# this counter is the alert an operator wires to notice
_SCRAPE_FAILED = telemetry.REGISTRY.counter(
    "minio_tpu_cluster_scrape_failed_total",
    "Peer scrapes that failed during a federated ?cluster=1 metrics "
    "render")


class HealSequence:
    """One background heal run, queryable by token
    (cmd/admin-heal-ops.go healSequence)."""

    def __init__(self, object_layer, bucket: str, prefix: str):
        self.token = str(uuid.uuid4())
        self.bucket = bucket
        self.prefix = prefix
        self.status = "running"
        self.items_scanned = 0
        self.items_healed = 0
        self.failures = 0
        self.started = time.time()
        self._obj = object_layer
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self) -> None:
        from ..object import api_errors
        try:
            buckets = ([self.bucket] if self.bucket else
                       [v.name for v in self._obj.list_buckets()])
            for b in buckets:
                try:
                    self._obj.heal_bucket(b)
                except api_errors.ObjectApiError:
                    pass
                marker = ""
                while True:
                    objs, _, trunc = self._obj.list_objects(
                        b, self.prefix, marker, "", 1000)
                    for oi in objs:
                        self.items_scanned += 1
                        try:
                            self._obj.heal_object(b, oi.name)
                            self.items_healed += 1
                        except api_errors.ObjectApiError:
                            self.failures += 1
                    if not trunc or not objs:
                        break
                    marker = objs[-1].name
            self.status = "done"
        except Exception:  # noqa: BLE001 — surfaced via status
            self.status = "failed"

    def to_dict(self) -> dict:
        return {"token": self.token, "status": self.status,
                "bucket": self.bucket, "prefix": self.prefix,
                "items_scanned": self.items_scanned,
                "items_healed": self.items_healed,
                "failures": self.failures,
                "elapsed": round(time.time() - self.started, 3)}


class AdminHandlers:
    """Router for /minio/admin/v3/* (mount via S3Server extra routers)."""

    def __init__(self, api, node=None):
        """api: S3ApiHandlers; node: optional ClusterNode (peer plane)."""
        self.api = api
        self.node = node
        self.started = time.time()
        self._heals: dict[str, HealSequence] = {}
        # the metrics endpoint's handler (mount_admin wires it): the
        # admin /metrics route and the peer metrics-text verb both
        # render through it so every surface reports the SAME scrape
        self.metrics: Optional["MetricsHandler"] = None

    # -- auth --------------------------------------------------------------

    def _auth(self, ctx: RequestContext, action: str) -> None:
        at = ctx.auth_type
        if at not in (sig.AUTH_SIGNED, sig.AUTH_PRESIGNED):
            raise S3Error("AccessDenied")
        if at == sig.AUTH_SIGNED:
            body_sha = ctx.header("x-amz-content-sha256",
                                  sig.UNSIGNED_PAYLOAD)
            cred = sig.verify_v4(ctx.req, self.api._cred_lookup,
                                 self.api.region, body_sha)
        else:
            cred = sig.verify_v4_presigned(ctx.req, self.api._cred_lookup,
                                           self.api.region)
        if cred.is_temp():
            # STS credentials must present their session token, same as
            # the S3 authenticate path — a leaked access/secret pair
            # alone must not authorize admin calls.
            token = ctx.header("x-amz-security-token") or \
                ctx.query1("X-Amz-Security-Token")
            if token != cred.session_token:
                raise S3Error("AccessDenied", "invalid security token")
        if cred.access_key == self.api.root_cred.access_key or \
                cred.parent_user == self.api.root_cred.access_key:
            return
        if self.api.iam is not None and self.api.iam.is_allowed(
                cred, action, "", "",
                self.api._policy_conditions(ctx)):
            return
        raise S3Error("AccessDenied")

    # -- dispatch ----------------------------------------------------------

    def route(self, ctx: RequestContext) -> HTTPResponse:
        try:
            return self._route(ctx)
        except S3Error as e:
            return HTTPResponse(
                status=e.status,
                body=json.dumps({"Code": e.code,
                                 "Message": e.message}).encode(),
                headers={"Content-Type": "application/json"})
        except sig.SigError as e:
            return HTTPResponse(
                status=403,
                body=json.dumps({"Code": e.code}).encode(),
                headers={"Content-Type": "application/json"})

    def _route(self, ctx: RequestContext) -> HTTPResponse:
        path = urllib.parse.unquote(ctx.req.path)
        sub = path[len(ADMIN_PREFIX):].strip("/")
        m = ctx.req.method

        if sub == "info" and m == "GET":
            self._auth(ctx, "admin:ServerInfo")
            return self._json(self.server_info())
        if sub == "storageinfo" and m == "GET":
            self._auth(ctx, "admin:StorageInfo")
            return self._json(self.api.obj.storage_info())
        if sub == "datausageinfo" and m == "GET":
            self._auth(ctx, "admin:DataUsageInfo")
            usage = self.api.usage.usage if self.api.usage is not None \
                else {}
            return self._json(usage)
        if sub == "top/locks" and m == "GET":
            self._auth(ctx, "admin:TopLocksInfo")
            return self._json(self.top_locks())
        if sub == "profiling/start" and m == "POST":
            self._auth(ctx, "admin:Profiling")
            return self._json(self._profiling_start(
                ctx.query1("profilerType", "cpu")))
        if sub == "profiling/stop" and m == "POST":
            self._auth(ctx, "admin:Profiling")
            return self._profiling_stop(
                ctx.query1("profilerType", "cpu"))
        if sub == "consolelog" and m == "GET":
            self._auth(ctx, "admin:ConsoleLog")
            try:
                n = int(ctx.query1("count", "0") or 0)
            except ValueError:
                n = 0
            from ..utils.console import get_console
            entries = list(get_console().recent(n))
            if self.node is not None:
                entries.extend(self.node.notification.console_log_all(n))
            entries.sort(key=lambda e: e.get("ts", 0))
            return self._json({"entries": entries[-1000:]})
        if sub == "bandwidth" and m == "GET":
            self._auth(ctx, "admin:BandwidthMonitor")
            from ..utils.bandwidth import merge_reports
            reports = [self.api.bandwidth.report()]
            if self.node is not None:
                reports.extend(self.node.notification.bandwidth_all())
            return self._json({"buckets": merge_reports(reports)})
        if sub == "drivehealth" and m == "GET":
            # the gray-failure plane's state: per-drive / per-peer
            # latency summaries, quarantine states, recent transitions
            self._auth(ctx, "admin:OBDInfo")
            from ..utils import healthtrack
            events: list = []
            node = self.node
            mon = getattr(node, "disk_monitor", None) \
                if node is not None else None
            if mon is not None:
                events = [{"drive": k, "event": e}
                          for k, e in list(mon.quarantine_events)[-100:]]
            from ..utils import eventlog
            return self._json({
                "drives": healthtrack.TRACKER.snapshot("drive"),
                "peers": healthtrack.TRACKER.snapshot("peer"),
                "events": events,
                # journal-backed transition history: replayed from
                # persisted segments at boot, so convictions survive a
                # restart (the in-memory deque above does not)
                "journal": eventlog.JOURNAL.recent(
                    100, subsystems={"drive", "health"})})
        if sub == "obdinfo" and m == "GET":
            self._auth(ctx, "admin:OBDInfo")
            from ..utils.obd import local_obd
            drives = list(self.node.spec.drives) \
                if self.node is not None else []
            # live StorageAPI objects (any wrapper depth) for the
            # per-drive fault counters; duck-typed — FS/gateway layers
            # have no erasure sets and report none
            storage_drives: list = []
            layers = getattr(self.api.obj, "server_sets", None) \
                or [self.api.obj]
            for layer in layers:
                for eng in getattr(layer, "sets", None) or []:
                    storage_drives.extend(eng.disks)
            nodes = [local_obd(drives,
                               storage_drives=storage_drives or None)]
            net: list = []
            if self.node is not None:
                nodes[0]["node"] = self.node.spec.addr
                nodes.extend(self.node.notification.obd_all())
                # internode throughput/RTT from this node's viewpoint
                # (cmd/obdinfo.go net perf; size kept small so the
                # bundle stays interactive)
                net = self.node.notification.net_obd(size=1 << 20)
            return self._json({"nodes": nodes, "net": net})
        if sub == "trace/cluster" and m == "GET":
            self._auth(ctx, "admin:ServerTrace")
            entries = list(self.api.trace.recent)
            if self.node is not None:
                entries.extend(self.node.notification.trace_all())
            entries.sort(key=lambda e: e.get("time", ""))
            return self._json({"entries": entries[-500:]})
        if sub == "metrics" and m == "GET":
            # authenticated metrics scrape; ?cluster=1 federates over
            # peer RPC into ONE exposition (counters summed, gauges
            # node-labelled, histograms bucket-merged) — the reference
            # /minio/v2/metrics/cluster analog
            self._auth(ctx, "admin:Prometheus")
            if self.metrics is None:
                raise S3Error("NotImplemented",
                              "metrics handler not mounted")
            if ctx.query1("cluster") == "1" and self.node is not None:
                text = self.cluster_metrics_text()
            else:
                text = self.metrics.local_text()
            return HTTPResponse(body=text.encode(),
                                headers={"Content-Type": "text/plain"})
        if sub == "spans" and m == "GET":
            # tail-sampled span trees (errors, slow requests, sampled
            # ordinary traffic), RPC fragments grafted in — the "where
            # did this slow PUT spend its time" endpoint. ?api= keeps
            # one API's roots (root names ARE api names under the
            # middleware), ?trace_id= selects the tree a trace-stream
            # entry named.
            self._auth(ctx, "admin:ServerTrace")
            try:
                n = int(ctx.query1("count", "50") or 50)
            except ValueError:
                raise S3Error("AdminInvalidArgument",
                              "bad count") from None
            slowest = ctx.query1("sort", "recent") == "slowest"
            return self._json({
                "spans": telemetry.SPANS.dump(
                    n, slowest=slowest, name=ctx.query1("api", ""),
                    trace_id=ctx.query1("trace_id", "")),
                "kept_total": telemetry.SPANS.kept_total,
                "dropped_total": telemetry.SPANS.dropped_total,
                "slow_threshold_ms": round(
                    telemetry.SPANS.slow_s * 1e3, 3),
                "sample": telemetry.SPANS.sample,
            })
        if sub == "trace" and m == "GET":
            # live ND-JSON request records. Default: bounded stream
            # that ends on idle (PR 3). ?follow=1 is the `mc admin
            # trace` analog — a long-lived stream with heartbeats, and
            # (on a cluster node) every PEER's records grafted in via
            # trace-stream subscriptions, so one client watches the
            # whole cluster. ?api=PutObject,GetObject and ?err=1
            # filter; filters apply to peer records too.
            self._auth(ctx, "admin:ServerTrace")
            follow = ctx.query1("follow", "") in ("1", "true")
            apis = {a for a in ctx.query1("api", "").split(",") if a} \
                or None
            errors_only = ctx.query1("err", "") in ("1", "true")
            try:
                n = int(ctx.query1("count", "0") or 0)
                idle = float(ctx.query1("idle", "10") or 10)
            except ValueError:
                raise S3Error("AdminInvalidArgument",
                              "bad count/idle") from None
            idle = min(max(idle, 1.0), 3600.0)
            max_s = knobs.get_float("MINIO_TPU_TRACE_FOLLOW_MAX_S")
            peer_subs = None
            if follow and self.node is not None:
                # a CALLABLE: the subscriptions open at the stream's
                # first iteration, so a response abandoned before its
                # first chunk never opens peers it cannot close
                node = self.node
                peer_subs = (lambda:
                             node.notification.trace_stream_all(
                                 max_s=max_s))
            return HTTPResponse(
                headers={"Content-Type": "application/x-ndjson"},
                stream=self.api.trace.stream(
                    max_entries=n, idle_timeout=idle, follow=follow,
                    apis=apis, errors_only=errors_only,
                    peer_subs=peer_subs, max_s=max_s),
                long_poll=follow)
        if sub == "events" and m == "GET":
            # the incident plane's journal. Default: the recent ring
            # window as JSON (?cluster=1 merges peer windows, deduped
            # by (node, seq) — in-process test clusters share one
            # journal). ?follow=1 streams ND-JSON live with peer
            # grafting — same contract (and lazy-subscription lesson)
            # as /trace?follow=1. Filters: ?class=a,b ?sub=drive,net
            # ?sev=warn (minimum severity); they apply to peer
            # entries too.
            self._auth(ctx, "admin:ServerTrace")
            from ..utils import eventlog
            classes = {c for c in ctx.query1("class", "").split(",")
                       if c} or None
            subsys = {s for s in ctx.query1("sub", "").split(",")
                      if s} or None
            sev = ctx.query1("sev", "")
            min_sev = eventlog.sev_rank(sev) if sev else 0
            follow = ctx.query1("follow", "") in ("1", "true")
            try:
                n = int(ctx.query1("count", "0") or 0)
                idle = float(ctx.query1("idle", "10") or 10)
            except ValueError:
                raise S3Error("AdminInvalidArgument",
                              "bad count/idle") from None
            if follow:
                idle = min(max(idle, 1.0), 3600.0)
                max_s = knobs.get_float(
                    "MINIO_TPU_EVENTS_FOLLOW_MAX_S")
                peer_subs = None
                if self.node is not None:
                    # a CALLABLE: subscriptions open at the stream's
                    # first iteration, so a response abandoned before
                    # its first chunk never opens peers it cannot
                    # close
                    node = self.node
                    peer_subs = (lambda:
                                 node.notification.event_stream_all(
                                     max_s=max_s))
                return HTTPResponse(
                    headers={"Content-Type":
                             "application/x-ndjson"},
                    stream=eventlog.JOURNAL.stream(
                        max_entries=n, idle_timeout=idle,
                        follow=True, classes=classes,
                        subsystems=subsys, min_sev=min_sev,
                        peer_subs=peer_subs, max_s=max_s),
                    long_poll=True)
            entries = eventlog.JOURNAL.recent(n, classes, subsys,
                                              min_sev)
            if ctx.query1("cluster") == "1" and self.node is not None:
                seen = {(e.get("node"), e.get("seq"))
                        for e in entries}
                for e in self.node.notification.events_all():
                    k = (e.get("node"), e.get("seq"))
                    if k in seen:
                        continue
                    if eventlog.JOURNAL.entry_matches(
                            e, classes, subsys, min_sev):
                        seen.add(k)
                        entries.append(e)
                entries.sort(key=lambda e: e.get("ts", 0))
            return self._json({"events": entries[-1000:]})
        if sub == "incidents" and m == "GET":
            # black-box capture bundles. ?id= fetches one bundle —
            # asking every peer when it is not local (bundles live on
            # the node that captured them); default lists summaries,
            # ?cluster=1 merging peer lists.
            self._auth(ctx, "admin:OBDInfo")
            from ..utils import incidents as inc_mod
            inc_id = ctx.query1("id", "")
            if inc_id:
                doc = inc_mod.RECORDER.get(inc_id)
                if doc is None and self.node is not None:
                    doc = self.node.notification.incident_any(inc_id)
                if doc is None:
                    raise S3Error("AdminInvalidArgument",
                                  "unknown incident id")
                return self._json(doc)
            out = inc_mod.RECORDER.list()
            if ctx.query1("cluster") == "1" and self.node is not None:
                have = {i.get("id") for i in out}
                for i in self.node.notification.incidents_all():
                    if i.get("id") not in have:
                        have.add(i.get("id"))
                        out.append(i)
                out.sort(key=lambda i: i.get("time") or 0,
                         reverse=True)
            return self._json({"incidents": out})
        if sub == "slo" and m == "GET":
            # burn-rate status per objective — what `mc admin` would
            # render as the error-budget dashboard
            self._auth(ctx, "admin:ServerInfo")
            from ..utils import slo
            return self._json(slo.ENGINE.status())

        if sub == "heal" and m == "POST":
            self._auth(ctx, "admin:Heal")
            bucket = ctx.query1("bucket")
            prefix = ctx.query1("prefix")
            seq = HealSequence(self.api.obj, bucket, prefix)
            self._heals[seq.token] = seq
            return self._json({"token": seq.token})
        if sub == "heal/status" and m == "GET":
            self._auth(ctx, "admin:Heal")
            seq = self._heals.get(ctx.query1("token"))
            if seq is None:
                raise S3Error("AdminInvalidArgument", "unknown heal token")
            return self._json(seq.to_dict())
        if sub == "mrf" and m == "GET":
            # MRF ("most recently failed") heal-queue stats: pending /
            # healed / requeued / failed / dropped per backend that has
            # a queue (erasure sets and zones; FS/gateway report {})
            self._auth(ctx, "admin:Heal")
            fn = getattr(self.api.obj, "mrf_stats", None)
            return self._json(fn() if callable(fn) else {})
        if sub == "fsck" and m in ("GET", "POST"):
            # crash-consistency auditor (object/fsck.py): GET audits,
            # POST audits AND repairs (repairable classes feed the
            # heal/delete/rebuild machinery; lost data is reported).
            # ?bucket= narrows, ?tmp_age=0 treats ALL staged tmp as
            # stale (boot/harness mode — nothing can be in flight)
            self._auth(ctx, "admin:Heal")
            from ..object.fsck import run_fsck
            bucket = ctx.query1("bucket")
            try:
                age = float(ctx.query1("tmp_age", "-1") or -1)
            except ValueError:
                raise S3Error("AdminInvalidArgument",
                              "bad tmp_age") from None
            report = run_fsck(self.api.obj, repair=(m == "POST"),
                              tiers=self.api.tiers,
                              buckets=[bucket] if bucket else None,
                              tmp_age_s=age if age >= 0 else None)
            return self._json(report.to_dict())
        if sub == "naughtynet" and m == "POST":
            # test-only network chaos control (distributed/naughtynet):
            # the proc harness partitions/heals/configures a LIVE node's
            # fault injector from outside the process. Gated off by
            # default — a production node must not expose a verb that
            # severs its own links
            self._auth(ctx, "admin:ServerUpdate")
            from ..utils import knobs as _knobs
            if not _knobs.get_bool("MINIO_TPU_NAUGHTYNET"):
                raise S3Error(
                    "NotImplemented",
                    "network chaos is disabled "
                    "(MINIO_TPU_NAUGHTYNET=on enables this verb)")
            from ..distributed import naughtynet as _nn
            try:
                payload = json.loads(ctx.read_body().decode() or "{}")
                return self._json(_nn.handle_admin(payload))
            except (ValueError, TypeError) as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
        if sub == "metacache" and m == "GET":
            # bucket metacache visibility (ROADMAP item 2 `mc.stats()`
            # remainder): per-bucket index state (entries, building/
            # ready, invalid, dirty names, generation), pending journal
            # deltas, and the serve/fallback/drop/reconcile counters —
            # ?bucket= narrows to one bucket's entry
            self._auth(ctx, "admin:ServerInfo")
            mc = getattr(self.api.obj, "metacache", None)
            if mc is None:
                return self._json({"enabled": False})
            st = mc.stats()
            st["enabled"] = True
            bucket = ctx.query1("bucket")
            if bucket:
                st["buckets"] = {b: v for b, v in st["buckets"].items()
                                 if b == bucket}
            return self._json(st)

        # -- topology plane: pool states, decommission, rebalance ----------
        if sub == "rebalance" and m == "POST":
            # start draining a pool: its objects migrate to the active
            # pools in the background (upstream decommission start)
            self._auth(ctx, "admin:Rebalance")
            try:
                pool = int(ctx.query1("pool", "-1"))
            except ValueError:
                raise S3Error("AdminInvalidArgument",
                              "bad pool index") from None
            return self._json(self._topology_call(
                "start_decommission", pool))
        if sub == "rebalance" and m == "GET":
            self._auth(ctx, "admin:Rebalance")
            return self._json(self._topology_call("rebalance_status"))
        if sub == "rebalance" and m == "DELETE":
            self._auth(ctx, "admin:Rebalance")
            return self._json(self._topology_call("cancel_rebalance"))
        if sub == "topology" and m == "GET":
            self._auth(ctx, "admin:Rebalance")
            topo = getattr(self.api.obj, "topology", None)
            if topo is None:
                raise S3Error("NotImplemented",
                              "backend has no pool topology")
            return self._json(topo.to_dict())
        if sub == "topology" and m == "POST":
            # suspend/resume a pool for writes without draining it
            self._auth(ctx, "admin:Rebalance")
            try:
                pool = int(ctx.query1("pool", "-1"))
            except ValueError:
                raise S3Error("AdminInvalidArgument",
                              "bad pool index") from None
            state = ctx.query1("state", "")
            epoch = self._topology_call("set_pool_state", pool, state)
            return self._json({"pool": pool, "state": state,
                               "epoch": epoch})

        # -- tiering plane: remote tier registry (cmd/tier-handlers.go) ----
        if sub == "tier" and m == "GET":
            self._auth(ctx, "admin:ListTier")
            tiers = self._tiers()
            return self._json({"epoch": tiers.epoch,
                               "tiers": tiers.list(redact=True)})
        if sub == "tier" and m == "PUT":
            # add (or with ?force=true update) one named remote tier
            self._auth(ctx, "admin:SetTier")
            from ..tier.config import TierConfig, TierConfigError
            try:
                body = json.loads(ctx.read_body().decode() or "{}")
                cfg = TierConfig.from_dict(body)
            except (ValueError, TierConfigError) as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            update = ctx.query1("force", "") == "true"
            try:
                epoch = self._tiers().add(cfg, update=update)
            except TierConfigError as e:
                code = "XMinioAdminTierAlreadyExists" \
                    if "already exists" in str(e) \
                    else "AdminInvalidArgument"
                raise S3Error(code, str(e)) from None
            return self._json({"name": cfg.name, "epoch": epoch})
        if sub == "tier" and m == "DELETE":
            self._auth(ctx, "admin:SetTier")
            from ..object import api_errors as _oerr
            name = ctx.query1("name", "")
            # removing a tier that lifecycle rules still reference
            # strands every transitioned stub behind an unrestorable
            # pointer — refuse unless ?force=true
            if ctx.query1("force", "") != "true" and \
                    self._tier_in_use(name):
                raise S3Error(
                    "XMinioAdminTierBackendInUse",
                    f"tier {name!r} is referenced by a lifecycle "
                    "Transition rule; detach the rule or pass "
                    "force=true")
            try:
                epoch = self._tiers().remove(name)
            except _oerr.TierNotFound:
                raise S3Error("XMinioAdminTierNotFound", name) from None
            return self._json({"name": name, "epoch": epoch})
        if sub == "tier/stats" and m == "GET":
            # transition-worker queue/throughput counters (the madmin
            # tier-status surface)
            self._auth(ctx, "admin:ListTier")
            worker = getattr(self.node, "transition_worker", None) \
                if self.node is not None else None
            return self._json(worker.stats() if worker is not None
                              else {})

        # -- multi-tenant QoS plane: budget registry (s3/qos.py) -----------
        if sub == "qos" and m == "GET":
            self._auth(ctx, "admin:ListQoS")
            qos = self.api.qos
            return self._json({
                "enabled": qos.enabled(),
                "epoch": qos.registry.epoch,
                "tenants": qos.registry.list("tenant"),
                "tiers": qos.registry.list("tier"),
                "stats": qos.stats()})
        if sub == "qos" and m == "PUT":
            # set (or replace) one tenant/tier budget
            self._auth(ctx, "admin:SetQoS")
            from .qos import Budget, QoSConfigError
            try:
                body = json.loads(ctx.read_body().decode() or "{}")
                scope = str(body.pop("scope", "tenant"))
                budget = Budget.from_dict(body)
                epoch = self.api.qos.registry.set_budget(scope, budget)
            except (ValueError, QoSConfigError) as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            return self._json({"scope": scope, "name": budget.name,
                               "epoch": epoch})
        if sub == "qos" and m == "DELETE":
            self._auth(ctx, "admin:SetQoS")
            from .qos import QoSConfigError
            scope = ctx.query1("scope", "tenant")
            name = ctx.query1("name", "")
            try:
                epoch = self.api.qos.registry.remove_budget(scope, name)
            except QoSConfigError as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            return self._json({"scope": scope, "name": name,
                               "epoch": epoch})

        # -- config KV (cmd/admin-handlers-config-kv.go) -------------------
        if sub == "get-config" and m == "GET":
            self._auth(ctx, "admin:ConfigUpdate")
            return self._json(self._config().dump())
        if sub == "set-config" and m == "PUT":
            self._auth(ctx, "admin:ConfigUpdate")
            subsys = ctx.query1("subsys")
            kv = json.loads(ctx.read_body().decode() or "{}")
            cfg = self._config()
            from ..config import kv as _kvmod
            try:
                cfg.set_kv(subsys, **{k: str(v) for k, v in kv.items()})
            except _kvmod.ConfigError as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            cfg.apply(self.api, events=self.api.events,
                      trace=self.api.trace)
            return self._json({})
        if sub == "config-history" and m == "GET":
            self._auth(ctx, "admin:ConfigUpdate")
            return self._json({"entries": self._config().history()})
        if sub == "restore-config" and m == "PUT":
            self._auth(ctx, "admin:ConfigUpdate")
            cfg = self._config()
            cfg.restore(ctx.query1("entry"))
            cfg.apply(self.api, events=self.api.events,
                      trace=self.api.trace)
            return self._json({})

        # -- IAM management (cmd/admin-handlers-users.go) ------------------
        if sub == "add-user" and m == "PUT":
            self._auth(ctx, "admin:CreateUser")
            body = json.loads(ctx.read_body().decode() or "{}")
            self._iam().add_user(ctx.query1("accessKey"),
                                 body.get("secretKey", ""),
                                 body.get("status", "on"))
            return self._json({})
        if sub == "remove-user" and m == "DELETE":
            self._auth(ctx, "admin:DeleteUser")
            self._iam().remove_user(ctx.query1("accessKey"))
            return self._json({})
        if sub == "list-users" and m == "GET":
            self._auth(ctx, "admin:ListUsers")
            return self._json({"users": self._iam().list_users()})
        if sub == "set-user-status" and m == "PUT":
            self._auth(ctx, "admin:EnableUser")
            self._iam().set_user_status(ctx.query1("accessKey"),
                                        ctx.query1("status"))
            return self._json({})
        if sub == "add-canned-policy" and m == "PUT":
            self._auth(ctx, "admin:CreatePolicy")
            from ..iam.policy import Policy
            self._iam().set_policy(
                ctx.query1("name"),
                Policy.from_json(ctx.read_body().decode()))
            return self._json({})
        if sub == "remove-canned-policy" and m == "DELETE":
            self._auth(ctx, "admin:DeletePolicy")
            self._iam().delete_policy(ctx.query1("name"))
            return self._json({})
        if sub == "list-canned-policies" and m == "GET":
            self._auth(ctx, "admin:ListUserPolicies")
            return self._json({
                "policies": sorted(self._iam().policies)})
        if sub == "set-user-or-group-policy" and m == "PUT":
            self._auth(ctx, "admin:AttachUserOrGroupPolicy")
            self._iam().attach_policy(
                ctx.query1("policyName"),
                user=ctx.query1("userOrGroup")
                if ctx.query1("isGroup") != "true" else "",
                group=ctx.query1("userOrGroup")
                if ctx.query1("isGroup") == "true" else "")
            return self._json({})
        if sub == "service" and m == "POST":
            self._auth(ctx, "admin:ServiceRestart")
            action = ctx.query1("action", "")
            if action not in ("restart", "stop"):
                raise S3Error("AdminInvalidArgument",
                              f"unknown service action {action!r}")
            if self.node is not None:
                self.node.notification.signal_all(action)
            # defer the local action so this response reaches the client
            # (reference cmd/service.go restarts via exec after reply)
            import threading as _threading
            _threading.Timer(0.2, self.service_action, (action,)).start()
            return self._json({"status": "success", "action": action})
        if sub == "set-bucket-quota" and m == "PUT":
            self._auth(ctx, "admin:SetBucketQuota")
            bucket = ctx.query1("bucket", "")
            self._require_bucket(bucket)
            body = json.loads(ctx.read_body().decode() or "{}")
            quota = int(body.get("quota", 0))
            qtype = (body.get("quotatype") or body.get("type")
                     or "hard").lower()
            if quota < 0 or qtype not in ("hard", "fifo"):
                raise S3Error("AdminInvalidArgument", "bad quota spec")
            self.api.bucket_meta.update(
                bucket, quota={"quota": quota, "type": qtype}
                if quota else {})
            return self._json({})
        if sub == "get-bucket-quota" and m == "GET":
            self._auth(ctx, "admin:GetBucketQuota")
            bucket = ctx.query1("bucket", "")
            return self._json(
                self.api.bucket_meta.get(bucket).quota or {})
        if sub == "replicate" and m == "GET":
            self._auth(ctx, "admin:ReplicationInfo")
            plane = self._repl_plane()
            out = {"site": plane.registry.site_id,
                   "epoch": plane.registry.epoch,
                   "targets": plane.registry.list(redact=True),
                   "stats": plane.stats(),
                   # per-target lag (ROADMAP item 4 remainder): queue
                   # depth, oldest-pending age, last-sync timestamp —
                   # the JSON twin of minio_tpu_repl_lag_seconds{target}
                   "targets_status": plane.target_status()}
            rs = plane.resync_status()
            if rs:
                out["resync"] = rs
            return self._json(out)
        if sub == "replicate/key" and m == "GET":
            # the peer-sync read: every version of one key as replayable
            # specs (HTTPReplClient.key_versions' server side)
            self._auth(ctx, "admin:ReplicationInfo")
            from ..object import api_errors as oerr
            from ..object.faithful import spec_of
            bucket = ctx.query1("bucket", "")
            key = ctx.query1("key", "")
            if not bucket or not key:
                raise S3Error("AdminInvalidArgument",
                              "bucket and key are required")
            site = ""
            repl = self.api.replication
            if repl is not None and hasattr(repl, "registry"):
                site = repl.registry.site_id
            try:
                versions = self.api.obj.object_versions(bucket, key)
            except oerr.ObjectApiError:
                versions = []
            return self._json({"site": site,
                               "versions": [spec_of(v).to_dict()
                                            for v in versions]})
        if sub == "replicate/target" and m == "PUT":
            self._auth(ctx, "admin:SetBucketTarget")
            from ..replicate.targets import (ReplTargetError, SiteTarget,
                                             new_arn)
            plane = self._repl_plane()
            body = json.loads(ctx.read_body().decode() or "{}")
            if not body.get("bucket"):
                raise S3Error("AdminInvalidArgument",
                              "bucket is required")
            self._require_bucket(body["bucket"])
            body.setdefault("arn",
                            new_arn(body.get("dest_bucket")
                                    or body["bucket"]))
            try:
                target = SiteTarget.from_dict(body)
                plane.registry.add(
                    target, update=ctx.query1("update") == "true")
            except ReplTargetError as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            return self._json({"arn": target.arn,
                               "epoch": plane.registry.epoch})
        if sub == "replicate/target" and m == "DELETE":
            self._auth(ctx, "admin:SetBucketTarget")
            from ..replicate.targets import ReplTargetError
            plane = self._repl_plane()
            try:
                plane.remove_target(ctx.query1("arn", ""))
            except ReplTargetError as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            return self._json({})
        if sub == "replicate/resync" and m == "POST":
            self._auth(ctx, "admin:ReplicationResync")
            from ..replicate.client import ReplClientError
            from ..replicate.targets import ReplTargetError
            plane = self._repl_plane()
            try:
                r = plane.start_resync(ctx.query1("arn", ""))
            except (ReplClientError, ReplTargetError) as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            return self._json(r.status())
        if sub == "replicate/resync" and m == "GET":
            self._auth(ctx, "admin:ReplicationInfo")
            return self._json(self._repl_plane().resync_status() or {})
        if sub == "replicate/resync" and m == "DELETE":
            self._auth(ctx, "admin:ReplicationResync")
            return self._json(
                {"canceled": self._repl_plane().cancel_resync()})
        if sub == "notify" and m == "GET":
            self._auth(ctx, "admin:ServerInfo")
            plane = self._notify_plane()
            return self._json(
                {"epoch": plane.registry.epoch,
                 "targets": plane.registry.list(redact=True),
                 "stats": plane.stats(),
                 # per-target delivery state: backlog depth, offline
                 # window, last delivery lag — the JSON twin of
                 # minio_tpu_notify_lag_seconds{target}
                 "targets_status": plane.target_status()})
        if sub == "notify/target" and m == "PUT":
            self._auth(ctx, "admin:SetBucketTarget")
            from ..notify.targets import (NotifyTarget, NotifyTargetError,
                                          new_arn)
            plane = self._notify_plane()
            body = json.loads(ctx.read_body().decode() or "{}")
            body.setdefault("arn", new_arn(body.pop("name", ""),
                                           body.get("type", "webhook")))
            try:
                target = NotifyTarget.from_dict(body)
                plane.registry.add(
                    target, update=ctx.query1("update") == "true")
            except NotifyTargetError as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            if plane.reload_peers is not None:
                plane.reload_peers()
            return self._json({"arn": target.arn,
                               "epoch": plane.registry.epoch})
        if sub == "notify/target" and m == "DELETE":
            self._auth(ctx, "admin:SetBucketTarget")
            from ..notify.targets import NotifyTargetError
            plane = self._notify_plane()
            try:
                plane.registry.remove(ctx.query1("arn", ""))
            except NotifyTargetError as e:
                raise S3Error("AdminInvalidArgument", str(e)) from None
            if plane.reload_peers is not None:
                plane.reload_peers()
            return self._json({})
        if sub == "set-remote-target" and m == "PUT":
            self._auth(ctx, "admin:SetBucketTarget")
            return self._set_remote_target(ctx)
        if sub == "list-remote-targets" and m == "GET":
            self._auth(ctx, "admin:GetBucketTarget")
            bucket = ctx.query1("bucket", "")
            targets = self.api.bucket_meta.get(
                bucket).replication_targets
            return HTTPResponse(
                body=json.dumps([{k: v for k, v in t.items()
                                  if k != "secret_key"}
                                 for t in targets]).encode(),
                headers={"Content-Type": "application/json"})
        if sub == "remove-remote-target" and m == "DELETE":
            self._auth(ctx, "admin:SetBucketTarget")
            bucket = ctx.query1("bucket", "")
            arn = ctx.query1("arn", "")
            targets = [t for t in self.api.bucket_meta.get(
                bucket).replication_targets if t.get("arn") != arn]
            self.api.bucket_meta.update(bucket,
                                        replication_targets=targets)
            repl = self.api.replication
            if repl is not None:
                if hasattr(repl, "remove_target"):
                    try:
                        repl.remove_target(arn)
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                else:
                    repl.targets.pop(arn, None)
            return self._json({})
        if sub == "add-service-account" and m == "PUT":
            self._auth(ctx, "admin:CreateServiceAccount")
            body = json.loads(ctx.read_body().decode() or "{}")
            cred = self._iam().new_service_account(
                body.get("parent", ""), body.get("accessKey", ""),
                body.get("secretKey", ""))
            return self._json({"accessKey": cred.access_key,
                               "secretKey": cred.secret_key})

        raise S3Error("AdminInvalidArgument",
                      f"unknown admin call {m} {sub!r}")

    def _iam(self):
        if self.api.iam is None:
            raise S3Error("NotImplemented", "IAM is not configured")
        return self.api.iam

    def _repl_plane(self):
        """The active-active plane (minio_tpu/replicate/); the legacy
        pool has no registry and no resync surface."""
        repl = self.api.replication
        if repl is None or not hasattr(repl, "registry"):
            raise S3Error("NotImplemented",
                          "no active-active replication plane")
        return repl

    def _notify_plane(self):
        """The bucket event notification plane (minio_tpu/notify/);
        the legacy config-driven notifier has no target registry."""
        plane = self.api.notify
        if plane is None:
            raise S3Error("NotImplemented",
                          "no notification plane")
        return plane

    def _tiers(self):
        if self.api.tiers is None:
            raise S3Error("NotImplemented",
                          "backend has no tier configuration")
        return self.api.tiers

    def _tier_in_use(self, name: str) -> bool:
        """True when any bucket's lifecycle Transition rule names this
        tier (best-effort: an unlistable namespace blocks nothing)."""
        from ..features.lifecycle import Lifecycle
        try:
            buckets = [v.name for v in self.api.obj.list_buckets()]
        except Exception:  # noqa: BLE001 — can't enumerate: don't block
            return False
        for b in buckets:
            xml = self.api.bucket_meta.get(b).lifecycle_xml
            if not xml:
                continue
            try:
                lc = Lifecycle.from_xml(xml)
            except Exception:  # noqa: BLE001 — malformed config
                continue
            for r in lc.rules:
                if r.enabled and name in (r.transition_tier,
                                          r.noncurrent_transition_tier):
                    return True
        return False

    def _topology_call(self, method: str, *args):
        """Dispatch a topology-plane verb on the object layer; backends
        without pools (FS, gateways) answer NotImplemented and invalid
        transitions map to AdminInvalidArgument."""
        from ..object.topology import TopologyError
        fn = getattr(self.api.obj, method, None)
        if not callable(fn):
            raise S3Error("NotImplemented",
                          "backend has no pool topology")
        try:
            return fn(*args)
        except TopologyError as e:
            raise S3Error("AdminInvalidArgument", str(e)) from None

    def _require_bucket(self, bucket: str) -> None:
        """Quota/remote-target admin must target a REAL bucket —
        bucket_meta.get() silently defaults for unknown names, so the
        existence check has to hit the object layer (review r3)."""
        from ..object import api_errors
        try:
            self.api.obj.get_bucket_info(bucket)
        except api_errors.BucketNotFound:
            raise S3Error("NoSuchBucket",
                          f"bucket {bucket!r} does not exist") from None

    def service_action(self, action: str) -> None:
        """Local service restart/stop. Overridable hook; the default
        re-execs the process for restart (reference cmd/service.go
        restartProcess) and exits for stop."""
        import os
        import sys
        if action == "restart":
            os.execv(sys.executable, [sys.executable] + sys.argv)
        elif action == "stop":
            os._exit(0)

    def _set_remote_target(self, ctx: RequestContext) -> HTTPResponse:
        """Register a replication destination for a bucket
        (cmd/admin-bucket-handlers.go SetRemoteTargetHandler +
        cmd/bucket-targets.go): persisted in bucket metadata, mounted
        into the live replication pool, ARN returned."""
        import uuid as _uuid
        bucket = ctx.query1("bucket", "")
        body = json.loads(ctx.read_body().decode() or "{}")
        host = body.get("host") or ""
        tbucket = body.get("targetbucket") or body.get("bucket") or ""
        if not bucket or not host or not tbucket:
            raise S3Error("AdminInvalidArgument",
                          "bucket, host and targetbucket are required")
        self._require_bucket(bucket)
        entry = {
            "arn": f"arn:minio:replication::{_uuid.uuid4().hex[:12]}:"
                   f"{tbucket}",
            "host": host, "port": int(body.get("port", 9000)),
            "bucket": tbucket,
            "access_key": body.get("accesskey", ""),
            "secret_key": body.get("secretkey", ""),
            "region": body.get("region", "us-east-1"),
            "secure": bool(body.get("secure", False)),
        }
        targets = list(self.api.bucket_meta.get(
            bucket).replication_targets) + [entry]
        self.api.bucket_meta.update(bucket, replication_targets=targets)
        if self.api.replication is not None:
            # the legacy entry's "bucket" is the REMOTE bucket — the
            # plane's registry needs the SOURCE bucket too, or the
            # target would watch the wrong namespace (cluster boot's
            # remount does the same)
            self.api.replication.mount_target_entry(
                dict(entry, source_bucket=bucket))
        return self._json({"arn": entry["arn"]})

    def _profiling_start(self, kinds: str = "cpu") -> dict:
        """Start profiling on EVERY node: locally via the process
        profilers, cluster-wide via the peer fan-out (reference admin
        profiling/start?profilerType=cpu,mem,
        cmd/admin-handlers.go:461-525 + peerRESTMethodStartProfiling;
        cProfile = pprof-cpu, tracemalloc = pprof-heap)."""
        from ..utils import profiling
        wanted = profiling.parse_kinds(kinds)
        bad = [k for k in profiling.split_raw(kinds)
               if k not in profiling.KINDS]
        if bad or not wanted:
            raise S3Error("AdminInvalidArgument",
                          f"unknown profiler type(s) {bad or kinds!r}; "
                          f"supported: {', '.join(profiling.KINDS)}")
        out = {"kinds": {k: ("started" if profiling.start(k)
                             else "already running") for k in wanted}}
        if self.node is not None:
            peers = self.node.notification.profiling_start_all(
                ",".join(wanted))
            out["peers"] = [p for p in peers if isinstance(p, dict)]
        return out

    def _profiling_stop(self, kinds: str = "cpu") -> HTTPResponse:
        """Stop everywhere and return one zip with a profile per
        (kind, node) (reference downloads a zip of all nodes'
        profiles)."""
        import io
        import zipfile
        from ..utils import profiling
        wanted = profiling.parse_kinds(kinds)
        bad = [k for k in profiling.split_raw(kinds)
               if k not in profiling.KINDS]
        if bad or not wanted:
            # stop must reject what start rejects — a typo'd stop
            # otherwise tears down someone else's cpu profile
            raise S3Error("AdminInvalidArgument",
                          f"unknown profiler type(s) {bad or kinds!r}; "
                          f"supported: {', '.join(profiling.KINDS)}")
        local_name = self.node.spec.addr if self.node is not None \
            else "local"
        profiles: list[tuple[str, str, str]] = []
        for k in wanted:
            local = profiling.stop_text(k)
            if local is not None:
                profiles.append((k, local_name, local))
        if self.node is not None:
            for res in self.node.notification.profiling_stop_all(
                    ",".join(wanted)):
                if not isinstance(res, dict):
                    continue
                for k, text in (res.get("profiles") or {}).items():
                    if text:
                        profiles.append((k, res.get("node", "peer"),
                                         text))
                if res.get("profile"):          # legacy single-kind
                    profiles.append(("cpu", res.get("node", "peer"),
                                     res["profile"]))
        if not profiles:
            raise S3Error("AdminInvalidArgument", "profiling not running")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for kind, node, text in profiles:
                safe = node.replace(":", "_").replace("/", "_")
                zf.writestr(f"profile-{kind}-{safe}.txt", text)
        return HTTPResponse(body=buf.getvalue(),
                            headers={"Content-Type": "application/zip"})

    def _config(self):
        cfg = getattr(self.api, "config", None)
        if cfg is None:
            from ..config import ConfigSys
            cfg = ConfigSys(self.api.obj,
                            secret=self.api.root_cred.secret_key)
            self.api.config = cfg
        return cfg

    @staticmethod
    def _json(payload: dict) -> HTTPResponse:
        return HTTPResponse(body=json.dumps(payload).encode(),
                            headers={"Content-Type": "application/json"})

    # -- info --------------------------------------------------------------

    def server_info(self) -> dict:
        info = {
            "version": "minio-tpu-dev",
            "uptime": round(time.time() - self.started, 3),
            "region": self.api.region,
            "storage": self.api.obj.storage_info()
            if self.api.obj is not None else {},
        }
        if self.node is not None:
            info["node"] = self.node.spec.addr
            info["sets"] = self.node.set_count
            info["drives_per_set"] = self.node.set_drive_count
            peers = self.node.notification.server_info_all()
            info["peers"] = [p for p in peers if isinstance(p, dict)]
        return info

    def top_locks(self) -> dict:
        merged: dict = {}
        if self.node is not None:
            merged.update(self.node.notification.top_locks())
            local = self.node.locker.dump()
        else:
            local = {}
        for res, holders in local.items():
            merged.setdefault(res, []).extend(holders)
        return merged

    def cluster_metrics_text(self) -> str:
        """The federated scrape: pull every peer's exposition (bounded
        by the per-peer MINIO_TPU_CLUSTER_SCRAPE_S deadline), count
        failures, then merge with this node's OWN render — local render
        runs AFTER the failure counting so the degraded-scrape counter
        appears in the very response that degraded."""
        from ..utils import promfed
        deadline = knobs.get_float("MINIO_TPU_CLUSTER_SCRAPE_S")
        peers = self.node.notification.metrics_text_all(
            deadline=deadline) if self.node is not None else []
        for addr, text in peers:
            if text is None:
                _SCRAPE_FAILED.inc(node=addr)
        local_name = self.node.spec.addr if self.node is not None \
            else "local"
        nodes = [(local_name, self.metrics.local_text())]
        nodes.extend((a, t) for a, t in peers if t is not None)
        return promfed.merge_expositions(nodes)


class HealthHandlers:
    """/minio/health/{live,ready,cluster} (cmd/healthcheck-handler.go)."""

    def __init__(self, api):
        self.api = api

    def route(self, ctx: RequestContext) -> HTTPResponse:
        sub = ctx.req.path[len(HEALTH_PREFIX):].strip("/")
        if sub == "live":
            return HTTPResponse(status=200)
        if sub in ("ready", "cluster"):
            obj = self.api.obj
            if obj is None:
                return HTTPResponse(status=503)
            try:
                info = obj.storage_info()
            except Exception:  # noqa: BLE001 — failure = not ready
                return HTTPResponse(status=503)
            total = info["online_disks"] + info["offline_disks"]
            # ready when a write quorum of drives is online
            if total and info["online_disks"] > total // 2:
                return HTTPResponse(status=200)
            return HTTPResponse(status=503)
        return HTTPResponse(status=404)


class MetricsHandler:
    """Prometheus text exposition (cmd/metrics.go analog).

    Every sample now comes out of the shared telemetry registry
    (utils/telemetry.REGISTRY): subsystems that own live state
    (pipeline overlap, scheduler queue, profilers, RPC transport)
    register their own collectors; the server-topology gauges below
    are refreshed here because only this handler holds the api/node
    handles. Metric names predate the registry and stay stable."""

    def __init__(self, api, node=None):
        self.api = api
        self.node = node
        self.reg = telemetry.REGISTRY

    def _collect(self) -> None:
        g = self.reg.gauge
        try:
            info = self.api.obj.storage_info() if self.api.obj else {}
        except Exception:  # noqa: BLE001
            info = {}
        g("minio_disks_online", "Online drives").set(
            info.get("online_disks", 0))
        g("minio_disks_offline", "Offline drives").set(
            info.get("offline_disks", 0))
        g("minio_capacity_raw_total_bytes", "Raw capacity").set(
            info.get("total", 0))
        g("minio_capacity_raw_free_bytes", "Raw free").set(
            info.get("free", 0))
        if self.api.usage is not None:
            u = self.api.usage.usage
            g("minio_usage_object_total", "Objects").set(
                u.get("objects_total", 0))
            g("minio_usage_size_total_bytes", "Logical bytes").set(
                u.get("size_total", 0))
            bg = g("minio_bucket_usage_size_bytes",
                   "Logical bytes per bucket")
            bg.clear()          # deleted buckets must drop off
            for b, v in u.get("buckets", {}).items():
                bg.set(v["size"], bucket=b)
        if self.api.replication is not None:
            g("minio_replication_completed_total",
              "Replicated ops").set(self.api.replication.replicated)
            g("minio_replication_failed_total",
              "Failed replication ops").set(self.api.replication.failed)
        # MRF heal queue (degraded reads/writes awaiting re-redundancy)
        mrf_fn = getattr(self.api.obj, "mrf_stats", None)
        if callable(mrf_fn):
            try:
                mrf = mrf_fn()
            except Exception:  # noqa: BLE001
                mrf = {}
            g("minio_heal_mrf_pending",
              "Objects queued for MRF heal").set(mrf.get("pending", 0))
            g("minio_heal_mrf_healed_total",
              "Objects healed via MRF").set(mrf.get("healed", 0))
            g("minio_heal_mrf_failed_total",
              "MRF heals that exhausted retries").set(
                mrf.get("failed", 0))
            g("minio_heal_mrf_dropped_total",
              "MRF enqueues dropped (queue full)").set(
                mrf.get("dropped", 0))
        # background plane liveness: consecutive scan failures per loop
        if self.node is not None:
            for attr, name in (("disk_monitor", "disk_monitor"),
                               ("heal_scanner", "heal_scanner"),
                               ("crawler", "crawler")):
                loop = getattr(self.node, attr, None)
                if loop is not None:
                    g(f"minio_{name}_consecutive_errors",
                      f"Consecutive failed {name} scans").set(
                        getattr(loop, "consecutive_errors", 0))

    def local_text(self) -> str:
        """This node's full exposition with the server-scoped refresh
        applied — what /minio/prometheus/metrics serves, what the admin
        /metrics route returns, and what the peer `metrics-text` verb
        hands a federating scraper. One renderer, three surfaces."""
        return self.reg.render(self._collect)

    def route(self, ctx: RequestContext) -> HTTPResponse:
        # _collect runs as this scrape's one-shot collector, NOT a
        # globally registered one: with several servers in one process
        # each metrics endpoint must report ITS api/node values, and a
        # stopped server must stop reporting (registered collectors
        # live as long as the process-global registry)
        return HTTPResponse(body=self.local_text().encode(),
                            headers={"Content-Type": "text/plain"})


def mount_admin(server, node=None) -> AdminHandlers:
    """Attach admin/health/metrics routers to an S3Server."""
    admin = AdminHandlers(server.api, node)
    server.admin = admin       # reachable from the server handle
    admin.metrics = MetricsHandler(server.api, node)
    server.register_router(ADMIN_PREFIX, admin.route)
    server.register_router(HEALTH_PREFIX, HealthHandlers(server.api).route)
    server.register_router(METRICS_PREFIX, admin.metrics.route)
    return admin
