"""S3 XML response marshaling (reference cmd/api-response.go).

Hand-rolled writer (like the reference's encoding/xml structs) producing
the exact S3 dialect: ListAllMyBucketsResult, ListBucketResult (V1/V2),
ListVersionsResult, multipart responses, DeleteResult, CopyObjectResult,
Error.
"""

from __future__ import annotations

import datetime
import urllib.parse
from typing import Iterable, Optional
from xml.sax.saxutils import escape

from ..storage.datatypes import ObjectInfo

S3_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _ts(t: float) -> str:
    """RFC3339 with millis, UTC (the reference's amazon time format)."""
    dt = datetime.datetime.fromtimestamp(t or 0, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


class X:
    """Tiny XML builder."""

    def __init__(self):
        self.parts: list[str] = ['<?xml version="1.0" encoding="UTF-8"?>']

    def open(self, tag: str, **attrs) -> "X":
        a = "".join(f' {k}="{escape(v)}"' for k, v in attrs.items())
        self.parts.append(f"<{tag}{a}>")
        return self

    def close(self, tag: str) -> "X":
        self.parts.append(f"</{tag}>")
        return self

    def elem(self, tag: str, value) -> "X":
        self.parts.append(f"<{tag}>{escape(str(value))}</{tag}>")
        return self

    def empty(self, tag: str) -> "X":
        self.parts.append(f"<{tag}/>")
        return self

    def bytes(self) -> bytes:
        return "".join(self.parts).encode()


def _maybe_encode(s: str, encoding_type: str) -> str:
    if encoding_type == "url":
        return urllib.parse.quote(s, safe="/")
    return s


def error_response(code: str, message: str, resource: str,
                   request_id: str, host_id: str = "") -> bytes:
    x = X()
    x.open("Error")
    x.elem("Code", code).elem("Message", message)
    x.elem("Resource", resource).elem("RequestId", request_id)
    x.elem("HostId", host_id)
    x.close("Error")
    return x.bytes()


def list_buckets_response(owner_id: str, buckets) -> bytes:
    x = X()
    x.open("ListAllMyBucketsResult", xmlns=S3_XMLNS)
    x.open("Owner").elem("ID", owner_id).elem("DisplayName", owner_id)
    x.close("Owner")
    x.open("Buckets")
    for b in buckets:
        x.open("Bucket").elem("Name", b.name)
        x.elem("CreationDate", _ts(b.created)).close("Bucket")
    x.close("Buckets").close("ListAllMyBucketsResult")
    return x.bytes()


def _write_object_entry(x: X, o: ObjectInfo, encoding_type: str,
                        fetch_owner: bool = True,
                        owner_id: str = "minio") -> None:
    x.open("Contents")
    x.elem("Key", _maybe_encode(o.name, encoding_type))
    x.elem("LastModified", _ts(o.mod_time))
    x.elem("ETag", f'"{o.etag}"' if o.etag else "")
    x.elem("Size", o.size)
    x.elem("StorageClass", o.storage_class or "STANDARD")
    if fetch_owner:
        x.open("Owner").elem("ID", owner_id)
        x.elem("DisplayName", owner_id).close("Owner")
    x.close("Contents")


def _write_prefixes(x: X, prefixes: Iterable[str],
                    encoding_type: str) -> None:
    for p in prefixes:
        x.open("CommonPrefixes")
        x.elem("Prefix", _maybe_encode(p, encoding_type))
        x.close("CommonPrefixes")


def list_objects_v1_response(bucket: str, prefix: str, marker: str,
                             delimiter: str, max_keys: int,
                             encoding_type: str, objects: list[ObjectInfo],
                             prefixes: list[str], is_truncated: bool,
                             next_marker: str = "") -> bytes:
    x = X()
    x.open("ListBucketResult", xmlns=S3_XMLNS)
    x.elem("Name", bucket)
    x.elem("Prefix", _maybe_encode(prefix, encoding_type))
    x.elem("Marker", _maybe_encode(marker, encoding_type))
    x.elem("MaxKeys", max_keys)
    if delimiter:
        x.elem("Delimiter", _maybe_encode(delimiter, encoding_type))
    if encoding_type:
        x.elem("EncodingType", encoding_type)
    x.elem("IsTruncated", "true" if is_truncated else "false")
    if is_truncated and next_marker:
        x.elem("NextMarker", _maybe_encode(next_marker, encoding_type))
    for o in objects:
        _write_object_entry(x, o, encoding_type)
    _write_prefixes(x, prefixes, encoding_type)
    x.close("ListBucketResult")
    return x.bytes()


def list_objects_v2_response(bucket: str, prefix: str, delimiter: str,
                             max_keys: int, encoding_type: str,
                             start_after: str, token: str,
                             next_token: str, objects: list[ObjectInfo],
                             prefixes: list[str], is_truncated: bool,
                             fetch_owner: bool) -> bytes:
    x = X()
    x.open("ListBucketResult", xmlns=S3_XMLNS)
    x.elem("Name", bucket)
    x.elem("Prefix", _maybe_encode(prefix, encoding_type))
    if start_after:
        x.elem("StartAfter", _maybe_encode(start_after, encoding_type))
    if token:
        x.elem("ContinuationToken", token)
    if next_token:
        x.elem("NextContinuationToken", next_token)
    x.elem("KeyCount", len(objects) + len(prefixes))
    x.elem("MaxKeys", max_keys)
    if delimiter:
        x.elem("Delimiter", _maybe_encode(delimiter, encoding_type))
    if encoding_type:
        x.elem("EncodingType", encoding_type)
    x.elem("IsTruncated", "true" if is_truncated else "false")
    for o in objects:
        _write_object_entry(x, o, encoding_type, fetch_owner)
    _write_prefixes(x, prefixes, encoding_type)
    x.close("ListBucketResult")
    return x.bytes()


def list_versions_response(bucket: str, prefix: str, key_marker: str,
                           version_marker: str, delimiter: str,
                           max_keys: int, encoding_type: str,
                           versions: list[ObjectInfo],
                           prefixes: list[str],
                           is_truncated: bool,
                           next_key_marker: str = "",
                           next_version_marker: str = "") -> bytes:
    x = X()
    x.open("ListVersionsResult", xmlns=S3_XMLNS)
    x.elem("Name", bucket)
    x.elem("Prefix", _maybe_encode(prefix, encoding_type))
    x.elem("KeyMarker", key_marker)
    x.elem("VersionIdMarker", version_marker)
    x.elem("MaxKeys", max_keys)
    if delimiter:
        x.elem("Delimiter", _maybe_encode(delimiter, encoding_type))
    x.elem("IsTruncated", "true" if is_truncated else "false")
    if is_truncated and next_key_marker:
        x.elem("NextKeyMarker",
               _maybe_encode(next_key_marker, encoding_type))
        x.elem("NextVersionIdMarker", next_version_marker or "null")
    for o in versions:
        tag = "DeleteMarker" if o.delete_marker else "Version"
        x.open(tag)
        x.elem("Key", _maybe_encode(o.name, encoding_type))
        x.elem("VersionId", o.version_id or "null")
        x.elem("IsLatest", "true" if o.is_latest else "false")
        x.elem("LastModified", _ts(o.mod_time))
        if not o.delete_marker:
            x.elem("ETag", f'"{o.etag}"')
            x.elem("Size", o.size)
            x.elem("StorageClass", o.storage_class or "STANDARD")
        x.open("Owner").elem("ID", "minio")
        x.elem("DisplayName", "minio").close("Owner")
        x.close(tag)
    _write_prefixes(x, prefixes, encoding_type)
    x.close("ListVersionsResult")
    return x.bytes()


def location_response(region: str) -> bytes:
    x = X()
    if region:
        x.open("LocationConstraint", xmlns=S3_XMLNS)
        x.parts.append(escape(region))
        x.close("LocationConstraint")
    else:
        x.parts.append(f'<LocationConstraint xmlns="{S3_XMLNS}"/>')
    return x.bytes()


def initiate_multipart_response(bucket: str, key: str,
                                upload_id: str) -> bytes:
    x = X()
    x.open("InitiateMultipartUploadResult", xmlns=S3_XMLNS)
    x.elem("Bucket", bucket).elem("Key", key).elem("UploadId", upload_id)
    x.close("InitiateMultipartUploadResult")
    return x.bytes()


def complete_multipart_response(location: str, bucket: str, key: str,
                                etag: str) -> bytes:
    x = X()
    x.open("CompleteMultipartUploadResult", xmlns=S3_XMLNS)
    x.elem("Location", location).elem("Bucket", bucket)
    x.elem("Key", key).elem("ETag", f'"{etag}"')
    x.close("CompleteMultipartUploadResult")
    return x.bytes()


def list_parts_response(bucket: str, key: str, upload_id: str,
                        part_marker: int, next_marker: int, max_parts: int,
                        is_truncated: bool, parts) -> bytes:
    x = X()
    x.open("ListPartsResult", xmlns=S3_XMLNS)
    x.elem("Bucket", bucket).elem("Key", key).elem("UploadId", upload_id)
    x.open("Initiator").elem("ID", "minio")
    x.elem("DisplayName", "minio").close("Initiator")
    x.open("Owner").elem("ID", "minio")
    x.elem("DisplayName", "minio").close("Owner")
    x.elem("StorageClass", "STANDARD")
    x.elem("PartNumberMarker", part_marker)
    x.elem("NextPartNumberMarker", next_marker)
    x.elem("MaxParts", max_parts)
    x.elem("IsTruncated", "true" if is_truncated else "false")
    for p in parts:
        x.open("Part")
        x.elem("PartNumber", p.part_number)
        x.elem("LastModified", _ts(getattr(p, "mod_time", 0.0)))
        x.elem("ETag", f'"{p.etag}"')
        x.elem("Size", p.size)
        x.close("Part")
    x.close("ListPartsResult")
    return x.bytes()


def list_multipart_uploads_response(bucket: str, key_marker: str,
                                    upload_id_marker: str, prefix: str,
                                    delimiter: str, max_uploads: int,
                                    is_truncated: bool, uploads,
                                    next_key_marker: str = "",
                                    next_upload_id_marker: str = ""
                                    ) -> bytes:
    x = X()
    x.open("ListMultipartUploadsResult", xmlns=S3_XMLNS)
    x.elem("Bucket", bucket)
    x.elem("KeyMarker", key_marker)
    x.elem("UploadIdMarker", upload_id_marker)
    if is_truncated and next_key_marker:
        x.elem("NextKeyMarker", next_key_marker)
        x.elem("NextUploadIdMarker", next_upload_id_marker)
    x.elem("Prefix", prefix)
    if delimiter:
        x.elem("Delimiter", delimiter)
    x.elem("MaxUploads", max_uploads)
    x.elem("IsTruncated", "true" if is_truncated else "false")
    for u in uploads:
        x.open("Upload")
        x.elem("Key", u["object"])
        x.elem("UploadId", u["upload_id"])
        x.open("Initiator").elem("ID", "minio")
        x.elem("DisplayName", "minio").close("Initiator")
        x.open("Owner").elem("ID", "minio")
        x.elem("DisplayName", "minio").close("Owner")
        x.elem("StorageClass", "STANDARD")
        x.elem("Initiated", _ts(u.get("initiated", 0.0)))
        x.close("Upload")
    x.close("ListMultipartUploadsResult")
    return x.bytes()


def delete_objects_response(deleted: list[dict],
                            errors: list[dict]) -> bytes:
    x = X()
    x.open("DeleteResult", xmlns=S3_XMLNS)
    for d in deleted:
        x.open("Deleted").elem("Key", d["key"])
        if d.get("version_id"):
            x.elem("VersionId", d["version_id"])
        if d.get("delete_marker"):
            x.elem("DeleteMarker", "true")
            x.elem("DeleteMarkerVersionId", d.get("delete_marker_version",
                                                  ""))
        x.close("Deleted")
    for e in errors:
        x.open("Error").elem("Key", e["key"])
        x.elem("Code", e["code"]).elem("Message", e["message"])
        x.close("Error")
    x.close("DeleteResult")
    return x.bytes()


def copy_object_response(etag: str, mod_time: float) -> bytes:
    x = X()
    x.open("CopyObjectResult", xmlns=S3_XMLNS)
    x.elem("LastModified", _ts(mod_time))
    x.elem("ETag", f'"{etag}"')
    x.close("CopyObjectResult")
    return x.bytes()


def versioning_response(status: str) -> bytes:
    x = X()
    x.open("VersioningConfiguration", xmlns=S3_XMLNS)
    if status:
        x.elem("Status", status)
    x.close("VersioningConfiguration")
    return x.bytes()


def tagging_response(tags: dict[str, str]) -> bytes:
    x = X()
    x.open("Tagging", xmlns=S3_XMLNS).open("TagSet")
    for k, v in tags.items():
        x.open("Tag").elem("Key", k).elem("Value", v).close("Tag")
    x.close("TagSet").close("Tagging")
    return x.bytes()
