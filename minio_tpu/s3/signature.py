"""AWS signature verification: SigV4 (header, presigned, streaming
chunked) and SigV2 (header, presigned).

Mirrors the behavior of the reference's cmd/signature-v4.go,
cmd/signature-v4-parser.go, cmd/streaming-signature-v4.go and
cmd/signature-v2.go, rebuilt around a request snapshot (method, path,
query, headers, body) rather than net/http internals.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import hmac
import re
import urllib.parse
from typing import Callable, Iterable, Optional

from .credentials import Credentials

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
STREAMING_CONTENT_SHA256 = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
ISO8601_FORMAT = "%Y%m%dT%H%M%SZ"
YYYYMMDD = "%Y%m%d"
SERVICE_S3 = "s3"
MAX_SKEW_SECONDS = 15 * 60
MAX_PRESIGN_EXPIRES = 7 * 24 * 3600


class SigError(Exception):
    """Signature failure; .code is an S3 error code name."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code


@dataclasses.dataclass
class Request:
    """Snapshot of an incoming HTTP request for auth purposes."""
    method: str
    path: str                      # URL-encoded path as received
    query: dict[str, list[str]]    # parsed query (values url-decoded)
    headers: dict[str, str]        # lower-cased header names
    raw_query: str = ""            # original query string

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def _hmac_sha256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = SERVICE_S3) -> bytes:
    """AWS4 derived signing key (cmd/signature-v4.go getSigningKey)."""
    k = _hmac_sha256(("AWS4" + secret).encode(), date)
    k = _hmac_sha256(k, region)
    k = _hmac_sha256(k, service)
    return _hmac_sha256(k, "aws4_request")


def _canonical_query(query: dict[str, list[str]],
                     skip: Iterable[str] = ()) -> str:
    pairs = []
    skipset = set(skip)
    for k in sorted(query):
        if k in skipset:
            continue
        for v in sorted(query[k]):
            pairs.append(f"{_uri_encode(k)}={_uri_encode(v)}")
    return "&".join(pairs)


def _canonical_headers(headers: dict[str, str],
                       signed: list[str]) -> tuple[str, str]:
    lines = []
    for h in signed:
        v = headers.get(h, "")
        lines.append(f"{h}:{' '.join(v.split())}\n")
    return "".join(lines), ";".join(signed)


def canonical_request(method: str, path: str, query_str: str,
                      headers: dict[str, str], signed_headers: list[str],
                      payload_hash: str) -> str:
    ch, sh = _canonical_headers(headers, signed_headers)
    return "\n".join([method, path, query_str, ch, sh, payload_hash])


def string_to_sign(canon_req: str, amz_date: str, scope: str) -> str:
    return "\n".join([SIGN_V4_ALGORITHM, amz_date, scope,
                      hashlib.sha256(canon_req.encode()).hexdigest()])


def _scope(date: str, region: str, service: str = SERVICE_S3) -> str:
    return f"{date}/{region}/{service}/aws4_request"


def _parse_amz_date(s: str) -> datetime.datetime:
    for fmt in (ISO8601_FORMAT, "%a, %d %b %Y %H:%M:%S %Z"):
        try:
            return datetime.datetime.strptime(s, fmt).replace(
                tzinfo=datetime.timezone.utc)
        except ValueError:
            continue
    raise SigError("MalformedDate", f"bad date: {s}")


# ---------------------------------------------------------------------------
# SigV4 header auth
# ---------------------------------------------------------------------------

_CRED_RE = re.compile(
    r"^(?P<ak>[^/]+)/(?P<date>\d{8})/(?P<region>[^/]*)/"
    r"(?P<service>[^/]+)/aws4_request$")


@dataclasses.dataclass
class SigV4Parts:
    access_key: str
    date: str
    region: str
    service: str
    signed_headers: list[str]
    signature: str


def parse_sign_v4(auth_header: str) -> SigV4Parts:
    """Parse `Authorization: AWS4-HMAC-SHA256 Credential=..,
    SignedHeaders=.., Signature=..` (cmd/signature-v4-parser.go)."""
    if not auth_header.startswith(SIGN_V4_ALGORITHM):
        raise SigError("SignatureVersionNotSupported")
    rest = auth_header[len(SIGN_V4_ALGORITHM):].strip()
    fields = {}
    for part in rest.split(","):
        part = part.strip()
        if "=" not in part:
            raise SigError("AuthorizationHeaderMalformed")
        k, v = part.split("=", 1)
        fields[k.strip()] = v.strip()
    try:
        cred, sh, sig = (fields["Credential"], fields["SignedHeaders"],
                         fields["Signature"])
    except KeyError:
        raise SigError("AuthorizationHeaderMalformed")
    mm = _CRED_RE.match(cred)
    if not mm:
        raise SigError("CredMalformed")
    return SigV4Parts(access_key=mm["ak"], date=mm["date"],
                      region=mm["region"], service=mm["service"],
                      signed_headers=sorted(h.lower()
                                            for h in sh.split(";")),
                      signature=sig)


def _check_required_signed_headers(signed: list[str]) -> None:
    if "host" not in signed:
        raise SigError("UnsignedHeaders", "host header must be signed")


def verify_v4(req: Request, cred_lookup: Callable[[str], Credentials],
              region: str = "", payload_hash: Optional[str] = None
              ) -> Credentials:
    """Verify a header-signed V4 request; returns the matched creds.
    (cmd/signature-v4.go doesSignatureMatch)."""
    parts = parse_sign_v4(req.header("authorization"))
    _check_required_signed_headers(parts.signed_headers)
    creds = cred_lookup(parts.access_key)
    if region and parts.region and parts.region != region:
        raise SigError("AuthorizationHeaderMalformed",
                       f"region mismatch: {parts.region}")

    date_str = req.header("x-amz-date") or req.header("date")
    if not date_str:
        raise SigError("MissingDateHeader")
    t = _parse_amz_date(date_str)
    now = datetime.datetime.now(datetime.timezone.utc)
    if abs((now - t).total_seconds()) > MAX_SKEW_SECONDS:
        raise SigError("RequestTimeTooSkewed")

    if payload_hash is None:
        payload_hash = req.header("x-amz-content-sha256", UNSIGNED_PAYLOAD)

    canon = canonical_request(
        req.method, _canonical_uri(req.path), _canonical_query(req.query),
        req.headers, parts.signed_headers, payload_hash)
    sts = string_to_sign(canon, t.strftime(ISO8601_FORMAT),
                         _scope(parts.date, parts.region, parts.service))
    key = signing_key(creds.secret_key, parts.date, parts.region,
                      parts.service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, parts.signature):
        raise SigError("SignatureDoesNotMatch")
    return creds


def _canonical_uri(path: str) -> str:
    # Path arrives percent-encoded from the wire; canonical form keeps
    # it encoded (s3 does NOT double-encode, unlike other services).
    return path or "/"


# ---------------------------------------------------------------------------
# SigV4 presigned
# ---------------------------------------------------------------------------

def verify_v4_presigned(req: Request,
                        cred_lookup: Callable[[str], Credentials],
                        region: str = "") -> Credentials:
    """Verify `?X-Amz-Algorithm=AWS4-HMAC-SHA256&...` presigned URL
    (cmd/signature-v4.go doesPresignedSignatureMatch)."""
    q = {k: v[0] for k, v in req.query.items()}
    if q.get("X-Amz-Algorithm") != SIGN_V4_ALGORITHM:
        raise SigError("SignatureVersionNotSupported")
    try:
        cred, amz_date = q["X-Amz-Credential"], q["X-Amz-Date"]
        expires, sh = q["X-Amz-Expires"], q["X-Amz-SignedHeaders"]
        signature = q["X-Amz-Signature"]
    except KeyError:
        raise SigError("InvalidQueryParams")
    mm = _CRED_RE.match(cred)
    if not mm:
        raise SigError("CredMalformed")
    creds = cred_lookup(mm["ak"])
    if region and mm["region"] and mm["region"] != region:
        raise SigError("AuthorizationHeaderMalformed")

    t = _parse_amz_date(amz_date)
    now = datetime.datetime.now(datetime.timezone.utc)
    try:
        exp = int(expires)
    except ValueError:
        raise SigError("MalformedExpires")
    if exp < 0:
        raise SigError("NegativeExpires")
    if exp > MAX_PRESIGN_EXPIRES:
        raise SigError("MaximumExpires")
    if (now - t).total_seconds() > exp:
        raise SigError("ExpiredPresignRequest")
    if (t - now).total_seconds() > MAX_SKEW_SECONDS:
        raise SigError("RequestNotReadyYet")

    signed_headers = sorted(h.lower() for h in sh.split(";"))
    _check_required_signed_headers(signed_headers)
    payload_hash = q.get("X-Amz-Content-Sha256", UNSIGNED_PAYLOAD)
    canon = canonical_request(
        req.method, _canonical_uri(req.path),
        _canonical_query(req.query, skip=("X-Amz-Signature",)),
        req.headers, signed_headers, payload_hash)
    sts = string_to_sign(canon, amz_date,
                         _scope(mm["date"], mm["region"], mm["service"]))
    key = signing_key(creds.secret_key, mm["date"], mm["region"],
                      mm["service"])
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise SigError("SignatureDoesNotMatch")
    return creds


def presign_v4(method: str, path: str, query: dict[str, str],
               headers: dict[str, str], creds: Credentials, region: str,
               expires: int, t: Optional[datetime.datetime] = None) -> str:
    """Produce the presigned query string (client side; used by tests,
    the admin client, and share-URL generation)."""
    t = t or datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime(ISO8601_FORMAT)
    date = t.strftime(YYYYMMDD)
    scope = _scope(date, region)
    q = dict(query)
    q.update({
        "X-Amz-Algorithm": SIGN_V4_ALGORITHM,
        "X-Amz-Credential": f"{creds.access_key}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    })
    if creds.session_token:
        q["X-Amz-Security-Token"] = creds.session_token
    mq = {k: [v] for k, v in q.items()}
    canon = canonical_request(
        method, _canonical_uri(path), _canonical_query(mq),
        {"host": headers.get("host", "")}, ["host"], UNSIGNED_PAYLOAD)
    sts = string_to_sign(canon, amz_date, scope)
    key = signing_key(creds.secret_key, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    q["X-Amz-Signature"] = sig
    return urllib.parse.urlencode(q)


def sign_v4(method: str, path: str, query: dict[str, list[str]],
            headers: dict[str, str], payload_hash: str,
            creds: Credentials, region: str,
            t: Optional[datetime.datetime] = None) -> dict[str, str]:
    """Client-side header signing: returns headers to add (Authorization,
    x-amz-date, x-amz-content-sha256). Used by tests + internode client."""
    t = t or datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime(ISO8601_FORMAT)
    date = t.strftime(YYYYMMDD)
    hdrs = {k.lower(): v for k, v in headers.items()}
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = payload_hash
    if creds.session_token:
        hdrs["x-amz-security-token"] = creds.session_token
    signed = sorted(h for h in hdrs
                    if h in ("host", "content-type", "content-md5")
                    or h.startswith("x-amz-"))
    canon = canonical_request(method, _canonical_uri(path),
                              _canonical_query(query), hdrs, signed,
                              payload_hash)
    scope = _scope(date, region)
    sts = string_to_sign(canon, amz_date, scope)
    key = signing_key(creds.secret_key, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    hdrs["authorization"] = (
        f"{SIGN_V4_ALGORITHM} Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return hdrs


# ---------------------------------------------------------------------------
# SigV4 streaming chunked payload
# ---------------------------------------------------------------------------

class ChunkedReader:
    """Decode `aws-chunked` streaming-signed V4 payload, verifying each
    chunk signature (cmd/streaming-signature-v4.go newSignV4ChunkedReader).

    Frame:  <hex size>;chunk-signature=<sig>\r\n<payload>\r\n ...
    Final:  0;chunk-signature=<sig>\r\n\r\n
    Chunk string-to-sign chains the previous signature
    ("AWS4-HMAC-SHA256-PAYLOAD").
    """

    def __init__(self, raw, seed_signature: str, seed_date: str,
                 scope_date: str, region: str, secret_key: str):
        self.raw = raw
        self.prev_sig = seed_signature
        self.seed_date = seed_date
        self.scope = _scope(scope_date, region)
        self.key = signing_key(secret_key, scope_date, region)
        self.buf = b""
        self.eof = False

    def _read_line(self) -> bytes:
        line = b""
        while not line.endswith(b"\r\n"):
            c = self.raw.read(1)
            if not c:
                raise SigError("IncompleteBody", "truncated chunk header")
            line += c
            if len(line) > 4096:
                raise SigError("MalformedPOSTRequest", "chunk header too long")
        return line[:-2]

    def _chunk_string_to_sign(self, payload: bytes) -> str:
        return "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", self.seed_date, self.scope,
            self.prev_sig, EMPTY_SHA256,
            hashlib.sha256(payload).hexdigest()])

    def _next_chunk(self) -> bytes:
        header = self._read_line().decode("latin-1")
        if ";" not in header:
            raise SigError("MalformedPOSTRequest", "missing chunk-signature")
        size_hex, sigpart = header.split(";", 1)
        if not sigpart.startswith("chunk-signature="):
            raise SigError("MalformedPOSTRequest", "bad chunk signature tag")
        sig = sigpart[len("chunk-signature="):]
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise SigError("MalformedPOSTRequest", "bad chunk size")
        payload = b""
        while len(payload) < size:
            got = self.raw.read(size - len(payload))
            if not got:
                raise SigError("IncompleteBody", "truncated chunk payload")
            payload += got
        want = hmac.new(self.key, self._chunk_string_to_sign(payload)
                        .encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            raise SigError("SignatureDoesNotMatch", "chunk signature")
        self.prev_sig = sig
        crlf = self.raw.read(2)
        if crlf != b"\r\n":
            raise SigError("MalformedPOSTRequest", "missing chunk CRLF")
        if size == 0:
            self.eof = True
        return payload

    def read(self, n: int = -1) -> bytes:
        while not self.eof and (n < 0 or len(self.buf) < n):
            self.buf += self._next_chunk()
        if n < 0:
            out, self.buf = self.buf, b""
        else:
            out, self.buf = self.buf[:n], self.buf[n:]
        return out


def new_chunked_reader(req: Request, raw,
                       creds: Credentials) -> ChunkedReader:
    """Build the verifying reader from a streaming-signed request
    (requires the header signature already verified with payload hash
    STREAMING_CONTENT_SHA256)."""
    parts = parse_sign_v4(req.header("authorization"))
    date_str = req.header("x-amz-date") or req.header("date")
    t = _parse_amz_date(date_str)
    return ChunkedReader(raw, parts.signature, t.strftime(ISO8601_FORMAT),
                         parts.date, parts.region, creds.secret_key)


# ---------------------------------------------------------------------------
# SigV2 (legacy)
# ---------------------------------------------------------------------------

_SUBRESOURCES = (
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type", "response-expires",
    "torrent", "uploadId", "uploads", "versionId", "versioning", "versions",
    "website", "tagging", "select", "select-type")


def _canonical_v2(method: str, path: str, query: dict[str, list[str]],
                  headers: dict[str, str]) -> str:
    amz = sorted((k, ",".join(" ".join(vv.split()) for vv in [v]))
                 for k, v in headers.items() if k.startswith("x-amz-"))
    canon_amz = "".join(f"{k}:{v}\n" for k, v in amz)
    res = path
    sub = []
    for k in sorted(query):
        if k in _SUBRESOURCES:
            v = query[k][0]
            sub.append(f"{k}={v}" if v else k)
    if sub:
        res += "?" + "&".join(sub)
    return "\n".join([
        method,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        headers.get("date", ""),
    ]) + "\n" + canon_amz + res


def verify_v2(req: Request, cred_lookup: Callable[[str], Credentials]
              ) -> Credentials:
    """Verify `Authorization: AWS AKID:signature` (cmd/signature-v2.go)."""
    import base64
    auth = req.header("authorization")
    if not auth.startswith("AWS "):
        raise SigError("SignatureVersionNotSupported")
    try:
        ak, sig = auth[4:].split(":", 1)
    except ValueError:
        raise SigError("InvalidArgument", "malformed v2 auth header")
    creds = cred_lookup(ak)
    sts = _canonical_v2(req.method, req.path, req.query, req.headers)
    want = base64.b64encode(
        hmac.new(creds.secret_key.encode(), sts.encode(),
                 hashlib.sha1).digest()).decode()
    if not hmac.compare_digest(want, sig):
        raise SigError("SignatureDoesNotMatch")
    return creds


# ---------------------------------------------------------------------------
# request auth-type classification (cmd/auth-handler.go:54-118)
# ---------------------------------------------------------------------------

AUTH_UNKNOWN = "unknown"
AUTH_ANONYMOUS = "anonymous"
AUTH_PRESIGNED = "presigned"
AUTH_PRESIGNED_V2 = "presignedv2"
AUTH_SIGNED = "signed"
AUTH_SIGNED_V2 = "signedv2"
AUTH_STREAMING_SIGNED = "streaming-signed"
AUTH_POST_POLICY = "post-policy"
AUTH_JWT = "jwt"
AUTH_STS = "sts"


def get_request_auth_type(req: Request) -> str:
    auth = req.header("authorization")
    if auth.startswith(SIGN_V4_ALGORITHM):
        if req.header("x-amz-content-sha256") == STREAMING_CONTENT_SHA256:
            return AUTH_STREAMING_SIGNED
        return AUTH_SIGNED
    if auth.startswith("AWS "):
        return AUTH_SIGNED_V2
    if auth.startswith("Bearer "):
        return AUTH_JWT
    if "X-Amz-Credential" in req.query:
        return AUTH_PRESIGNED
    if "AWSAccessKeyId" in req.query:
        return AUTH_PRESIGNED_V2
    if req.header("content-type", "").startswith("multipart/form-data") \
            and req.method == "POST":
        return AUTH_POST_POLICY
    if "Action" in req.query:
        return AUTH_STS
    if not auth:
        return AUTH_ANONYMOUS
    return AUTH_UNKNOWN
