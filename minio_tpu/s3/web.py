"""Web JSON-RPC control surface (reference cmd/web-handlers.go:1-2291,
cmd/web-router.go, cmd/jwt.go — the server capability behind the
browser SPA; the SPA itself is out of scope, VERDICT r3 missing #1).

Mounted by S3Server as an extra router:

  POST /minio/webrpc                      JSON-RPC 2.0 endpoint
  PUT  /minio/web/upload/<bucket>/<key>   browser upload path
  GET  /minio/web/download/<bucket>/<key>?token=   browser download
  POST /minio/web/zip?token=              zip-of-prefix download

RPC methods (gorilla json2's "Web.X" names, case-insensitive):
Login, ServerInfo, StorageInfo, MakeBucket, DeleteBucket, ListBuckets,
ListObjects, RemoveObject, GenerateAuth, SetAuth, CreateURLToken,
PresignedGet, GetBucketPolicy, SetBucketPolicy, ListAllBucketPolicies.

Auth model mirrors the reference: Login verifies credentials and mints
a JWT signed with THAT account's secret key (cmd/jwt.go
authenticateWeb); requests carry it as `Authorization: Bearer <jwt>`;
download/zip accept a short-lived URL token minted by CreateURLToken
(authenticateURL) since browsers can't set headers on navigation.
Verification decodes the unverified subject claim, looks the account
up, then verifies the HMAC with that account's secret — so revoking a
user (or rotating a secret) invalidates outstanding tokens.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import re
import time
import urllib.parse
import zipfile
from binascii import Error as binascii_error
from typing import Optional

from ..object import api_errors as oerr
from .credentials import Credentials
from .handlers import HTTPResponse, RequestContext, S3ApiHandlers
from .s3errors import S3Error
from . import signature as sig

UI_VERSION = "minio-tpu-web-1"
SESSION_EXPIRY_S = 24 * 3600          # web session token
URL_TOKEN_EXPIRY_S = 3600             # download/zip token


# ---------------------------------------------------------------------------
# minimal JWT (HS256) — web tokens are signed with the ACCOUNT's secret
# ---------------------------------------------------------------------------

def _b64url(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()


def _b64url_dec(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def jwt_encode(claims: dict, secret: str) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    mac = hmac.new(secret.encode(), f"{header}.{payload}".encode(),
                   hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(mac)}"


def jwt_claims_unverified(token: str) -> dict:
    parts = token.split(".")
    if len(parts) != 3:
        raise S3Error("AccessDenied", "malformed token")
    try:
        claims = json.loads(_b64url_dec(parts[1]))
    except (ValueError, UnicodeDecodeError, binascii_error):
        raise S3Error("AccessDenied", "malformed token") from None
    if not isinstance(claims, dict):
        raise S3Error("AccessDenied", "malformed token")
    return claims


def jwt_verify(token: str, secret: str) -> dict:
    parts = token.split(".")
    if len(parts) != 3:
        raise S3Error("AccessDenied", "malformed token")
    mac = hmac.new(secret.encode(), f"{parts[0]}.{parts[1]}".encode(),
                   hashlib.sha256).digest()
    if not hmac.compare_digest(_b64url(mac), parts[2]):
        raise S3Error("AccessDenied", "invalid token signature")
    claims = jwt_claims_unverified(token)
    try:
        exp = float(claims.get("exp", 0))
    except (TypeError, ValueError):
        raise S3Error("AccessDenied", "malformed token") from None
    if exp < time.time():
        raise S3Error("AccessDenied", "token expired")
    return claims


class _RPCError(Exception):
    def __init__(self, message: str, code: int = 1):
        super().__init__(message)
        self.code = code


class WebHandlers:
    """The RPC + upload/download surface; holds no state of its own —
    everything delegates to the S3 handler layer's object layer, bucket
    metadata, and IAM."""

    def __init__(self, api: S3ApiHandlers):
        self.api = api

    # -- auth --------------------------------------------------------------

    def _lookup(self, access_key: str) -> Optional[Credentials]:
        root = self.api.root_cred
        if access_key == root.access_key:
            return root
        if self.api.iam is not None:
            return self.api.iam.get_credentials(access_key)
        return None

    def _mint(self, cred: Credentials, typ: str, expiry_s: int) -> str:
        return jwt_encode({"sub": cred.access_key, "typ": typ,
                           "exp": time.time() + expiry_s}, cred.secret_key)

    def _token_auth(self, token: str,
                    want_typ: tuple = ("web",)) -> tuple[Credentials, bool]:
        """token -> (credentials, is_owner); raises AccessDenied."""
        if not token:
            raise S3Error("AccessDenied", "no auth token")
        claims = jwt_claims_unverified(token)
        cred = self._lookup(str(claims.get("sub", "")))
        if cred is None or cred.status != "on":
            raise S3Error("AccessDenied", "no such user")
        claims = jwt_verify(token, cred.secret_key)
        if claims.get("typ") not in want_typ:
            raise S3Error("AccessDenied", "wrong token type")
        # root-derived service/STS creds are owners too (_is_owner
        # checks parent_user like the reference's IsOwner)
        return cred, self.api._is_owner(cred)

    def _request_auth(self, ctx: RequestContext,
                      want_typ: tuple = ("web",)
                      ) -> tuple[Credentials, bool]:
        auth = ctx.header("authorization")
        token = auth[len("Bearer "):] if auth.startswith("Bearer ") else ""
        if not token:
            token = ctx.query1("token")
        return self._token_auth(token, want_typ)

    def _allowed(self, cred: Credentials, owner: bool, action: str,
                 bucket: str, obj: str = "") -> bool:
        if owner:
            return True
        if self.api.iam is None:
            return False
        return self.api.iam.is_allowed(cred, action, bucket, obj)

    def _require(self, cred, owner, action, bucket, obj="") -> None:
        if not self._allowed(cred, owner, action, bucket, obj):
            raise _RPCError("access denied", code=403)

    # -- router ------------------------------------------------------------

    #: exact paths the static UI answers for; anything else under
    #: /minio/ belongs to admin/storage/lock/peer routers (the server
    #: continues matching when a router returns None)
    _UI_PATHS = ("/minio", "/minio/", "/minio/index.html",
                 "/minio/login")

    def router(self, ctx: RequestContext) -> HTTPResponse:
        path = urllib.parse.unquote(ctx.req.path)
        if path == "/minio/webrpc" and ctx.req.method == "POST":
            return self._rpc(ctx)
        if path.startswith("/minio/web/upload/"):
            return self._upload(ctx, path[len("/minio/web/upload/"):])
        if path.startswith("/minio/web/download/"):
            return self._download(ctx, path[len("/minio/web/download/"):])
        if path == "/minio/web/zip" and ctx.req.method == "POST":
            return self._zip(ctx)
        return HTTPResponse(status=404, body=b"not found")

    def ui(self, ctx: RequestContext) -> Optional[HTTPResponse]:
        """The static browser page (reference browser/app SPA as one
        build-chain-free HTML file, s3/webui.html). Returns None for
        paths outside _UI_PATHS so later-mounted /minio/* routers keep
        working."""
        path = urllib.parse.unquote(ctx.req.path).split("?", 1)[0]
        if path not in self._UI_PATHS:
            return None
        if ctx.req.method not in ("GET", "HEAD"):
            return HTTPResponse(status=405)
        page = _ui_page()
        return HTTPResponse(headers={
            "Content-Type": "text/html; charset=utf-8",
            "Cache-Control": "no-store",
            "X-Frame-Options": "DENY",
            "Content-Security-Policy":
                "default-src 'self'; style-src 'unsafe-inline'; "
                "script-src 'unsafe-inline'; img-src 'self' data:",
        }, body=page)

    # -- JSON-RPC ----------------------------------------------------------

    def _rpc(self, ctx: RequestContext) -> HTTPResponse:
        try:
            req = json.loads(ctx.read_body() or b"{}")
        except ValueError:
            return self._rpc_response(None, error={"code": -32700,
                                                   "message": "parse error"})
        if not isinstance(req, dict):
            return self._rpc_response(None, error={
                "code": -32600, "message": "invalid request"})
        rid = req.get("id")
        method = str(req.get("method", ""))
        name = method.split(".", 1)[-1].lower()
        params = req.get("params", {})
        if isinstance(params, list):
            params = params[0] if params else {}
        if not isinstance(params, dict):
            return self._rpc_response(rid, error={
                "code": -32602, "message": "params must be an object"})
        fn = getattr(self, f"rpc_{name}", None)
        if fn is None:
            return self._rpc_response(rid, error={
                "code": -32601, "message": f"unknown method {method}"})
        from ..iam.store import IAMStoreError
        try:
            return self._rpc_response(rid, result=fn(ctx, params or {}))
        except _RPCError as e:
            return self._rpc_response(rid, error={"code": e.code,
                                                  "message": str(e)})
        except S3Error as e:
            # token problems (expired/forged/no such user — raised as
            # AccessDenied by _token_auth) map to 401 so the UI can
            # return to the login screen; IAM *authorization* denials
            # use _RPCError 403 above and must NOT end the session
            code = 401 if e.code == "AccessDenied" else 1
            return self._rpc_response(rid, error={"code": code,
                                                  "message": str(e)})
        except oerr.ObjectApiError as e:
            return self._rpc_response(rid, error={"code": 1,
                                                  "message": str(e)})
        except IAMStoreError as e:
            return self._rpc_response(rid, error={
                "code": 500, "message": f"identity store: {e}"})

    @staticmethod
    def _rpc_response(rid, result=None, error=None) -> HTTPResponse:
        body: dict = {"jsonrpc": "2.0", "id": rid}
        if error is not None:
            body["error"] = error
        else:
            body["result"] = result
        return HTTPResponse(
            headers={"Content-Type": "application/json"},
            body=json.dumps(body).encode())

    # -- RPC methods -------------------------------------------------------

    def rpc_login(self, ctx, args) -> dict:
        username = str(args.get("username", ""))
        password = str(args.get("password", ""))
        cred = self._lookup(username)
        if cred is None or cred.status != "on" or not hmac.compare_digest(
                cred.secret_key, password):
            raise _RPCError("invalid credentials", code=403)
        return {"token": self._mint(cred, "web", SESSION_EXPIRY_S),
                "uiVersion": UI_VERSION}

    def rpc_serverinfo(self, ctx, args) -> dict:
        self._request_auth(ctx)
        import platform
        return {"MinioVersion": UI_VERSION,
                "MinioPlatform": platform.platform(),
                "MinioRuntime": platform.python_version(),
                "uiVersion": UI_VERSION}

    def rpc_storageinfo(self, ctx, args) -> dict:
        self._request_auth(ctx)
        info = {}
        su = getattr(self.api.obj, "storage_info", None)
        if su is not None:
            try:
                info = su()
            except Exception:  # noqa: BLE001 — best effort, like reference
                info = {}
        return {"storageInfo": info, "uiVersion": UI_VERSION}

    def rpc_makebucket(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        self._require(cred, owner, "s3:CreateBucket", bucket)
        self.api.obj.make_bucket(bucket)
        return {"uiVersion": UI_VERSION}

    def rpc_deletebucket(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        self._require(cred, owner, "s3:DeleteBucket", bucket)
        self.api.obj.delete_bucket(bucket)
        self.api.bucket_meta.delete(bucket)
        return {"uiVersion": UI_VERSION}

    def rpc_listbuckets(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        out = []
        for b in self.api.obj.list_buckets():
            if self._allowed(cred, owner, "s3:ListBucket", b.name):
                out.append({"name": b.name,
                            "creationDate": _iso(b.created)})
        return {"buckets": out, "uiVersion": UI_VERSION}

    def rpc_listobjects(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        prefix = str(args.get("prefix", ""))
        marker = str(args.get("marker", ""))
        self._require(cred, owner, "s3:ListBucket", bucket)
        objs, prefixes, truncated = self.api.obj.list_objects(
            bucket, prefix=prefix, delimiter="/", marker=marker,
            max_keys=1000)
        objects = [{"name": p, "size": 0, "contentType": "",
                    "lastModified": ""} for p in prefixes]
        objects += [{"name": o.name, "size": o.size,
                     "contentType": o.content_type,
                     "lastModified": _iso(o.mod_time)} for o in objs]
        reply = {"objects": objects, "uiVersion": UI_VERSION,
                 "istruncated": bool(truncated)}
        if truncated:
            # the marker must be the lexicographically LAST entry
            # returned — objects and common prefixes interleave in
            # sorted order, so a prefix can be the page's last item
            last = ""
            if objs:
                last = objs[-1].name
            if prefixes:
                last = max(last, prefixes[-1])
            if last:
                reply["nextmarker"] = last
        return reply

    def rpc_removeobject(self, ctx, args) -> dict:
        """Reference RemoveObject: a list of keys; a key ending in '/'
        removes the whole prefix recursively."""
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        objects = list(args.get("objects", []))
        for key in objects:
            key = str(key)
            if key.endswith("/") or key == "":
                self._require(cred, owner, "s3:ListBucket", bucket)
                marker = ""
                while True:
                    objs, _p, trunc = self.api.obj.list_objects(
                        bucket, prefix=key, marker=marker, max_keys=1000)
                    for o in objs:
                        self._require(cred, owner, "s3:DeleteObject",
                                      bucket, o.name)
                        self._delete_one(ctx, cred, bucket, o.name)
                    if not trunc or not objs:
                        break
                    marker = objs[-1].name
            else:
                self._require(cred, owner, "s3:DeleteObject", bucket, key)
                self._delete_one(ctx, cred, bucket, key)
        return {"uiVersion": UI_VERSION}

    def _delete_one(self, ctx, cred, bucket: str, key: str) -> None:
        """Delete with the SAME semantics as the S3 DELETE path: WORM
        retention enforced, versioned buckets get a delete marker, and
        the removal event fires (the first web cut bypassed all three)."""
        versioned = self.api.bucket_meta.versioning_enabled(bucket)
        ctx.cred = cred                 # governance-bypass check input
        self.api._enforce_object_lock(ctx, bucket, key, "", versioned)
        try:
            self.api.obj.delete_object(bucket, key, versioned=versioned)
        except oerr.ObjectNotFound:
            pass
        self.api._notify("s3:ObjectRemoved:Delete", bucket, key)

    def rpc_generateauth(self, ctx, args) -> dict:
        _cred, owner = self._request_auth(ctx)
        if not owner:
            raise _RPCError("access denied", code=403)
        from .credentials import generate_credentials
        new = generate_credentials()
        return {"accessKey": new.access_key, "secretKey": new.secret_key,
                "uiVersion": UI_VERSION}

    def rpc_setauth(self, ctx, args) -> dict:
        """Non-owner secret rotation (owner creds come from config/env,
        not the browser — reference errChangeCredNotAllowed)."""
        cred, owner = self._request_auth(ctx)
        if owner:
            raise _RPCError("owner credentials cannot be changed here",
                            code=403)
        if self.api.iam is None:
            raise _RPCError("IAM not configured", code=500)
        if not hmac.compare_digest(cred.secret_key,
                                   str(args.get("currentSecretKey", ""))):
            raise _RPCError("current secret key does not match", code=403)
        new_secret = str(args.get("newSecretKey", ""))
        if len(new_secret) < 8:
            raise _RPCError("secret key must be at least 8 chars")
        # add_user overwrites the identity record in place; policy
        # mappings live in policydb and survive the rotation
        self.api.iam.add_user(cred.access_key, new_secret)
        new_cred = self._lookup(cred.access_key)
        assert new_cred is not None
        return {"token": self._mint(new_cred, "web", SESSION_EXPIRY_S),
                "uiVersion": UI_VERSION, "peerErrMsgs": {}}

    def rpc_createurltoken(self, ctx, args) -> dict:
        cred, _owner = self._request_auth(ctx)
        return {"token": self._mint(cred, "url", URL_TOKEN_EXPIRY_S),
                "uiVersion": UI_VERSION}

    def rpc_presignedget(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        obj = str(args.get("objectName", ""))
        host = str(args.get("hostName", ctx.header("host")))
        try:
            expiry = int(args.get("expiry", 0) or 0)
        except (TypeError, ValueError):
            raise _RPCError("expiry must be an integer") from None
        if not (0 < expiry < 604800):
            expiry = 604800
        if not bucket or not obj:
            raise _RPCError("Bucket and Object are mandatory arguments.")
        self._require(cred, owner, "s3:GetObject", bucket, obj)
        path = "/" + urllib.parse.quote(f"{bucket}/{obj}")
        qs = sig.presign_v4("GET", path, {}, {"host": host}, cred,
                            self.api.region, expiry)
        return {"url": f"{host}{path}?{qs}", "uiVersion": UI_VERSION}

    # canned policy names per reference web UI semantics
    _POLICY_ACTIONS = {
        "readonly": ["s3:GetObject"],
        "writeonly": ["s3:PutObject"],
        "readwrite": ["s3:GetObject", "s3:PutObject", "s3:DeleteObject"],
    }

    def rpc_getbucketpolicy(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        prefix = str(args.get("prefix", ""))
        self._require(cred, owner, "s3:GetBucketPolicy", bucket)
        return {"policy": self._classify_policy(bucket, prefix),
                "uiVersion": UI_VERSION}

    def rpc_listallbucketpolicies(self, ctx, args) -> dict:
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        self._require(cred, owner, "s3:GetBucketPolicy", bucket)
        policies = []
        for st in self._bucket_statements(bucket):
            kind = self._statement_kind(st)
            if kind == "none":
                continue
            for res in st.resources:
                policies.append({"prefix": res.split(":::", 1)[-1],
                                 "policy": kind})
        return {"policies": policies, "uiVersion": UI_VERSION}

    def rpc_setbucketpolicy(self, ctx, args) -> dict:
        """Canned policy ∈ none|readonly|readwrite|writeonly applied to
        bucket[/prefix] (reference SetBucketPolicy web args)."""
        cred, owner = self._request_auth(ctx)
        bucket = str(args.get("bucketName", ""))
        prefix = str(args.get("prefix", ""))
        kind = str(args.get("policy", "none"))
        self._require(cred, owner, "s3:PutBucketPolicy", bucket)
        if kind not in ("none", "readonly", "readwrite", "writeonly"):
            raise _RPCError(f"invalid policy {kind}")
        res_obj = f"arn:aws:s3:::{bucket}/{prefix}*" if prefix else \
            f"arn:aws:s3:::{bucket}/*"
        statements = []
        if kind != "none":
            statements = [
                {"Effect": "Allow", "Principal": {"AWS": ["*"]},
                 "Action": ["s3:GetBucketLocation", "s3:ListBucket"],
                 "Resource": [f"arn:aws:s3:::{bucket}"]},
                {"Effect": "Allow", "Principal": {"AWS": ["*"]},
                 "Action": self._POLICY_ACTIONS[kind],
                 "Resource": [res_obj]},
            ]
        doc = json.dumps({"Version": "2012-10-17",
                          "Statement": statements}) if statements else ""
        self.api.bucket_meta.update(bucket, policy_json=doc)
        return {"uiVersion": UI_VERSION}

    def _bucket_statements(self, bucket: str) -> list:
        """Parsed statements of the bucket policy via the shared policy
        machinery (iam/policy.py) — not a second JSON walker."""
        from ..iam.policy import Policy
        doc = self.api.bucket_meta.get(bucket).policy_json
        if not doc:
            return []
        try:
            return Policy.from_json(doc).statements
        except (ValueError, KeyError):
            return []

    @staticmethod
    def _statement_kind(st) -> str:
        if st.effect != "Allow":
            return "none"   # a Deny granting nothing must not read back
        actions = set(st.actions)
        if "s3:PutObject" in actions and "s3:GetObject" in actions:
            return "readwrite"
        if "s3:PutObject" in actions:
            return "writeonly"
        if "s3:GetObject" in actions:
            return "readonly"
        return "none"

    def _classify_policy(self, bucket: str, prefix: str) -> str:
        want = f"arn:aws:s3:::{bucket}/{prefix}*" if prefix else \
            f"arn:aws:s3:::{bucket}/*"
        for st in self._bucket_statements(bucket):
            if want in st.resources:
                kind = self._statement_kind(st)
                if kind != "none":
                    return kind
        return "none"

    # -- upload / download / zip ------------------------------------------

    def _upload(self, ctx: RequestContext, rest: str) -> HTTPResponse:
        if ctx.req.method != "PUT":
            return HTTPResponse(status=405)
        bucket, _, key = rest.partition("/")
        # "web" sessions only: the 1-hour token minted by CreateURLToken
        # exists for download/zip navigation and must not authorize PUTs
        # (reference authenticateURL scope; ADVICE r4)
        cred, owner = self._request_auth(ctx, want_typ=("web",))
        if not key:
            raise S3Error("InvalidArgument", "missing object name")
        if not self._allowed(cred, owner, "s3:PutObject", bucket, key):
            raise S3Error("AccessDenied")
        from .handlers import MAX_OBJECT_SIZE
        size = max(ctx.content_length, 0)
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")
        # same enforcement as the S3 PUT path: quota, bucket default
        # retention, creation event
        self.api._enforce_quota(bucket, size)
        from ..object.hash_reader import HashReader
        reader = HashReader(ctx.body_stream, size)
        metadata = {}
        if ctx.header("content-type"):
            metadata["content-type"] = ctx.header("content-type")
        from ..features import objectlock as olock
        lock_cfg = self.api.bucket_meta.get(bucket).object_lock_xml
        if lock_cfg:
            olock.DefaultRetention.from_config_xml(lock_cfg).apply_to(
                metadata)
        from ..object.engine import PutOptions
        versioned = self.api.bucket_meta.versioning_enabled(bucket)
        info = self.api.obj.put_object(
            bucket, key, reader, size,
            PutOptions(metadata=metadata, versioned=versioned))
        self.api.bandwidth.record(bucket, "rx", max(size, 0))
        self.api._notify("s3:ObjectCreated:Put", bucket, key)
        return HTTPResponse(headers={"ETag": f'"{info.etag}"'})

    def _plain_object(self, ctx, bucket: str, key: str
                      ) -> tuple[object, "Iterator[bytes]", int]:
        """Plaintext (info, stream, size) for a web download — the same
        SSE/compression seam as the S3 GET/copy paths (ADVICE r4: the
        first cut returned stored ciphertext/compressed bytes with the
        stored size). SSE-C objects are rejected with AccessDenied
        inside _plaintext_stream: a browser navigation cannot present
        client key headers."""
        from ..object.engine import GetOptions
        info = self.api.obj.get_object_info(bucket, key)
        stream, size = self.api._plaintext_stream(
            bucket, key, info, ctx.header, GetOptions())
        return info, stream, size

    def _download(self, ctx: RequestContext, rest: str) -> HTTPResponse:
        if ctx.req.method != "GET":
            return HTTPResponse(status=405)
        bucket, _, key = rest.partition("/")
        cred, owner = self._request_auth(ctx, want_typ=("web", "url"))
        if not self._allowed(cred, owner, "s3:GetObject", bucket, key):
            raise S3Error("AccessDenied")
        _info, stream, size = self._plain_object(ctx, bucket, key)
        self.api.bandwidth.record(bucket, "tx", size)
        name = key.rsplit("/", 1)[-1] or "download"
        return HTTPResponse(
            headers={
                "Content-Type": "application/octet-stream",
                "Content-Length": str(size),
                "Content-Disposition": _attachment(name),
            },
            stream=stream)

    def _zip(self, ctx: RequestContext) -> HTTPResponse:
        """Zip-of-prefix download (reference DownloadZip): body names a
        bucket, a prefix, and entries; entries ending in '/' expand
        recursively. Spooled to a temp file so huge selections don't
        live in memory, streamed out in chunks."""
        cred, owner = self._request_auth(ctx, want_typ=("web", "url"))
        try:
            args = json.loads(ctx.read_body() or b"{}")
        except ValueError:
            raise S3Error("InvalidArgument", "malformed body") from None
        bucket = str(args.get("bucketName", ""))
        prefix = str(args.get("prefix", ""))
        objects = [str(o) for o in args.get("objects", [])]
        if not bucket or not objects:
            raise S3Error("InvalidArgument", "bucketName/objects required")

        keys: list[str] = []
        for entry in objects:
            full = prefix + entry
            if entry.endswith("/") or entry == "":
                if not self._allowed(cred, owner, "s3:ListBucket", bucket):
                    raise S3Error("AccessDenied")
                marker = ""
                while True:
                    objs, _p, trunc = self.api.obj.list_objects(
                        bucket, prefix=full, marker=marker, max_keys=1000)
                    keys.extend(o.name for o in objs)
                    if not trunc or not objs:
                        break
                    marker = objs[-1].name
            else:
                keys.append(full)
        for k in keys:
            if not self._allowed(cred, owner, "s3:GetObject", bucket, k):
                raise S3Error("AccessDenied")

        import tempfile
        spool = tempfile.SpooledTemporaryFile(max_size=64 << 20)
        total = 0
        with zipfile.ZipFile(spool, "w", zipfile.ZIP_DEFLATED) as zf:
            for k in keys:
                _i, stream, size = self._plain_object(ctx, bucket, k)
                arcname = k[len(prefix):] if k.startswith(prefix) else k
                zi = zipfile.ZipInfo(arcname or k)
                # zf.open honors the ZipInfo's own compress_type
                # (default STORED), not the archive default
                zi.compress_type = zipfile.ZIP_DEFLATED
                with zf.open(zi, "w", force_zip64=True) as dst:
                    for chunk in stream:
                        dst.write(chunk)
                total += size
        self.api.bandwidth.record(bucket, "tx", total)
        size = spool.tell()
        spool.seek(0)

        def gen():
            try:
                while True:
                    chunk = spool.read(1 << 20)
                    if not chunk:
                        return
                    yield chunk
            finally:
                spool.close()

        return HTTPResponse(
            headers={"Content-Type": "application/zip",
                     "Content-Length": str(size),
                     "Content-Disposition": _attachment(f"{bucket}.zip")},
            stream=gen())


def _iso(t: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(
        t, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


_HEADER_UNSAFE = re.compile(r'[\x00-\x1f\x7f"\\]')


def _attachment(filename: str) -> str:
    """Content-Disposition value with the filename made header-safe:
    object keys are attacker-chosen, and send_header performs no CR/LF
    validation — an unsanitized key would split the response headers."""
    safe = _HEADER_UNSAFE.sub("_", filename)
    return f'attachment; filename="{safe}"'


_UI_PAGE_CACHE: Optional[bytes] = None


def _ui_page() -> bytes:
    global _UI_PAGE_CACHE
    if _UI_PAGE_CACHE is None:
        import os
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "webui.html")
        with open(path, "rb") as f:
            _UI_PAGE_CACHE = f.read()
    return _UI_PAGE_CACHE


def mount(server) -> WebHandlers:
    """Attach the web surface to an S3Server (before S3 routing)."""
    web = WebHandlers(server.api)

    def route(ctx: RequestContext) -> HTTPResponse:
        try:
            return web.router(ctx)
        except (S3Error, oerr.ObjectApiError) as e:
            status = getattr(e, "status", 400) or 400
            return HTTPResponse(status=status if isinstance(status, int)
                                else 400,
                                body=str(e).encode())
        except Exception:  # noqa: BLE001 — never abort the connection
            return HTTPResponse(status=500, body=b"internal error")

    server.register_router("/minio/webrpc", route)
    server.register_router("/minio/web/", route)
    # the human-facing page: exact-path match with fall-through, so the
    # prefix never shadows admin/health/internode routers regardless of
    # mount order
    def ui_route(ctx: RequestContext) -> Optional[HTTPResponse]:
        try:
            return web.ui(ctx)
        except Exception:  # noqa: BLE001 — never abort the connection
            return HTTPResponse(status=500, body=b"internal error")

    server.register_router("/minio", ui_route)
    return web
