"""S3 HTTP frontend: signatures, handlers, XML dialect, server.

The rebuild of the reference's L1-L3 (cmd/http, cmd/routers.go,
cmd/auth-handler.go, cmd/signature-v*.go, cmd/object-handlers.go,
cmd/bucket-handlers.go) as a request-snapshot handler layer over the
object engine.
"""

from .credentials import Credentials, generate_credentials  # noqa: F401
from .handlers import S3ApiHandlers  # noqa: F401
from .server import S3Server  # noqa: F401
