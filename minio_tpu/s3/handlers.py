"""S3 API handlers — the request→ObjectLayer glue.

The rebuild of the reference's handler layer (cmd/object-handlers.go,
cmd/bucket-handlers.go, cmd/bucket-listobjects-handlers.go) on top of a
request snapshot + the object layer: auth classification and signature
verification, conditional headers, ranged reads, streaming-signed
payload decoding, multipart, copy, delete-multiple, tagging, versioning.
"""

from __future__ import annotations

import base64
import binascii
import dataclasses
import hashlib
import io
import json
import os
import re
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from email.utils import formatdate, parsedate_to_datetime
from typing import Callable, Iterator, Optional

from ..features import crypto as sse
from ..object import api_errors as oerr
from ..object.bucket_metadata import BucketMetadataSys
from ..object.engine import GetOptions, PutOptions
from ..object.hash_reader import HashReader
from ..object.multipart import CompletePart
from ..storage.datatypes import ObjectInfo
from ..utils import knobs
from ..utils import stagetimer, telemetry
from ..utils.streams import IterStream as _IterStream
from . import signature as sig
from xml.sax.saxutils import escape as _sax_escape

from . import xmlgen
from .credentials import Credentials, global_credentials
from .s3errors import S3Error, api_error_from

MAX_OBJECT_SIZE = 5 * (1 << 40)          # 5 TiB
MAX_PART_SIZE = 5 * (1 << 30)            # 5 GiB
MIN_PART_SIZE = 5 * (1 << 20)            # 5 MiB
MAX_PARTS = 10000
_BUCKET_RE = re.compile(r"^[a-z0-9][a-z0-9.\-]{1,61}[a-z0-9]$")


@dataclasses.dataclass
class HTTPResponse:
    status: int = 200
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    body: bytes = b""
    stream: Optional[Iterator[bytes]] = None   # used instead of body if set
    long_poll: bool = False   # idle event stream: exempt from admission
    # admission-refusal label riding the response so the middleware's
    # trace record can say WHY a 503 shed happened (set only by
    # ShedDecision.response — the one shed construction site)
    shed_reason: str = ""

    def with_xml(self, payload: bytes) -> "HTTPResponse":
        self.headers["Content-Type"] = "application/xml"
        self.body = payload
        return self


class RequestContext:
    """Everything a handler needs about one request."""

    def __init__(self, req: sig.Request, body_stream, content_length: int):
        self.req = req
        self.body_stream = body_stream
        self.content_length = content_length
        self.cred: Optional[Credentials] = None
        self.remote_addr = ""              # filled by the server loop
        self.secure = False                # True on a TLS listener
        self.auth_type = sig.get_request_auth_type(req)
        # hex digest the client signed over (x-amz-content-sha256);
        # enforced when the body is consumed (isReqAuthenticated analog)
        self.expect_body_sha = ""
        # QoS tenant the admission ticket resolved ("" = plane off);
        # confirmed from the verified credential post-auth
        self.tenant = ""

    def query1(self, name: str, default: str = "") -> str:
        v = self.req.query.get(name)
        return v[0] if v else default

    def has_query(self, name: str) -> bool:
        return name in self.req.query

    def header(self, name: str, default: str = "") -> str:
        return self.req.header(name, default)

    def read_body(self) -> bytes:
        if self.content_length <= 0:
            data = b""
        else:
            data = self.body_stream.read(self.content_length)
        if self.expect_body_sha:
            if hashlib.sha256(data).hexdigest() != self.expect_body_sha:
                raise S3Error("XAmzContentSHA256Mismatch")
            self.expect_body_sha = ""
        return data


def _http_date(t: float) -> str:
    return formatdate(t, usegmt=True)


def _is_hex_sha(s: str) -> bool:
    return len(s) == 64 and all(c in "0123456789abcdef" for c in s)


def _skip_take(chunks: Iterator[bytes], skip: int, take: int
               ) -> Iterator[bytes]:
    """Trim a chunk stream to [skip, skip+take)."""
    for chunk in chunks:
        if skip:
            if len(chunk) <= skip:
                skip -= len(chunk)
                continue
            chunk = chunk[skip:]
            skip = 0
        if take <= 0:
            return
        if len(chunk) > take:
            yield chunk[:take]
            return
        take -= len(chunk)
        yield chunk


def _extract_metadata(ctx: RequestContext) -> dict[str, str]:
    """User + standard metadata from headers
    (cmd/utils.go extractMetadata)."""
    md: dict[str, str] = {}
    for k, v in ctx.req.headers.items():
        if k.startswith("x-amz-meta-"):
            md["X-Amz-Meta-" + k[len("x-amz-meta-"):].title()] = v
        elif k in ("content-type", "content-encoding", "cache-control",
                   "content-disposition", "content-language", "expires"):
            md[k] = v
    if "content-type" not in md:
        md["content-type"] = "application/octet-stream"
    if ctx.header("x-amz-storage-class"):
        md["x-amz-storage-class"] = ctx.header("x-amz-storage-class")
    if ctx.header("x-amz-website-redirect-location"):
        md["x-amz-website-redirect-location"] = ctx.header(
            "x-amz-website-redirect-location")
    return md


def _parse_range(header: str, size: int) -> Optional[tuple[int, int]]:
    """`bytes=a-b` → (offset, length); None = whole object. Raises
    InvalidRange when unsatisfiable (cmd/httprange.go)."""
    if not header:
        return None
    if not header.startswith("bytes="):
        return None  # ignored per S3 semantics
    spec = header[len("bytes="):]
    if "," in spec:
        raise S3Error("NotImplemented", "multiple ranges not supported")
    try:
        first, last = spec.split("-", 1)
        if first == "":
            n = int(last)
            if n == 0:
                raise S3Error("InvalidRange")
            offset = max(size - n, 0)
            return offset, size - offset
        start = int(first)
        if last == "":
            if start >= size:
                raise S3Error("InvalidRange")
            return start, size - start
        end = int(last)
        if start > end:
            raise S3Error("InvalidRange")
        if start >= size:
            raise S3Error("InvalidRange")
        return start, min(end, size - 1) - start + 1
    except ValueError:
        return None


class _ReleasingStream:
    """Response-body wrapper that returns its admission ticket when the
    stream is exhausted or closed (whichever comes first; the ticket's
    release is idempotent)."""

    def __init__(self, inner, ticket):
        self._inner = inner
        self._ticket = ticket

    def __iter__(self):
        try:
            for chunk in self._inner:
                yield chunk
        finally:
            self.close()

    def close(self) -> None:
        try:
            close = getattr(self._inner, "close", None)
            if close is not None:
                close()
        finally:
            self._ticket.release()


class S3ApiHandlers:
    def __init__(self, object_layer, region: str = "us-east-1",
                 creds: Optional[Credentials] = None,
                 iam=None, max_clients: Optional[int] = None):
        self.obj = object_layer
        self.region = region
        self.root_cred = creds or global_credentials()
        self.iam = iam            # optional IAMSys (policy checks + users)
        self.bucket_meta = BucketMetadataSys(object_layer)
        # The unified admission plane (s3/edge/admission.py): the ONE
        # place every shed decision — staging window, scheduler
        # occupancy, the maxClients budget — is made, shared with the
        # event-loop edge so both frontends refuse identically. The
        # cluster boot overrides the default gate size with the full
        # RAM+CPU budget (requests_budget) via set_max_clients().
        from .edge.admission import AdmissionController
        self.admission = AdmissionController(max_clients)
        # The multi-tenant QoS plane (s3/qos.py): per-tenant shares and
        # budgets enforced AT the admission gate. The iam lookup is
        # late-bound — the cluster boot sets self.iam after this
        # constructor runs. Off by default (MINIO_TPU_QOS).
        from .qos import QoSPlane, QoSRegistry
        self.qos = QoSPlane(QoSRegistry(object_layer),
                            iam_lookup=lambda: self.iam,
                            root_access_key=self.root_cred.access_key)
        self.admission.qos = self.qos
        self.events = None        # optional event notifier hook
        self.notify = None        # optional NotificationPlane
                                  # (minio_tpu/notify/, feed-driven)
        self.usage = None         # optional DataUsageCrawler (quota cache)
        self.replication = None   # optional ReplicationPlane (or the
        # legacy ReplicationPool — _notify duck-types the difference)
        self.tiers = None         # optional TierManager (ILM tiering)
        self.restore_worker = None  # optional TransitionWorker: async
        # RestoreObject (202 + background tier pull) for large objects
        from .trace import TraceSys
        self.trace = TraceSys()   # request tracing + audit hub
        from ..utils.bandwidth import BandwidthMonitor
        self.bandwidth = BandwidthMonitor()  # per-bucket byte rates
        self.config = None        # optional ConfigSys (admin KV)
        # upload-session metadata cache: immutable after create, so part
        # uploads don't re-read the session journal per part
        from collections import OrderedDict
        self._mpu_meta: "OrderedDict[str, dict]" = OrderedDict()
        # resolved SSE-S3 object keys per upload (bounds KMS round
        # trips to one per upload, not one per part)
        self._mpu_keys: "OrderedDict[str, tuple]" = OrderedDict()
        self.kms = sse.kms_from_env()        # SSE-S3 KMS seam
        self.compression_enabled = os.environ.get(
            "MINIO_COMPRESS", "").lower() in ("on", "true", "1")
        # "s2" (snappy framing, reference-interoperable — the default)
        # or "zstd" (better ratio, no cross-binary interop)
        self.compression_algorithm = os.environ.get(
            "MINIO_COMPRESS_ALGORITHM", "s2").lower()
        self.cors_allow_origin = "*"   # config api.cors_allow_origin
        self.federation = None    # optional BucketFederation (etcd DNS)
        # device scan plane (scan/): SelectObjectContent rides the
        # compiled-kernel path with the CPU evaluator as fallback; the
        # cluster boot swaps in an instance wired to the shared batch
        # former so concurrent Selects coalesce
        from ..scan import ScanEngine
        self.scan = ScanEngine()

    def set_max_clients(self, n: int) -> None:
        """Re-size the admission gate once topology is known (the
        reference computes maxClients from RAM + drive count,
        cmd/handler-api.go:46-57)."""
        self.admission.resize(n)

    def set_object_layer(self, object_layer) -> None:
        """Late-bind the ObjectLayer (cluster boot mounts the HTTP routers
        before the drive/format bootstrap finishes — the reference's
        server also serves peers before newObjectLayer returns)."""
        self.obj = object_layer
        self.bucket_meta.obj = object_layer
        # the scheduler-occupancy admission signal probes the live
        # layer's batch formers
        self.admission.layer = object_layer
        # the QoS budget registry persists to the live layer's pools
        self.qos.registry.obj = object_layer

    # ------------------------------------------------------------------
    # auth
    # ------------------------------------------------------------------

    def _is_owner(self, cred: Credentials) -> bool:
        """Root and its derived temp/service creds (reference
        cred.ParentUser == globalActiveCred.AccessKey => IsOwner)."""
        return cred.access_key == self.root_cred.access_key or \
            cred.parent_user == self.root_cred.access_key

    def _cred_lookup(self, access_key: str) -> Credentials:
        if access_key == self.root_cred.access_key:
            return self.root_cred
        if self.iam is not None:
            cred = self.iam.get_credentials(access_key)
            if cred is not None and cred.is_valid():
                return cred
        raise sig.SigError("InvalidAccessKeyId")

    def authenticate(self, ctx: RequestContext,
                     action: str = "", bucket: str = "",
                     object_name: str = "") -> None:
        """Verify the request signature and (if IAM is wired) that the
        caller may perform `action` (cmd/auth-handler.go checkRequestAuthType)."""
        with stagetimer.stage("auth"):
            self._authenticate(ctx, action, bucket, object_name)

    def _authenticate(self, ctx: RequestContext,
                      action: str = "", bucket: str = "",
                      object_name: str = "") -> None:
        at = ctx.auth_type
        if at == sig.AUTH_SIGNED:
            body_sha = ctx.header("x-amz-content-sha256",
                                  sig.UNSIGNED_PAYLOAD)
            ctx.cred = sig.verify_v4(ctx.req, self._cred_lookup,
                                     self.region, body_sha)
            # a signed hex digest must match the actual body; object PUT
            # verifies via HashReader, every other consumer via read_body
            if _is_hex_sha(body_sha):
                ctx.expect_body_sha = body_sha
        elif at == sig.AUTH_STREAMING_SIGNED:
            ctx.cred = sig.verify_v4(ctx.req, self._cred_lookup,
                                     self.region,
                                     sig.STREAMING_CONTENT_SHA256)
        elif at == sig.AUTH_PRESIGNED:
            ctx.cred = sig.verify_v4_presigned(ctx.req, self._cred_lookup,
                                               self.region)
        elif at == sig.AUTH_SIGNED_V2:
            ctx.cred = sig.verify_v2(ctx.req, self._cred_lookup)
        elif at == sig.AUTH_ANONYMOUS:
            if not self._anonymous_allowed(ctx, action, bucket,
                                           object_name):
                raise S3Error("AccessDenied")
            ctx.cred = Credentials()
            if self.qos.enabled():
                ctx.tenant = self.qos.tenant_for_cred(None)
            return
        else:
            raise S3Error("SignatureVersionNotSupported")
        # temp (STS) credentials must present their session token —
        # header for signed requests, X-Amz-Security-Token query param
        # for presigned URLs (signature.py:291)
        if ctx.cred.is_temp():
            token = ctx.header("x-amz-security-token") or \
                ctx.query1("X-Amz-Security-Token")
            if token != ctx.cred.session_token:
                raise S3Error("InvalidTokenId")
        if self.iam is not None and ctx.cred.access_key and \
                not self._is_owner(ctx.cred):
            if not self.iam.is_allowed(ctx.cred, action, bucket,
                                       object_name,
                                       self._policy_conditions(ctx)):
                raise S3Error("AccessDenied")
        # confirm the tenant from the VERIFIED credential (the
        # admission gate charged the budget of the *claimed* key; a
        # forged claim never reaches here)
        if self.qos.enabled():
            ctx.tenant = self.qos.tenant_for_cred(ctx.cred)

    @staticmethod
    def _policy_conditions(ctx: "RequestContext") -> dict:
        """Request facts for policy Condition evaluation (reference
        getConditionValues, cmd/auth-handler.go)."""
        cond = {}
        if ctx.remote_addr:
            cond["aws:SourceIp"] = ctx.remote_addr
        referer = ctx.header("referer")
        if referer:
            cond["aws:Referer"] = referer
        # real connection state, never a client-supplied header
        cond["aws:SecureTransport"] = "true" if ctx.secure else "false"
        return cond

    def _anonymous_allowed(self, ctx: "RequestContext", action: str,
                           bucket: str, object_name: str) -> bool:
        if not bucket or self.iam is None:
            return False
        return self.iam.is_anonymous_allowed(
            self.bucket_meta.get(bucket).policy_json, action, bucket,
            object_name, self._policy_conditions(ctx))

    # ------------------------------------------------------------------
    # STS (POST / with Action=AssumeRole; cmd/sts-handlers.go:43-86)
    # ------------------------------------------------------------------

    def handle_sts(self, ctx: RequestContext) -> HTTPResponse:
        """STS action dispatch (reference cmd/sts-handlers.go:43-86):
        AssumeRole is SigV4-authenticated; the federation actions
        (WebIdentity/ClientGrants JWT, LDAP bind) are authenticated by
        the presented token/credentials themselves."""
        if self.iam is None:
            raise S3Error("NotImplemented", "STS requires IAM")
        body_sha = ctx.header("x-amz-content-sha256",
                              sig.UNSIGNED_PAYLOAD)
        if _is_hex_sha(body_sha):
            ctx.expect_body_sha = body_sha     # enforced by read_body
        body = ctx.read_body()
        form = {k: v[0] for k, v in
                urllib.parse.parse_qs(body.decode(errors="replace")).items()}
        action = form.get("Action", "")
        try:
            duration = int(form.get("DurationSeconds", "3600"))
        except ValueError:
            raise S3Error("InvalidArgument", "bad DurationSeconds") from None

        if action == "AssumeRole":
            # SigV4 over the form body (service "sts" or "s3" both
            # accepted); any valid non-temporary user may assume a role
            # — the minted credential inherits the PARENT's policies,
            # so no policy check gates the call itself
            cred = sig.verify_v4(ctx.req, self._cred_lookup, self.region,
                                 body_sha)
            if cred.is_temp():
                raise S3Error("AccessDenied",
                              "temporary credentials cannot assume roles")
            minted = self.iam.assume_role(cred, duration)
            return self._sts_response(action, minted)

        if action in ("AssumeRoleWithWebIdentity",
                      "AssumeRoleWithClientGrants"):
            from ..iam.providers import STSValidationError
            token = form.get("WebIdentityToken") or form.get("Token", "")
            if not token:
                raise S3Error("InvalidArgument", "missing identity token")
            provider = self._openid_provider()
            if provider is None:
                raise S3Error("NotImplemented",
                              "OpenID is not configured")
            try:
                claims = provider.validate(token)
            except STSValidationError as e:
                raise S3Error("AccessDenied", str(e)) from None
            policies = provider.policy_names(claims)
            if not policies:
                # no policy claim -> no permissions mapping; reject like
                # the reference (policy claim is mandatory)
                raise S3Error(
                    "AccessDenied",
                    f"token lacks a '{provider.claim_name}' claim")
            subject = str(claims.get("sub") or claims.get("email") or "")
            if not subject:
                raise S3Error("AccessDenied", "token lacks sub claim")
            # minted credentials never outlive the token that
            # authenticated them
            import time as _time
            minted = self.iam.assume_role_with_claims(
                f"oidc:{subject}", policies, duration,
                max_seconds=float(claims["exp"]) - _time.time())
            return self._sts_response(action, minted, subject=subject)

        if action == "AssumeRoleWithLDAPIdentity":
            from ..iam.providers import STSValidationError
            provider = self._ldap_provider()
            if provider is None:
                raise S3Error("NotImplemented", "LDAP is not configured")
            try:
                dn = provider.bind(form.get("LDAPUsername", ""),
                                   form.get("LDAPPassword", ""))
            except STSValidationError as e:
                raise S3Error("AccessDenied", str(e)) from None
            # policies: the policy-DB mapping for the DN (set by the
            # admin), never from the client
            minted = self.iam.assume_role_with_claims(
                f"ldap:{dn}", None, duration)
            return self._sts_response(action, minted, subject=dn)

        raise S3Error("InvalidArgument",
                      f"unsupported STS action {action!r}")

    def _openid_provider(self):
        """identity_openid provider from config (rebuilt per call: the
        config may be live-edited via admin set-config)."""
        if getattr(self, "openid_provider", None) is not None:
            return self.openid_provider
        if self.config is None:
            return None
        from ..iam.providers import OpenIDProvider
        p = OpenIDProvider(self.config.get_subsys("identity_openid"))
        return p if p.enabled() else None

    def _ldap_provider(self):
        if getattr(self, "ldap_provider", None) is not None:
            return self.ldap_provider
        if self.config is None:
            return None
        from ..iam.providers import LDAPProvider
        p = LDAPProvider(self.config.get_subsys("identity_ldap"))
        return p if p.enabled() else None

    def _sts_response(self, action: str, minted,
                      subject: str = "") -> HTTPResponse:
        import datetime as _dt
        exp = _dt.datetime.fromtimestamp(
            minted.expiration, _dt.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ")
        subject_xml = ""
        if subject and action == "AssumeRoleWithWebIdentity":
            subject_xml = ("<SubjectFromWebIdentityToken>"
                           f"{_sax_escape(subject)}"
                           "</SubjectFromWebIdentityToken>")
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            f'<{action}Response xmlns='
            '"https://sts.amazonaws.com/doc/2011-06-15/">'
            f"<{action}Result><Credentials>"
            f"<AccessKeyId>{minted.access_key}</AccessKeyId>"
            f"<SecretAccessKey>{minted.secret_key}</SecretAccessKey>"
            f"<SessionToken>{minted.session_token}</SessionToken>"
            f"<Expiration>{exp}</Expiration>"
            f"</Credentials>{subject_xml}</{action}Result>"
            "<ResponseMetadata><RequestId>"
            f"{uuid.uuid4()}</RequestId></ResponseMetadata>"
            f"</{action}Response>")
        return HTTPResponse(body=xml.encode(),
                            headers={"Content-Type": "application/xml"})

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def handle(self, ctx: RequestContext) -> HTTPResponse:
        # Admission covers the FULL request lifetime — the reference's
        # maxClients gate wraps ServeHTTP including the response body
        # (cmd/handler-api.go:100), so a streaming GET holds its slot
        # until the body is fully written (slot released by the
        # _ReleasingStream when the server closes/exhausts it). The
        # event-loop edge admits BEFORE dispatching here (before any
        # body byte was read) and parks its ticket on the context; the
        # threaded frontend admits now — its body reader is lazy, so
        # the decision is still pre-body.
        from .edge.admission import AdmissionTicket
        ticket = getattr(ctx, "admission_ticket", None)
        if ticket is None:
            got = self.admission.admit(ctx.req.method, ctx.req.path,
                                       ctx.req.query, ctx.req.headers)
            if not isinstance(got, AdmissionTicket):
                # shed: 503 SlowDown + Retry-After + Connection: close
                # (unloading the server instead of draining a multi-GiB
                # body into a closing socket)
                return got.response(ctx.req.path)
            ticket = got
        # QoS data-path metering: the ticket carries the tenant the
        # admission gate resolved; its rx/tx buckets pace the admitted
        # body and response streams (admission already refused what
        # should never start — pacing only slows what's over budget)
        tenant = getattr(ticket, "tenant", "")
        if tenant:
            ctx.tenant = tenant
            if ctx.content_length > 0 and ctx.body_stream is not None:
                ctx.body_stream = self.qos.paced_body(tenant,
                                                      ctx.body_stream)
        release = True
        try:
            try:
                resp = self._route(ctx)
            except Exception as e:  # noqa: BLE001 — map to S3 error XML
                return self._error_response(ctx, api_error_from(e))
            if resp.stream is not None and not resp.long_poll:
                if tenant:
                    resp.stream = self.qos.paced_stream(tenant,
                                                        resp.stream)
                resp.stream = _ReleasingStream(resp.stream, ticket)
                release = False
            return resp
        finally:
            if release:
                ticket.release()

    def _error_response(self, ctx: RequestContext,
                        err: S3Error) -> HTTPResponse:
        body = xmlgen.error_response(err.code, err.message, ctx.req.path,
                                     str(uuid.uuid4()))
        r = HTTPResponse(status=err.status)
        return r.with_xml(body)

    def _route(self, ctx: RequestContext) -> HTTPResponse:
        path = urllib.parse.unquote(ctx.req.path)
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        m = ctx.req.method

        # federation middleware (setBucketForwardingHandler,
        # cmd/routers.go:46): a bucket another cluster owns is proxied
        # there BEFORE auth — the owner verifies the client's SigV4
        # (federated deployments share credentials)
        if bucket and self.federation is not None:
            fwd = self.federation.maybe_forward(ctx, bucket, self.obj)
            if fwd is not None:
                return fwd

        if not bucket:
            if m == "GET":
                return self.list_buckets(ctx)
            if m == "POST":
                return self.handle_sts(ctx)
            raise S3Error("MethodNotAllowed")

        if key:
            return self._route_object(ctx, m, bucket, key)
        return self._route_bucket(ctx, m, bucket)

    def _route_bucket(self, ctx, m, bucket) -> HTTPResponse:
        if m == "GET":
            if ctx.has_query("location"):
                return self.get_bucket_location(ctx, bucket)
            if ctx.has_query("versioning"):
                return self.get_bucket_versioning(ctx, bucket)
            if ctx.has_query("versions"):
                return self.list_object_versions(ctx, bucket)
            if ctx.has_query("uploads"):
                return self.list_multipart_uploads(ctx, bucket)
            if ctx.has_query("policy"):
                return self.get_bucket_policy(ctx, bucket)
            if ctx.has_query("tagging"):
                return self.get_bucket_tagging(ctx, bucket)
            if ctx.has_query("lifecycle"):
                return self.get_bucket_lifecycle(ctx, bucket)
            if ctx.has_query("encryption"):
                return self.get_bucket_encryption(ctx, bucket)
            if ctx.has_query("object-lock"):
                return self.get_object_lock_config(ctx, bucket)
            if ctx.has_query("replication"):
                return self.get_bucket_replication(ctx, bucket)
            if ctx.has_query("notification"):
                return self.get_bucket_notification(ctx, bucket)
            if ctx.has_query("events"):
                return self.listen_bucket_notification(ctx, bucket)
            if ctx.query1("list-type") == "2":
                return self.list_objects_v2(ctx, bucket)
            return self.list_objects_v1(ctx, bucket)
        if m == "PUT":
            if ctx.has_query("versioning"):
                return self.put_bucket_versioning(ctx, bucket)
            if ctx.has_query("policy"):
                return self.put_bucket_policy(ctx, bucket)
            if ctx.has_query("tagging"):
                return self.put_bucket_tagging(ctx, bucket)
            if ctx.has_query("lifecycle"):
                return self.put_bucket_lifecycle(ctx, bucket)
            if ctx.has_query("encryption"):
                return self.put_bucket_encryption(ctx, bucket)
            if ctx.has_query("object-lock"):
                return self.put_object_lock_config(ctx, bucket)
            if ctx.has_query("replication"):
                return self.put_bucket_replication(ctx, bucket)
            if ctx.has_query("notification"):
                return self.put_bucket_notification(ctx, bucket)
            return self.make_bucket(ctx, bucket)
        if m == "HEAD":
            return self.head_bucket(ctx, bucket)
        if m == "DELETE":
            if ctx.has_query("policy"):
                return self.delete_bucket_policy(ctx, bucket)
            if ctx.has_query("tagging"):
                return self.delete_bucket_tagging(ctx, bucket)
            if ctx.has_query("lifecycle"):
                return self.delete_bucket_lifecycle(ctx, bucket)
            if ctx.has_query("encryption"):
                return self.delete_bucket_encryption(ctx, bucket)
            if ctx.has_query("replication"):
                return self.delete_bucket_replication(ctx, bucket)
            return self.delete_bucket(ctx, bucket)
        if m == "POST":
            if ctx.has_query("delete"):
                return self.delete_multiple_objects(ctx, bucket)
            if "multipart/form-data" in ctx.header("content-type"):
                return self.post_policy_upload(ctx, bucket)
        raise S3Error("MethodNotAllowed")

    def listen_bucket_notification(self, ctx, bucket) -> HTTPResponse:
        """Live event stream for one bucket (ListenBucketNotification,
        cmd/listen-notification-handlers.go): ND-JSON event records,
        filtered by prefix/suffix/event-name query params, ends after an
        idle window."""
        import fnmatch as _fn
        import json as _json
        self.authenticate(ctx, "s3:ListenBucketNotification", bucket)
        self.obj.get_bucket_info(bucket)
        if self.events is None:
            raise S3Error("NotImplemented", "event system not running")
        prefix = ctx.query1("prefix")
        suffix = ctx.query1("suffix")
        patterns = ctx.req.query.get("events") or ["*"]
        try:
            idle = float(ctx.query1("idle", "10") or 10)
        except ValueError:
            raise S3Error("InvalidArgument", "bad idle value") from None
        idle = min(max(idle, 1.0), 3600.0)
        hub = self.events.hub

        def stream():
            with hub.subscribe() as sub:
                while True:
                    item = sub.get(timeout=idle)
                    if item is None:
                        return
                    b, record = item
                    if b != bucket:
                        continue
                    rec = record["Records"][0]
                    key = rec["s3"]["object"]["key"]
                    if prefix and not key.startswith(prefix):
                        continue
                    if suffix and not key.endswith(suffix):
                        continue
                    if not any(_fn.fnmatchcase(rec["eventName"], p)
                               or p == "*"
                               for p in patterns):
                        continue
                    yield (_json.dumps(record) + "\n").encode()

        # long_poll: a listener mostly idles — it must not pin one of
        # the (CPU-sized) admission slots for its whole lifetime
        return HTTPResponse(
            headers={"Content-Type": "application/x-ndjson"},
            stream=stream(), long_poll=True)

    def post_policy_upload(self, ctx, bucket) -> HTTPResponse:
        """Browser form upload (PostPolicyBucketHandler,
        cmd/bucket-handlers.go)."""
        from . import postpolicy as pp
        body = ctx.read_body()
        fields, file_bytes, file_name = pp.parse_multipart_form(
            body, ctx.header("content-type"))
        cred = pp.verify_post_signature(fields, self._cred_lookup,
                                        self.region)
        lower = {k.lower(): v for k, v in fields.items()}
        if cred.is_temp() and \
                lower.get("x-amz-security-token") != cred.session_token:
            raise S3Error("InvalidTokenId")
        key = lower.get("key", "")
        if not key:
            raise S3Error("MalformedPOSTRequest", "missing key field")
        key = key.replace("${filename}", file_name)
        # Bind the policy check to the REQUEST's bucket, not a client-
        # supplied form field (PostPolicyBucketHandler does the same) —
        # otherwise a policy signed for bucket A replays against bucket B.
        fields = {k: v for k, v in fields.items()
                  if k.lower() != "bucket"}
        fields["bucket"] = bucket
        pp.check_post_policy(lower.get("policy", ""), fields,
                             len(file_bytes))
        if self.iam is not None and not self._is_owner(cred):
            if not self.iam.is_allowed(cred, "s3:PutObject", bucket, key,
                                       self._policy_conditions(ctx)):
                raise S3Error("AccessDenied")
        self.obj.get_bucket_info(bucket)
        self._enforce_quota(bucket, len(file_bytes))
        metadata = {"content-type": lower.get(
            "content-type", "application/octet-stream")}
        for k, v in fields.items():
            if k.lower().startswith("x-amz-meta-"):
                metadata["X-Amz-Meta-" +
                         k[len("x-amz-meta-"):].title()] = v
        versioned = self.bucket_meta.versioning_enabled(bucket)
        info = self.obj.put_object(
            bucket, key, file_bytes,
            opts=PutOptions(metadata=metadata, versioned=versioned))
        self._notify("s3:ObjectCreated:Post", bucket, key)
        status = int(lower.get("success_action_status", "204"))
        if status not in (200, 201, 204):
            status = 204
        headers = {"ETag": f'"{info.etag}"',
                   "Location": f"/{bucket}/{key}"}
        if status == 201:
            xml = (f'<?xml version="1.0" encoding="UTF-8"?>'
                   f"<PostResponse><Location>/{bucket}/{key}</Location>"
                   f"<Bucket>{bucket}</Bucket><Key>{key}</Key>"
                   f'<ETag>"{info.etag}"</ETag></PostResponse>')
            return HTTPResponse(status=201, headers=headers,
                                body=xml.encode())
        return HTTPResponse(status=status, headers=headers)

    def _route_object(self, ctx, m, bucket, key) -> HTTPResponse:
        if m == "GET":
            if ctx.has_query("uploadId"):
                return self.list_object_parts(ctx, bucket, key)
            if ctx.has_query("tagging"):
                return self.get_object_tagging(ctx, bucket, key)
            if ctx.has_query("retention"):
                return self.get_object_retention(ctx, bucket, key)
            if ctx.has_query("legal-hold"):
                return self.get_object_legal_hold(ctx, bucket, key)
            return self.get_object(ctx, bucket, key)
        if m == "HEAD":
            return self.head_object(ctx, bucket, key)
        if m == "PUT":
            if ctx.has_query("uploadId") and ctx.has_query("partNumber"):
                if ctx.header("x-amz-copy-source"):
                    return self.copy_object_part(ctx, bucket, key)
                return self.put_object_part(ctx, bucket, key)
            if ctx.has_query("tagging"):
                return self.put_object_tagging(ctx, bucket, key)
            if ctx.has_query("retention"):
                return self.put_object_retention(ctx, bucket, key)
            if ctx.has_query("legal-hold"):
                return self.put_object_legal_hold(ctx, bucket, key)
            if ctx.header("x-amz-copy-source"):
                return self.copy_object(ctx, bucket, key)
            return self.put_object(ctx, bucket, key)
        if m == "POST":
            if ctx.has_query("uploads"):
                return self.new_multipart_upload(ctx, bucket, key)
            if ctx.has_query("uploadId"):
                return self.complete_multipart_upload(ctx, bucket, key)
            if ctx.has_query("restore"):
                return self.restore_object(ctx, bucket, key)
            if ctx.has_query("select") or \
                    ctx.query1("select-type") == "2":
                return self.select_object_content(ctx, bucket, key)
        if m == "DELETE":
            if ctx.has_query("uploadId"):
                return self.abort_multipart_upload(ctx, bucket, key)
            if ctx.has_query("tagging"):
                return self.delete_object_tagging(ctx, bucket, key)
            return self.delete_object(ctx, bucket, key)
        raise S3Error("MethodNotAllowed")

    # ------------------------------------------------------------------
    # service + bucket handlers
    # ------------------------------------------------------------------

    def list_buckets(self, ctx) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListAllMyBuckets")
        buckets = self.obj.list_buckets()
        if self.federation is not None:
            # federated mode merges DNS bucket names into the listing
            # (reference ListBucketsHandler in federated deployments) —
            # clients discover remote-cluster buckets they can then
            # address transparently through this endpoint
            local = {b.name for b in buckets}
            try:
                remote = [n for n in self.federation.list_buckets()
                          if n not in local]
            except Exception:  # noqa: BLE001 — etcd down: local only
                remote = []
            import types
            for name in remote:
                buckets.append(types.SimpleNamespace(name=name,
                                                     created=0.0))
        return HTTPResponse().with_xml(xmlgen.list_buckets_response(
            "minio", buckets))

    def make_bucket(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:CreateBucket", bucket)
        if not _BUCKET_RE.match(bucket) or ".." in bucket:
            raise S3Error("InvalidBucketName")
        body = ctx.read_body()
        if body:
            # LocationConstraint must match our region if present
            try:
                root = ET.fromstring(body)
                loc = root.find(f"{{{xmlgen.S3_XMLNS}}}LocationConstraint")
                loc_txt = (loc.text or "") if loc is not None else ""
                if loc_txt and loc_txt != self.region:
                    raise S3Error("InvalidRegion",
                                  f"region must be {self.region}")
            except ET.ParseError:
                raise S3Error("MalformedXML")
        if ctx.header("x-amz-bucket-object-lock-enabled") == "true":
            self.obj.make_bucket(bucket)
            self.bucket_meta.update(
                bucket, versioning="Enabled",
                object_lock_xml="<ObjectLockConfiguration>"
                "<ObjectLockEnabled>Enabled</ObjectLockEnabled>"
                "</ObjectLockConfiguration>")
        else:
            self.obj.make_bucket(bucket)
        if self.federation is not None:
            try:
                self.federation.register(bucket)
            except Exception:  # noqa: BLE001 — DNS best-effort, like ref
                pass
        self._notify("s3:BucketCreated:*", bucket, "")
        return HTTPResponse(headers={"Location": f"/{bucket}"})

    def head_bucket(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListBucket", bucket)
        self.obj.get_bucket_info(bucket)
        return HTTPResponse()

    def delete_bucket(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:DeleteBucket", bucket)
        force = ctx.header("x-minio-force-delete") == "true"
        self.obj.delete_bucket(bucket, force=force)
        self.bucket_meta.delete(bucket)
        if self.federation is not None:
            try:
                self.federation.unregister(bucket)
            except Exception:  # noqa: BLE001 — DNS best-effort
                pass
        self._notify("s3:BucketRemoved:*", bucket, "")
        return HTTPResponse(status=204)

    def get_bucket_location(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetBucketLocation", bucket)
        self.obj.get_bucket_info(bucket)
        region = "" if self.region == "us-east-1" else self.region
        return HTTPResponse().with_xml(xmlgen.location_response(region))

    def get_bucket_versioning(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetBucketVersioning", bucket)
        self.obj.get_bucket_info(bucket)
        return HTTPResponse().with_xml(xmlgen.versioning_response(
            self.bucket_meta.get(bucket).versioning))

    def put_bucket_versioning(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutBucketVersioning", bucket)
        self.obj.get_bucket_info(bucket)
        body = ctx.read_body()
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML")
        status_el = root.find(f"{{{xmlgen.S3_XMLNS}}}Status")
        if status_el is None:
            status_el = root.find("Status")
        status = (status_el.text or "") if status_el is not None else ""
        if status not in ("Enabled", "Suspended"):
            raise S3Error("MalformedXML", "bad versioning status")
        self.bucket_meta.update(bucket, versioning=status)
        return HTTPResponse()

    # --- policy / tagging / configs ------------------------------------

    def get_bucket_policy(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetBucketPolicy", bucket)
        self.obj.get_bucket_info(bucket)
        pj = self.bucket_meta.get(bucket).policy_json
        if not pj:
            raise S3Error("NoSuchBucketPolicy")
        return HTTPResponse(headers={"Content-Type": "application/json"},
                            body=pj.encode())

    def put_bucket_policy(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutBucketPolicy", bucket)
        self.obj.get_bucket_info(bucket)
        body = ctx.read_body()
        import json
        try:
            json.loads(body)
        except ValueError:
            raise S3Error("MalformedPolicy", "policy is not JSON")
        self.bucket_meta.update(bucket, policy_json=body.decode())
        return HTTPResponse(status=204)

    def delete_bucket_policy(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:DeleteBucketPolicy", bucket)
        self.obj.get_bucket_info(bucket)
        self.bucket_meta.update(bucket, policy_json="")
        return HTTPResponse(status=204)

    def get_bucket_tagging(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetBucketTagging", bucket)
        self.obj.get_bucket_info(bucket)
        tags = self.bucket_meta.get(bucket).tagging
        if not tags:
            raise S3Error("NoSuchTagSet")
        return HTTPResponse().with_xml(xmlgen.tagging_response(tags))

    def put_bucket_tagging(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutBucketTagging", bucket)
        self.obj.get_bucket_info(bucket)
        tags = _parse_tagging_xml(ctx.read_body())
        self.bucket_meta.update(bucket, tagging=tags)
        return HTTPResponse()

    def delete_bucket_tagging(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutBucketTagging", bucket)
        self.obj.get_bucket_info(bucket)
        self.bucket_meta.update(bucket, tagging={})
        return HTTPResponse(status=204)

    def _xml_config(self, ctx, bucket, field: str, action: str,
                    missing_code: str) -> HTTPResponse:
        self.authenticate(ctx, action, bucket)
        self.obj.get_bucket_info(bucket)
        xml_doc = getattr(self.bucket_meta.get(bucket), field)
        if not xml_doc:
            raise S3Error(missing_code)
        return HTTPResponse(headers={"Content-Type": "application/xml"},
                            body=xml_doc.encode())

    def _put_xml_config(self, ctx, bucket, field: str,
                        action: str) -> HTTPResponse:
        self.authenticate(ctx, action, bucket)
        self.obj.get_bucket_info(bucket)
        body = ctx.read_body()
        try:
            ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML")
        self.bucket_meta.update(bucket, **{field: body.decode()})
        return HTTPResponse()

    def _del_xml_config(self, ctx, bucket, field: str,
                        action: str) -> HTTPResponse:
        self.authenticate(ctx, action, bucket)
        self.obj.get_bucket_info(bucket)
        self.bucket_meta.update(bucket, **{field: ""})
        return HTTPResponse(status=204)

    def get_bucket_lifecycle(self, ctx, bucket):
        return self._xml_config(ctx, bucket, "lifecycle_xml",
                                "s3:GetLifecycleConfiguration",
                                "NoSuchLifecycleConfiguration")

    def put_bucket_lifecycle(self, ctx, bucket):
        return self._put_xml_config(ctx, bucket, "lifecycle_xml",
                                    "s3:PutLifecycleConfiguration")

    def delete_bucket_lifecycle(self, ctx, bucket):
        return self._del_xml_config(ctx, bucket, "lifecycle_xml",
                                    "s3:PutLifecycleConfiguration")

    def get_bucket_encryption(self, ctx, bucket):
        return self._xml_config(
            ctx, bucket, "sse_config_xml", "s3:GetEncryptionConfiguration",
            "ServerSideEncryptionConfigurationNotFoundError")

    def put_bucket_encryption(self, ctx, bucket):
        return self._put_xml_config(ctx, bucket, "sse_config_xml",
                                    "s3:PutEncryptionConfiguration")

    def delete_bucket_encryption(self, ctx, bucket):
        return self._del_xml_config(ctx, bucket, "sse_config_xml",
                                    "s3:PutEncryptionConfiguration")

    def get_object_lock_config(self, ctx, bucket):
        return self._xml_config(ctx, bucket, "object_lock_xml",
                                "s3:GetBucketObjectLockConfiguration",
                                "NoSuchObjectLockConfiguration")

    def put_object_lock_config(self, ctx, bucket):
        return self._put_xml_config(ctx, bucket, "object_lock_xml",
                                    "s3:PutBucketObjectLockConfiguration")

    def get_bucket_replication(self, ctx, bucket):
        return self._xml_config(ctx, bucket, "replication_xml",
                                "s3:GetReplicationConfiguration",
                                "ReplicationConfigurationNotFoundError")

    def put_bucket_replication(self, ctx, bucket):
        return self._put_xml_config(ctx, bucket, "replication_xml",
                                    "s3:PutReplicationConfiguration")

    def delete_bucket_replication(self, ctx, bucket):
        return self._del_xml_config(ctx, bucket, "replication_xml",
                                    "s3:PutReplicationConfiguration")

    def get_bucket_notification(self, ctx, bucket):
        self.authenticate(ctx, "s3:GetBucketNotification", bucket)
        self.obj.get_bucket_info(bucket)
        doc = self.bucket_meta.get(bucket).notification_xml
        if not doc:
            doc = ('<?xml version="1.0" encoding="UTF-8"?>'
                   f'<NotificationConfiguration xmlns="{xmlgen.S3_XMLNS}"/>')
        return HTTPResponse(headers={"Content-Type": "application/xml"},
                            body=doc.encode())

    def put_bucket_notification(self, ctx, bucket):
        self.authenticate(ctx, "s3:PutBucketNotification", bucket)
        self.obj.get_bucket_info(bucket)
        body = ctx.read_body()
        try:
            ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML")
        if self.notify is not None:
            # the reference rejects configs naming unknown target ARNs
            # or event names at PUT time (ErrARNNotification /
            # ErrEventNotification) — a rule that can never fire is a
            # config error, not a silent no-op. Legacy config-driven
            # notifier targets stay valid.
            from ..notify.rules import BucketNotifyConfig, NotifyRuleError
            try:
                cfg = BucketNotifyConfig.from_xml(body)
            except NotifyRuleError as e:
                raise S3Error("MalformedXML", str(e)) from None
            known = self.notify.registry.arns()
            legacy = getattr(self.events, "targets", None) or {}
            for rule in cfg.rules:
                if rule.arn not in known and rule.arn not in legacy:
                    raise S3Error(
                        "InvalidArgument",
                        f"unknown notification target ARN {rule.arn}")
            bad = cfg.unknown_events()
            if bad:
                raise S3Error(
                    "InvalidArgument",
                    f"unsupported notification event(s): "
                    f"{', '.join(sorted(set(bad)))}")
        self.bucket_meta.update(bucket, notification_xml=body.decode())
        return HTTPResponse()

    # --- listings -------------------------------------------------------

    def list_objects_v1(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListBucket", bucket)
        prefix = ctx.query1("prefix")
        marker = ctx.query1("marker")
        delimiter = ctx.query1("delimiter")
        enc = ctx.query1("encoding-type")
        max_keys = _parse_max_keys(ctx.query1("max-keys", "1000"))
        if max_keys == 0:
            self.obj.get_bucket_info(bucket)
            objs, prefixes, trunc = [], [], False
        else:
            objs, prefixes, trunc = self.obj.list_objects(
                bucket, prefix, marker, delimiter, max_keys)
        next_marker = ""
        if trunc:
            if objs and (not prefixes or objs[-1].name > prefixes[-1]):
                next_marker = objs[-1].name
            elif prefixes:
                next_marker = prefixes[-1]
        return HTTPResponse().with_xml(xmlgen.list_objects_v1_response(
            bucket, prefix, marker, delimiter, max_keys, enc, objs,
            prefixes, trunc, next_marker))

    def list_objects_v2(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListBucket", bucket)
        prefix = ctx.query1("prefix")
        delimiter = ctx.query1("delimiter")
        enc = ctx.query1("encoding-type")
        start_after = ctx.query1("start-after")
        token = ctx.query1("continuation-token")
        fetch_owner = ctx.query1("fetch-owner") == "true"
        max_keys = _parse_max_keys(ctx.query1("max-keys", "1000"))
        marker = _decode_token(token) if token else start_after
        if max_keys == 0:
            self.obj.get_bucket_info(bucket)
            objs, prefixes, trunc = [], [], False
        else:
            objs, prefixes, trunc = self.obj.list_objects(
                bucket, prefix, marker, delimiter, max_keys)
        next_token = ""
        if trunc:
            last = objs[-1].name if objs else (prefixes[-1] if prefixes
                                               else "")
            next_token = _encode_token(last)
        return HTTPResponse().with_xml(xmlgen.list_objects_v2_response(
            bucket, prefix, delimiter, max_keys, enc, start_after, token,
            next_token, objs, prefixes, trunc, fetch_owner))

    def list_object_versions(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListBucketVersions", bucket)
        prefix = ctx.query1("prefix")
        key_marker = ctx.query1("key-marker")
        vid_marker = ctx.query1("version-id-marker")
        delimiter = ctx.query1("delimiter")
        enc = ctx.query1("encoding-type")
        max_keys = _parse_max_keys(ctx.query1("max-keys", "1000"))
        if max_keys == 0:
            self.obj.get_bucket_info(bucket)
            versions, prefixes, nkm, nvm, trunc = [], [], "", "", False
        else:
            # a version-id-marker without a key-marker is meaningless
            # (S3 rejects it; we ignore it) — and the object layer
            # handles the "null" wire form of the empty version id
            versions, prefixes, nkm, nvm, trunc = \
                self.obj.list_object_versions(
                    bucket, prefix, key_marker, max_keys,
                    vid_marker if key_marker else "", delimiter)
        return HTTPResponse().with_xml(xmlgen.list_versions_response(
            bucket, prefix, key_marker, vid_marker, delimiter, max_keys,
            enc, versions, prefixes, trunc, nkm, nvm))

    def delete_multiple_objects(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:DeleteObject", bucket)
        self.obj.get_bucket_info(bucket)  # missing bucket -> 404, not 200
        body = ctx.read_body()
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML")
        quiet = False
        keys: list[tuple[str, str]] = []
        for child in root:
            tag = child.tag.split("}")[-1]
            if tag == "Quiet":
                quiet = (child.text or "").strip() == "true"
            elif tag == "Object":
                key_el = vid = None
                for sub in child:
                    st = sub.tag.split("}")[-1]
                    if st == "Key":
                        key_el = sub.text or ""
                    elif st == "VersionId":
                        vid = sub.text or ""
                if key_el:
                    keys.append((key_el, vid or ""))
        if len(keys) > 1000:
            raise S3Error("MalformedXML", "too many objects (max 1000)")
        versioned = self.bucket_meta.versioning_enabled(bucket)
        # batch deletes must free remote tier copies like the single
        # DELETE path does (same eff_vid gate: only when a DATA version
        # is removed, never for marker writes)
        tiers_live = self.tiers is not None \
            and getattr(self.tiers, "tiers", None)
        deleted, errors = [], []
        for key, vid in keys:
            if vid == "null":
                vid = ""  # same normalization as single DELETE
            tiered_md = None
            if tiers_live and (vid or not versioned):
                try:
                    tiered_md = self.obj.get_object_info(
                        bucket, key,
                        GetOptions(version_id=vid)).user_defined or {}
                except oerr.ObjectApiError:
                    pass
            try:
                res = self.obj.delete_object(bucket, key, version_id=vid,
                                             versioned=versioned)
                entry = {"key": key, "version_id": vid}
                if isinstance(res, ObjectInfo) and res.delete_marker:
                    entry["delete_marker"] = True
                    entry["delete_marker_version"] = res.version_id
                deleted.append(entry)
                if tiered_md is not None:
                    from ..tier.transition import free_remote
                    free_remote(self.tiers, tiered_md)
                self._notify("s3:ObjectRemoved:Delete", bucket, key)
            except oerr.ObjectNotFound:
                deleted.append({"key": key, "version_id": vid})
            except Exception as e:  # noqa: BLE001 — per-key error entry
                ae = api_error_from(e)
                errors.append({"key": key, "code": ae.code,
                               "message": ae.message})
        if quiet:
            deleted = []
        return HTTPResponse().with_xml(
            xmlgen.delete_objects_response(deleted, errors))

    def list_multipart_uploads(self, ctx, bucket) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListBucketMultipartUploads", bucket)
        self.obj.get_bucket_info(bucket)
        prefix = ctx.query1("prefix")
        max_uploads = _parse_max_keys(ctx.query1("max-uploads", "1000"))
        uploads = self.obj.list_multipart_uploads(bucket)
        if prefix:
            uploads = [u for u in uploads
                       if u["object"].startswith(prefix)]
        trunc = len(uploads) > max_uploads
        uploads = uploads[:max_uploads]
        nkm = uploads[-1]["object"] if trunc and uploads else ""
        num = uploads[-1]["upload_id"] if trunc and uploads else ""
        return HTTPResponse().with_xml(
            xmlgen.list_multipart_uploads_response(
                bucket, "", "", prefix, "", max_uploads, trunc, uploads,
                nkm, num))

    # ------------------------------------------------------------------
    # object handlers
    # ------------------------------------------------------------------

    def _put_reader(self, ctx) -> tuple[HashReader, int]:
        """Build the verified PUT stream: content-md5 / x-amz-content-
        sha256 expectations + streaming-signature decoding
        (cmd/object-handlers.go:1343-1435)."""
        size = ctx.content_length
        md5_hex = ""
        cm = ctx.header("content-md5")
        if cm:
            try:
                md5_hex = binascii.hexlify(
                    base64.b64decode(cm, validate=True)).decode()
            except (binascii.Error, ValueError):
                raise S3Error("InvalidDigest")
        sha_hex = ""
        body_sha = ctx.header("x-amz-content-sha256")
        stream = ctx.body_stream
        if ctx.auth_type == sig.AUTH_STREAMING_SIGNED:
            decoded = ctx.header("x-amz-decoded-content-length")
            if not decoded:
                raise S3Error("MissingContentLength")
            try:
                size = int(decoded)
            except ValueError:
                raise S3Error("InvalidArgument",
                              "bad x-amz-decoded-content-length")
            stream = sig.new_chunked_reader(ctx.req, ctx.body_stream,
                                            ctx.cred)
        elif body_sha and body_sha not in (sig.UNSIGNED_PAYLOAD, ""):
            sha_hex = body_sha
        if size < 0:
            raise S3Error("MissingContentLength")
        if size > MAX_OBJECT_SIZE:
            raise S3Error("EntityTooLarge")
        return HashReader(stream, size, md5_hex=md5_hex,
                          sha256_hex=sha_hex), size

    def put_object(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObject", bucket, key)
        self.obj.get_bucket_info(bucket)
        if ctx.header("x-minio-tpu-repl-spec"):
            # internal replication apply (the reference's
            # x-minio-source-* peer headers): a version-faithful write
            # carrying explicit identity — owner credential only
            return self._repl_apply(ctx, bucket, key)
        # _put_reader resolves the true payload size (including
        # x-amz-decoded-content-length for aws-chunked streams, where
        # Content-Length covers the chunk framing) — quota must gate on
        # that, or chunked PUTs bypass it entirely.
        reader, size = self._put_reader(ctx)
        self._enforce_quota(bucket, size)
        metadata = _extract_metadata(ctx)
        if ctx.header("x-amz-tagging"):
            metadata["X-Amz-Tagging"] = ctx.header("x-amz-tagging")
        reader, size, sse_headers, sse_spec = self._apply_put_transforms(
            ctx, key, reader, size, metadata)
        # object lock: explicit headers win; else the bucket default
        from ..features import objectlock as olock
        olock.retention_headers_from_request(ctx.header, metadata)
        lock_cfg = self.bucket_meta.get(bucket).object_lock_xml
        if lock_cfg and olock.MD_MODE not in metadata:
            olock.DefaultRetention.from_config_xml(lock_cfg).apply_to(
                metadata)
        versioned = self.bucket_meta.versioning_enabled(bucket)
        info = self.obj.put_object(
            bucket, key, reader, size,
            PutOptions(metadata=metadata, versioned=versioned,
                       parity=self._parity_for(
                           ctx.header("x-amz-storage-class")),
                       sse_spec=sse_spec))
        # Count the client bytes actually received: `size` is the
        # resolved payload length (decoded length for aws-chunked
        # streams), unlike Content-Length (framing included) or
        # info.size (post-compression/SSE stored size).
        self.bandwidth.record(bucket, "rx", max(size, 0))
        headers = {"ETag": f'"{info.etag}"', **sse_headers}
        if info.version_id and info.version_id != "null":
            headers["x-amz-version-id"] = info.version_id
        self._notify("s3:ObjectCreated:Put", bucket, key)
        return HTTPResponse(headers=headers)

    def _repl_apply(self, ctx, bucket, key) -> HTTPResponse:
        """Apply one replicated version with full fidelity (identity,
        part boundaries, markers, transitioned stubs as metadata) —
        the HTTPReplClient's server side. Owner credential only: the
        spec header carries internal metadata and explicit version
        identity no ordinary client may set."""
        if self.iam is not None and ctx.cred is not None and \
                not self._is_owner(ctx.cred):
            raise S3Error("AccessDenied",
                          "replication apply needs the owner credential")
        from ..object.faithful import VersionSpec
        from ..replicate.client import LayerReplClient, ReplClientError
        try:
            spec = VersionSpec.from_dict(json.loads(
                base64.urlsafe_b64decode(
                    ctx.header("x-minio-tpu-repl-spec").encode())
                .decode()))
        except (ValueError, KeyError, TypeError):
            raise S3Error("InvalidArgument",
                          "bad replication spec header") from None
        body = ctx.read_body()
        if not spec.delete_marker and not spec.transitioned_stub \
                and len(body) != spec.size:
            raise S3Error("IncompleteBody")
        site = ""
        if self.replication is not None:
            site = getattr(getattr(self.replication, "registry", None),
                           "site_id", "")
        client = LayerReplClient(self.obj, bucket, site)
        try:
            result = client.apply_version(
                key, spec, reader_factory=lambda: io.BytesIO(body))
        except ReplClientError as e:
            raise S3Error("InternalError", str(e)) from None
        if result == "applied":
            self._notify("s3:ObjectCreated:Replication", bucket, key)
        return HTTPResponse(
            body=json.dumps({"result": result}).encode(),
            headers={"Content-Type": "application/json"})

    def _apply_put_transforms(self, ctx, key, reader, size, metadata
                              ) -> tuple:
        """Compression + SSE wrapping of the PUT stream (reference
        newS2CompressReader + EncryptRequest wiring,
        cmd/object-handlers.go:1452-1470). Returns (reader, size,
        response headers, sse_spec) — sse_spec rides PutOptions into
        the engine when the fused device cipher path takes the
        stream instead of a CPU transform here."""
        ssec_key = sse.parse_ssec_headers(ctx.header)
        sse_s3 = self._sse_s3_requested(ctx, ssec_key)
        compress = (self.compression_enabled
                    and sse.is_compressible(
                        key, metadata.get("content-type", "")))
        if ssec_key is None and not sse_s3 and not compress:
            return reader, size, {}, None
        reader2, size2, spec = sse.setup_put_transforms(
            key_name=key, raw_reader=reader, raw_size=size,
            metadata=metadata, ssec_key=ssec_key, sse_s3=sse_s3,
            kms=self.kms, compress=compress,
            compress_algo=self._compress_algo(),
            device_sse=getattr(self.obj, "supports_sse_device", False))
        headers = {}
        if sse_s3:
            headers["x-amz-server-side-encryption"] = "AES256"
        elif ssec_key is not None:
            headers["x-amz-server-side-encryption-customer-algorithm"] = \
                "AES256"
            headers["x-amz-server-side-encryption-customer-key-md5"] = \
                metadata.get(sse.MK_KEYMD5, "")
        return reader2, size2, headers, spec

    def _obj_response_headers(self, info: ObjectInfo) -> dict[str, str]:
        from ..storage import datatypes as dt
        h = {
            "ETag": f'"{info.etag}"',
            "Last-Modified": _http_date(info.mod_time),
            "Content-Type": info.content_type or
            "application/octet-stream",
            "Accept-Ranges": "bytes",
        }
        if info.content_encoding:
            h["Content-Encoding"] = info.content_encoding
        if info.version_id and info.version_id != "null":
            h["x-amz-version-id"] = info.version_id
        for k, v in info.user_defined.items():
            lk = k.lower()
            if lk.startswith("x-amz-meta-"):
                h[k] = v
            elif lk in ("cache-control", "content-disposition",
                        "content-language", "expires"):
                h[k] = v
        md = info.user_defined or {}
        if dt.is_transitioned(md):
            # transitioned objects report the TIER as their storage
            # class and their restore state (S3 GLACIER semantics)
            h["x-amz-storage-class"] = md.get(dt.TRANSITION_TIER_KEY, "")
            if md.get(dt.RESTORE_KEY):
                h["x-amz-restore"] = md[dt.RESTORE_KEY]
        if info.delete_marker:
            h["x-amz-delete-marker"] = "true"
        return h

    def _check_preconditions(self, ctx, info: ObjectInfo) -> Optional[int]:
        """Conditional header evaluation; returns an HTTP status to
        short-circuit with, or None (cmd/object-handlers-common.go)."""
        inm = ctx.header("if-none-match")
        im = ctx.header("if-match")
        etag = info.etag
        if im and im.strip('"') != etag:
            return 412
        if inm and inm.strip('"') == etag:
            return 304
        ims = ctx.header("if-modified-since")
        if ims and not inm:
            try:
                t = parsedate_to_datetime(ims).timestamp()
                if info.mod_time <= t:
                    return 304
            except (TypeError, ValueError):
                pass
        ius = ctx.header("if-unmodified-since")
        if ius and not im:
            try:
                t = parsedate_to_datetime(ius).timestamp()
                if info.mod_time > t:
                    return 412
            except (TypeError, ValueError):
                pass
        return None

    def get_object(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetObject", bucket, key)
        vid = ctx.query1("versionId")
        opts = GetOptions(version_id="" if vid == "null" else vid)
        info = self.obj.get_object_info(bucket, key, opts)
        short = self._check_preconditions(ctx, info)
        if short is not None:
            return HTTPResponse(status=short,
                                headers=self._obj_response_headers(info))
        md = info.user_defined or {}
        if md.get(sse.MK_SSE) or sse.stored_compression(md):
            return self._get_transformed(ctx, bucket, key, info, opts, md)
        rng = _parse_range(ctx.header("range"), info.size)
        offset, length = (0, info.size) if rng is None else rng
        info, stream = self.obj.get_object(bucket, key, offset, length,
                                           opts)
        headers = self._obj_response_headers(info)
        headers["Content-Length"] = str(length)
        status = 200
        if rng is not None:
            status = 206
            headers["Content-Range"] = (
                f"bytes {offset}-{offset + length - 1}/{info.size}")
        # response header overrides (presigned GET)
        for qk, hk in (("response-content-type", "Content-Type"),
                       ("response-content-disposition",
                        "Content-Disposition"),
                       ("response-cache-control", "Cache-Control"),
                       ("response-content-encoding", "Content-Encoding"),
                       ("response-content-language", "Content-Language")):
            if ctx.query1(qk):
                headers[hk] = ctx.query1(qk)
        self._notify("s3:ObjectAccessed:Get", bucket, key)
        return HTTPResponse(status=status, headers=headers,
                            stream=self.bandwidth.counting_stream(
                                bucket, stream))

    def _compress_algo(self) -> str:
        return sse.COMPRESS_ZSTD if self.compression_algorithm == \
            "zstd" else sse.COMPRESS_S2

    def _get_transformed(self, ctx, bucket, key, info, opts, md
                         ) -> HTTPResponse:
        """GET of an encrypted and/or compressed object: decrypt the
        covering package range / decompress, then trim to the requested
        plaintext range (reference DecryptBlocksRequestR + s2 reader
        stack, cmd/object-api-utils.go:626-697)."""
        enc = sse.resolve_get_key(md, ctx.header, self.kms)
        compressed = bool(sse.stored_compression(md))
        actual = self._plain_size(info, md)
        rng = _parse_range(ctx.header("range"), actual)
        offset, length = (0, actual) if rng is None else rng

        if actual <= 0 or length <= 0:
            stream = iter(())
        elif enc is not None and md.get(sse.MK_SSE_MP) and info.parts:
            # multipart SSE: parts are independent package streams under
            # per-part nonces; walk the parts covering the range
            stream = self._mp_decrypt_stream(opts, bucket, key, info,
                                             enc, offset, length)
        elif compressed:
            # compressed payloads have no random access: decode from the
            # start and skip (the reference's s2 path does the same)
            if enc is not None and \
                    sse.stored_sse_cipher(md) == sse.CIPHER_CHACHA:
                stream = self._chacha_full_stream(bucket, key, info,
                                                  opts, enc)
            else:
                _, stream = self.obj.get_object(bucket, key, 0,
                                                info.size, opts)
                if enc is not None:
                    stream = sse.decrypt_stream(stream, enc[0], enc[1])
            stream = sse.decompress_stream(
                    stream, sse.stored_compression(md)
                    or sse.COMPRESS_ZSTD)
            stream = _skip_take(stream, offset, length)
        elif sse.stored_sse_cipher(md) == sse.CIPHER_CHACHA:
            # detached-tag stream: ciphertext offsets match plaintext
            # 1:1 and the tag trailer sits at the end — the ranged
            # helper pulls both through the fetch seam and verifies
            # every covering package BEFORE its keystream XOR
            stream = sse.chacha_decrypt_ranged(
                self._obj_fetch(bucket, key, opts), info.size,
                enc[0], enc[1], offset, length)
            stream = _skip_take(stream, offset % sse.PKG_SIZE, length)
        else:
            # package-aligned ciphertext range
            pkg_full = sse.PKG_SIZE + sse.TAG_SIZE
            start_pkg = offset // sse.PKG_SIZE
            end_pkg = (offset + length - 1) // sse.PKG_SIZE
            coff = start_pkg * pkg_full
            clen = min(info.size - coff,
                       (end_pkg - start_pkg + 1) * pkg_full)
            _, stream = self.obj.get_object(bucket, key, coff, clen, opts)
            stream = sse.decrypt_stream(stream, enc[0], enc[1],
                                        start_seq=start_pkg)
            stream = _skip_take(stream, offset - start_pkg * sse.PKG_SIZE,
                                length)

        headers = self._obj_response_headers(info)
        headers.update(self._sse_response_headers(md))
        headers["Content-Length"] = str(length)
        status = 200
        if rng is not None:
            status = 206
            headers["Content-Range"] = (
                f"bytes {offset}-{offset + length - 1}/{actual}")
        self._notify("s3:ObjectAccessed:Get", bucket, key)
        return HTTPResponse(status=status, headers=headers,
                            stream=self.bandwidth.counting_stream(
                                bucket, stream))

    def _multipart_meta(self, bucket: str, key: str,
                        upload_id: str) -> dict:
        """Session metadata with a bounded cache (immutable after
        create; avoids one journal read per part upload)."""
        cache_key = f"{bucket}/{key}/{upload_id}"
        md = self._mpu_meta.get(cache_key)
        if md is None:
            md = self.obj.get_multipart_info(bucket, key, upload_id)
            self._mpu_meta[cache_key] = md
            while len(self._mpu_meta) > 1024:
                self._mpu_meta.popitem(last=False)
        return md

    def _mpu_sse_key(self, bucket: str, key: str, upload_id: str,
                     md: dict, ctx) -> tuple:
        """Resolved (oek, nonce_base) for a multipart SSE session.
        SSE-S3 resolutions are cached per upload — under a remote KMS,
        resolve_get_key is one decrypt-key HTTP round trip, and a
        1000-part upload must not make 1000 of them. SSE-C is NEVER
        cached: each part request must present (and re-verify) the
        client's key headers."""
        if md.get(sse.MK_SSE) != "S3":
            return sse.resolve_get_key(md, ctx.header, self.kms)
        cache_key = f"{bucket}/{key}/{upload_id}"
        enc = self._mpu_keys.get(cache_key)
        if enc is None:
            enc = sse.resolve_get_key(md, ctx.header, self.kms)
            self._mpu_keys[cache_key] = enc
            while len(self._mpu_keys) > 1024:
                self._mpu_keys.popitem(last=False)
        return enc

    def _sse_s3_requested(self, ctx, ssec_key) -> bool:
        """Validate x-amz-server-side-encryption: only AES256 (SSE-S3)
        is supported — aws:kms etc. must error, never silently store
        plaintext after an encryption request."""
        algo = ctx.header("x-amz-server-side-encryption")
        if not algo or ssec_key is not None:
            return False
        if algo != "AES256":
            raise S3Error("NotImplemented",
                          f"server-side encryption {algo!r} is not "
                          "supported (use AES256)")
        return True

    def _obj_fetch(self, bucket, key, opts, base: int = 0):
        """fetch(off, len) -> stored-byte chunk iterator, the read seam
        chacha_decrypt_ranged pulls ciphertext and tag-trailer ranges
        through (offset by `base` for a part inside a multipart
        object)."""
        def fetch(off, ln):
            _, st = self.obj.get_object(bucket, key, base + off, ln,
                                        opts)
            return st
        return fetch

    def _chacha_full_stream(self, bucket, key, info, opts, enc
                            ) -> Iterator[bytes]:
        """Whole-object verify-then-decrypt of a detached-tag chacha
        stream (the cipher's plaintext length comes from the stored
        size — under compression it is the compressed length, which
        metadata does not record)."""
        ct_len, _ = sse.chacha_ct_len(info.size)
        return sse.chacha_decrypt_ranged(
            self._obj_fetch(bucket, key, opts), info.size,
            enc[0], enc[1], 0, ct_len)

    def _plaintext_stream(self, bucket, key, info, header, opts
                          ) -> tuple[Iterator[bytes], int]:
        """Full plaintext stream + size of a stored object, decrypting
        and decompressing as its metadata requires. ONE decode stack
        shared by the copy-source and web download paths (the ranged
        S3 GET keeps its own package-range arithmetic in
        _get_transformed). `header` is a callable(name, default="")
        supplying SSE-C key headers; without them an SSE-C object
        raises AccessDenied from resolve_get_key."""
        md = info.user_defined or {}
        if not (md.get(sse.MK_SSE) or sse.stored_compression(md)):
            _, stream = self.obj.get_object(bucket, key, 0, info.size,
                                            opts)
            return stream, info.size
        enc = sse.resolve_get_key(md, header, self.kms)
        plain_size = self._plain_size(info, md)
        if enc is not None and md.get(sse.MK_SSE_MP) and info.parts:
            return (self._mp_decrypt_stream(opts, bucket, key, info,
                                            enc, 0, plain_size),
                    plain_size)
        if enc is not None and \
                sse.stored_sse_cipher(md) == sse.CIPHER_CHACHA:
            stream = self._chacha_full_stream(bucket, key, info, opts,
                                              enc)
        else:
            _, stream = self.obj.get_object(bucket, key, 0, info.size,
                                            opts)
            if enc is not None:
                stream = sse.decrypt_stream(stream, enc[0], enc[1])
        if sse.stored_compression(md):
            stream = sse.decompress_stream(
                    stream, sse.stored_compression(md)
                    or sse.COMPRESS_ZSTD)
        return stream, plain_size

    def _copy_source_plaintext(self, ctx, src_bucket, src_key, src_info,
                               opts) -> tuple[Iterator[bytes], int]:
        """Plaintext stream + size of a copy source, decrypting with the
        x-amz-copy-source-* SSE-C headers (or the master key) and
        decompressing as needed."""

        def src_header(name, default=""):
            prefix = "x-amz-server-side-encryption-customer"
            if name.startswith(prefix):
                return ctx.header(
                    "x-amz-copy-source-server-side-encryption-customer"
                    + name[len(prefix):], default)
            return ctx.header(name, default)

        return self._plaintext_stream(src_bucket, src_key, src_info,
                                      src_header, opts)

    @staticmethod
    def _plain_size(info, md: dict) -> int:
        if md.get(sse.MK_SSE_MP) and info.parts:
            return sum(p.actual_size for p in info.parts)
        return int(md.get(sse.MK_ACTUAL, info.size))

    def _mp_decrypt_stream(self, opts, bucket, key, info, enc,
                           offset: int, length: int) -> Iterator[bytes]:
        """Decrypt a multipart-SSE object across part boundaries
        (DecryptBlocksRequestR's part walk, cmd/encryption-v1.go:356).
        Each part is an independent package stream under a per-part
        nonce — either cipher's layout, per the object's metadata."""
        pkg_full = sse.PKG_SIZE + sse.TAG_SIZE
        chacha = sse.stored_sse_cipher(info.user_defined or {}) == \
            sse.CIPHER_CHACHA

        def gen():
            remaining = length
            want = offset
            plain_start = 0
            cipher_start = 0
            for p in info.parts:
                psize, csize = p.actual_size, p.size
                plain_end = plain_start + psize
                if remaining <= 0:
                    return
                if plain_end <= want:
                    plain_start = plain_end
                    cipher_start += csize
                    continue
                in_off = want - plain_start
                in_len = min(remaining, psize - in_off)
                start_pkg = in_off // sse.PKG_SIZE
                if chacha:
                    pt = sse.chacha_decrypt_ranged(
                        self._obj_fetch(bucket, key, opts,
                                        base=cipher_start),
                        csize, enc[0], sse.part_nonce(enc[1], p.number),
                        in_off, in_len)
                else:
                    end_pkg = (in_off + in_len - 1) // sse.PKG_SIZE
                    coff = cipher_start + start_pkg * pkg_full
                    clen = min(csize - start_pkg * pkg_full,
                               (end_pkg - start_pkg + 1) * pkg_full)
                    _, stream = self.obj.get_object(bucket, key, coff,
                                                    clen, opts)
                    pt = sse.decrypt_stream(
                        stream, enc[0], sse.part_nonce(enc[1], p.number),
                        start_seq=start_pkg)
                yield from _skip_take(pt,
                                      in_off - start_pkg * sse.PKG_SIZE,
                                      in_len)
                remaining -= in_len
                want += in_len
                plain_start = plain_end
                cipher_start += csize

        return gen()

    def _sse_response_headers(self, md: dict) -> dict:
        mode = md.get(sse.MK_SSE, "")
        if mode == "S3":
            return {"x-amz-server-side-encryption": "AES256"}
        if mode == "C":
            return {
                "x-amz-server-side-encryption-customer-algorithm":
                    "AES256",
                "x-amz-server-side-encryption-customer-key-md5":
                    md.get(sse.MK_KEYMD5, ""),
            }
        return {}

    def head_object(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetObject", bucket, key)
        vid = ctx.query1("versionId")
        opts = GetOptions(version_id="" if vid == "null" else vid)
        info = self.obj.get_object_info(bucket, key, opts)
        short = self._check_preconditions(ctx, info)
        headers = self._obj_response_headers(info)
        md = info.user_defined or {}
        if md.get(sse.MK_SSE) or sse.stored_compression(md):
            if md.get(sse.MK_SSE) == "C":
                sse.resolve_get_key(md, ctx.header, self.kms)
            headers.update(self._sse_response_headers(md))
            headers["Content-Length"] = str(self._plain_size(info, md))
        else:
            headers["Content-Length"] = str(info.size)
        if short is not None:
            return HTTPResponse(status=short, headers=headers)
        self._notify("s3:ObjectAccessed:Head", bucket, key)
        return HTTPResponse(headers=headers)

    def delete_object(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:DeleteObject", bucket, key)
        self.obj.get_bucket_info(bucket)
        if ctx.header("x-minio-tpu-repl-purge"):
            # internal replica prune: remove ONE version outright (no
            # delete marker), owner credential only — the wire form of
            # the replication plane's prune step
            if self.iam is not None and ctx.cred is not None and \
                    not self._is_owner(ctx.cred):
                raise S3Error("AccessDenied",
                              "replica prune needs the owner credential")
            pvid = ctx.query1("versionId")
            # object-lock retention binds the prune too: a COMPLIANCE-
            # locked version must survive replication convergence
            # exactly like it survives a direct versioned DELETE. The
            # prune ALWAYS removes a version (never writes a marker),
            # so the marker exemption must not apply — an empty vid
            # names the null version explicitly
            self._enforce_object_lock(ctx, bucket, key, pvid or "null",
                                      False)
            try:
                self.obj.delete_object(
                    bucket, key,
                    version_id="" if pvid == "null" else pvid,
                    versioned=False)
            except (oerr.ObjectNotFound, oerr.VersionNotFound):
                pass                    # already converged
            self._notify("s3:ObjectRemoved:Delete", bucket, key)
            return HTTPResponse(status=204)
        vid = ctx.query1("versionId")
        versioned = self.bucket_meta.versioning_enabled(bucket)
        self._enforce_object_lock(ctx, bucket, key, vid, versioned)
        # "null" targets the pre-versioning null version, which this
        # stack stores under the empty version id — normalize ONCE so
        # the tier-free gate below and delete_object agree on whether
        # this request removes a DATA version or only writes a marker
        eff_vid = "" if vid == "null" else vid
        # a delete that removes a DATA version (explicit version, or an
        # unversioned delete — not a marker write) must free the remote
        # tier copy of a transitioned object too. Gated on a NON-EMPTY
        # registry: with no tiers configured nothing can be
        # transitioned, and the extra quorum metadata read would tax
        # every DELETE for nothing. eff_vid (not the raw vid) decides:
        # ?versionId=null on a versioned bucket is a MARKER write — the
        # stub stays, so freeing its remote copy would destroy the
        # archived data.
        tiered_md = None
        if self.tiers is not None and getattr(self.tiers, "tiers", None) \
                and (eff_vid or not versioned):
            try:
                tinfo = self.obj.get_object_info(
                    bucket, key, GetOptions(version_id=eff_vid))
                tiered_md = tinfo.user_defined or {}
            except oerr.ObjectApiError:
                pass
        headers = {}
        try:
            res = self.obj.delete_object(
                bucket, key, version_id=eff_vid, versioned=versioned)
            if isinstance(res, ObjectInfo):
                if res.delete_marker:
                    headers["x-amz-delete-marker"] = "true"
                if res.version_id and res.version_id != "null":
                    headers["x-amz-version-id"] = res.version_id
            if tiered_md is not None:
                from ..tier.transition import free_remote
                free_remote(self.tiers, tiered_md)
        except oerr.ObjectNotFound:
            pass  # S3 DELETE of a missing key is 204
        self._notify("s3:ObjectRemoved:Delete", bucket, key)
        return HTTPResponse(status=204, headers=headers)

    def restore_object(self, ctx, bucket, key) -> HTTPResponse:
        """POST /bucket/key?restore — pull a transitioned object back as
        an expiring local copy (S3 RestoreObject; 202 on a fresh
        restore, 200 when only the expiry window was extended)."""
        self.authenticate(ctx, "s3:RestoreObject", bucket, key)
        self.obj.get_bucket_info(bucket)
        if self.tiers is None:
            raise S3Error("NotImplemented", "no tier configuration")
        body = ctx.read_body()
        days = 1
        if body.strip():
            try:
                root = ET.fromstring(body)
            except ET.ParseError:
                raise S3Error("MalformedXML") from None
            ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
            del_ = root.find("Days")
            if del_ is None:
                del_ = root.find(ns + "Days")
            if del_ is not None and (del_.text or "").strip():
                try:
                    days = int(del_.text.strip())
                except ValueError:
                    raise S3Error("MalformedXML", "bad Days") from None
        if days < 1:
            raise S3Error("InvalidArgument", "restore Days must be >= 1")
        vid = ctx.query1("versionId")
        eff_vid = "" if vid == "null" else vid
        from ..storage import datatypes as dt
        from ..tier.transition import (clear_restore_ongoing,
                                       mark_restore_ongoing,
                                       restore_object as _restore)
        info = self.obj.get_object_info(bucket, key,
                                        GetOptions(version_id=eff_vid))
        md = info.user_defined or {}
        if dt.RESTORE_ONGOING in md.get(dt.RESTORE_KEY, ""):
            raise S3Error("RestoreAlreadyInProgress")
        async_bytes = knobs.get_int("MINIO_TPU_RESTORE_ASYNC_BYTES")
        if (self.restore_worker is not None and async_bytes
                and info.size >= async_bytes and dt.is_transitioned(md)
                and not dt.is_restored(md)):
            # large object: answer 202 NOW, run the tier pull in the
            # background worker (carried-over ROADMAP item) — the
            # ongoing-request marker makes the state visible to
            # GET/HEAD and gates duplicate restores
            mark_restore_ongoing(self.obj, bucket, key, eff_vid)
            if self.restore_worker.enqueue_restore(
                    bucket, key, eff_vid or info.version_id, days):
                self._notify("s3:ObjectRestore:Post", bucket, key)
                return HTTPResponse(status=202)
            # worker queue full / stopping: nothing will ever clear the
            # marker — undo it and serve the restore synchronously
            clear_restore_ongoing(self.obj, bucket, key, eff_vid)
        out = _restore(self.obj, self.tiers, bucket, key,
                       version_id=eff_vid, days=days)
        self._notify("s3:ObjectRestore:Completed", bucket, key)
        return HTTPResponse(
            status=202 if out["status"] == "restored" else 200)

    def copy_object(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObject", bucket, key)
        src_bucket, src_key, src_vid = _parse_copy_source(
            ctx.header("x-amz-copy-source"))
        if self.iam is not None and ctx.cred and \
                not self._is_owner(ctx.cred):
            if not self.iam.is_allowed(ctx.cred, "s3:GetObject",
                                       src_bucket, src_key,
                                       self._policy_conditions(ctx)):
                raise S3Error("AccessDenied")
        opts = GetOptions(version_id=src_vid)
        src_info = self.obj.get_object_info(src_bucket, src_key, opts)
        # copy preconditions
        csm = ctx.header("x-amz-copy-source-if-match")
        if csm and csm.strip('"') != src_info.etag:
            raise S3Error("PreconditionFailed")
        csnm = ctx.header("x-amz-copy-source-if-none-match")
        if csnm and csnm.strip('"') == src_info.etag:
            raise S3Error("PreconditionFailed")
        directive = ctx.header("x-amz-metadata-directive", "COPY")
        src_md = src_info.user_defined or {}
        src_transformed = bool(src_md.get(sse.MK_SSE)
                               or sse.stored_compression(src_md))
        # target transform request (re-encrypt / encrypt-on-copy), or an
        # explicit source key (decrypt-on-copy)?
        tgt_ssec = sse.parse_ssec_headers(ctx.header)
        tgt_sse_s3 = self._sse_s3_requested(ctx, tgt_ssec)
        re_transform = (tgt_ssec is not None or tgt_sse_s3
                        or bool(ctx.header(
                            "x-amz-copy-source-server-side-encryption-"
                            "customer-algorithm")))

        if directive == "REPLACE":
            metadata = _extract_metadata(ctx)
            if src_transformed and not re_transform:
                # stored bytes copied verbatim: the transform state
                # (seals, compression flag, actual size) must survive a
                # metadata REPLACE or the copy is unreadable
                for ik in (sse.MK_SSE, sse.MK_SEALED, sse.MK_IV,
                           sse.MK_KEYMD5, sse.MK_COMPRESS,
                           sse.MK_COMPRESS_LEGACY, sse.MK_ACTUAL,
                           sse.MK_SSE_MP):
                    if ik in src_md:
                        metadata[ik] = src_md[ik]
        else:
            if src_bucket == bucket and src_key == key \
                    and not re_transform:
                raise S3Error("InvalidRequest",
                              "self-copy requires metadata directive "
                              "REPLACE")
            metadata = dict(src_md)
            metadata["content-type"] = src_info.content_type
            if re_transform:
                for ik in (sse.MK_SSE, sse.MK_SEALED, sse.MK_IV,
                           sse.MK_KEYMD5, sse.MK_COMPRESS,
                           sse.MK_COMPRESS_LEGACY, sse.MK_ACTUAL,
                           sse.MK_SSE_MP):
                    metadata.pop(ik, None)

        if re_transform:
            # re-encryption path (CopyObject with SSE change, reference
            # re-encrypt wiring in cmd/object-handlers.go CopyObject):
            # decrypt/decompress the source to plaintext, then apply the
            # TARGET transforms like a fresh PUT
            plain_stream, plain_size = self._copy_source_plaintext(
                ctx, src_bucket, src_key, src_info, opts)
            if src_bucket == bucket and src_key == key:
                plain_stream = iter([b"".join(plain_stream)])
            reader = HashReader(_IterStream(plain_stream), plain_size)
            metadata["etag"] = src_info.etag
            reader2, size2, spec = sse.setup_put_transforms(
                key_name=key, raw_reader=reader, raw_size=plain_size,
                metadata=metadata, ssec_key=tgt_ssec, sse_s3=tgt_sse_s3,
                kms=self.kms, compress=False,
                device_sse=getattr(self.obj, "supports_sse_device",
                                   False))
            versioned = self.bucket_meta.versioning_enabled(bucket)
            info = self.obj.put_object(
                bucket, key, reader2, size2,
                PutOptions(metadata=metadata, versioned=versioned,
                           sse_spec=spec))
            headers = {}
            if info.version_id and info.version_id != "null":
                headers["x-amz-version-id"] = info.version_id
            self._notify("s3:ObjectCreated:Copy", bucket, key)
            return HTTPResponse(headers=headers).with_xml(
                xmlgen.copy_object_response(info.etag, info.mod_time))

        _, stream = self.obj.get_object(src_bucket, src_key, 0,
                                        src_info.size, opts)
        if src_bucket == bucket and src_key == key:
            # self-copy: drain before writing — the GET stream holds the
            # read lock the PUT's write lock would wait on
            stream = iter([b"".join(stream)])
        reader = HashReader(_IterStream(stream), src_info.size)
        # the bytes are identical, so the ETag is too — and for
        # transformed objects the stored-byte MD5 is NOT the ETag
        metadata["etag"] = src_info.etag
        versioned = self.bucket_meta.versioning_enabled(bucket)
        info = self.obj.put_object(
            bucket, key, reader, src_info.size,
            PutOptions(metadata=metadata, versioned=versioned))
        headers = {}
        if info.version_id and info.version_id != "null":
            headers["x-amz-version-id"] = info.version_id
        self._notify("s3:ObjectCreated:Copy", bucket, key)
        return HTTPResponse(headers=headers).with_xml(
            xmlgen.copy_object_response(info.etag, info.mod_time))

    # --- multipart ------------------------------------------------------

    def new_multipart_upload(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObject", bucket, key)
        self.obj.get_bucket_info(bucket)
        metadata = _extract_metadata(ctx)
        # SSE multipart: seal one object key now; every part encrypts
        # under it with a per-part nonce space
        ssec_key = sse.parse_ssec_headers(ctx.header)
        sse_s3 = self._sse_s3_requested(ctx, ssec_key)
        if (ssec_key is not None or sse_s3) and not getattr(
                self.obj, "supports_sse_multipart", True):
            raise S3Error("NotImplemented",
                          "SSE multipart is not supported on this "
                          "backend")
        sse.create_sse_seals(metadata, ssec_key, sse_s3,
                             self.kms, multipart=True,
                             kms_context={"object": key})
        upload_id = self.obj.new_multipart_upload(
            bucket, key, PutOptions(metadata=metadata))
        return HTTPResponse().with_xml(
            xmlgen.initiate_multipart_response(bucket, key, upload_id))

    def put_object_part(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObject", bucket, key)
        upload_id = ctx.query1("uploadId")
        try:
            part_number = int(ctx.query1("partNumber"))
        except ValueError:
            raise S3Error("InvalidArgument", "partNumber must be an int")
        if not 1 <= part_number <= MAX_PARTS:
            raise S3Error("InvalidArgument",
                          f"partNumber must be 1..{MAX_PARTS}")
        reader, size = self._put_reader(ctx)
        if size > MAX_PART_SIZE:
            raise S3Error("EntityTooLarge")
        # multipart must not bypass bucket quota (the reference
        # enforces in PutObjectPart too); size is the resolved
        # plaintext length, aws-chunked included
        self._enforce_quota(bucket, size)
        # SSE upload: encrypt the part under the session's object key
        md = self._multipart_meta(bucket, key, upload_id)
        if md.get(sse.MK_SSE):
            enc = self._mpu_sse_key(bucket, key, upload_id, md, ctx)
            pnonce = sse.part_nonce(enc[1], part_number)
            if sse.stored_sse_cipher(md) == sse.CIPHER_CHACHA:
                transform = sse.ChaChaEncryptor(enc[0], pnonce)
            else:
                transform = sse.Encryptor(enc[0], pnonce)
            reader = sse.PutObjReader(reader, [transform])
            size = -1
        part = self.obj.put_object_part(bucket, key, upload_id,
                                        part_number, reader, size)
        # multipart is the standard large-upload path — its ingress
        # must count toward the bucket's bandwidth like single PUTs;
        # actual_size is the client (plaintext) byte count even when
        # the part was SSE-wrapped above (size would be ciphertext)
        self.bandwidth.record(bucket, "rx", max(part.actual_size, 0))
        return HTTPResponse(headers={"ETag": f'"{part.etag}"'})

    def copy_object_part(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObject", bucket, key)
        upload_id = ctx.query1("uploadId")
        try:
            part_number = int(ctx.query1("partNumber"))
        except ValueError:
            raise S3Error("InvalidArgument", "partNumber must be an int")
        if self._multipart_meta(bucket, key,
                                upload_id).get(sse.MK_SSE):
            raise S3Error("NotImplemented",
                          "copy-part into SSE uploads is not supported")
        src_bucket, src_key, src_vid = _parse_copy_source(
            ctx.header("x-amz-copy-source"))
        opts = GetOptions(version_id=src_vid)
        src_info = self.obj.get_object_info(src_bucket, src_key, opts)
        rng = _parse_range(ctx.header("x-amz-copy-source-range"),
                           src_info.size)
        offset, length = (0, src_info.size) if rng is None else rng
        _, stream = self.obj.get_object(src_bucket, src_key, offset,
                                        length, opts)
        reader = HashReader(_IterStream(stream), length)
        part = self.obj.put_object_part(bucket, key, upload_id,
                                        part_number, reader, length)
        x = xmlgen.X()
        x.open("CopyPartResult", xmlns=xmlgen.S3_XMLNS)
        x.elem("LastModified", xmlgen._ts(part.mod_time
                                          if hasattr(part, "mod_time")
                                          else 0.0))
        x.elem("ETag", f'"{part.etag}"')
        x.close("CopyPartResult")
        return HTTPResponse().with_xml(x.bytes())

    def complete_multipart_upload(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObject", bucket, key)
        upload_id = ctx.query1("uploadId")
        body = ctx.read_body()
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error("MalformedXML")
        parts: list[CompletePart] = []
        for child in root:
            if not child.tag.endswith("Part"):
                continue
            num = etag = None
            for sub in child:
                st = sub.tag.split("}")[-1]
                if st == "PartNumber":
                    try:
                        num = int(sub.text or "0")
                    except ValueError:
                        raise S3Error("MalformedXML",
                                      "PartNumber must be an int")
                elif st == "ETag":
                    etag = (sub.text or "").strip('"')
            if num is None or etag is None:
                raise S3Error("MalformedXML")
            parts.append(CompletePart(num, etag))
        if not parts:
            raise S3Error("MalformedXML", "no parts")
        if parts != sorted(parts, key=lambda p: p.part_number):
            raise S3Error("InvalidPartOrder")
        info = self.obj.complete_multipart_upload(bucket, key, upload_id,
                                                  parts)
        self._notify("s3:ObjectCreated:CompleteMultipartUpload", bucket,
                     key)
        host = ctx.header("host", "")
        return HTTPResponse().with_xml(xmlgen.complete_multipart_response(
            f"http://{host}/{bucket}/{key}", bucket, key, info.etag))

    def abort_multipart_upload(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:AbortMultipartUpload", bucket, key)
        self.obj.abort_multipart_upload(bucket, key,
                                        ctx.query1("uploadId"))
        return HTTPResponse(status=204)

    def list_object_parts(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:ListMultipartUploadParts", bucket, key)
        upload_id = ctx.query1("uploadId")
        try:
            marker = int(ctx.query1("part-number-marker", "0"))
        except ValueError:
            raise S3Error("InvalidArgument",
                          "part-number-marker must be an int")
        max_parts = _parse_max_keys(ctx.query1("max-parts", "1000"))
        parts = self.obj.list_object_parts(bucket, key, upload_id, marker,
                                           max_parts + 1)
        trunc = len(parts) > max_parts
        parts = parts[:max_parts]
        next_marker = parts[-1].part_number if parts and trunc else 0
        return HTTPResponse().with_xml(xmlgen.list_parts_response(
            bucket, key, upload_id, marker, next_marker, max_parts, trunc,
            parts))

    # --- object tagging -------------------------------------------------

    def get_object_tagging(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetObjectTagging", bucket, key)
        info = self.obj.get_object_info(bucket, key)
        raw = info.user_defined.get("X-Amz-Tagging", "")
        tags = dict(urllib.parse.parse_qsl(raw))
        return HTTPResponse().with_xml(xmlgen.tagging_response(tags))

    def put_object_tagging(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObjectTagging", bucket, key)
        tags = _parse_tagging_xml(ctx.read_body())
        self._rewrite_metadata(
            bucket, key,
            {"X-Amz-Tagging": urllib.parse.urlencode(tags)})
        return HTTPResponse()

    def delete_object_tagging(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:DeleteObjectTagging", bucket, key)
        self._rewrite_metadata(bucket, key, {"X-Amz-Tagging": None})
        return HTTPResponse(status=204)

    def _rewrite_metadata(self, bucket, key, updates: dict,
                          version_id: str = "") -> None:
        """Metadata-only update in place — no data rewrite, no new
        version (tags on a versioned bucket must not grow the stack)."""
        info = self.obj.get_object_info(bucket, key,
                                        GetOptions(version_id=version_id))
        md = dict(info.user_defined)
        md["content-type"] = info.content_type
        if info.content_encoding:
            md["content-encoding"] = info.content_encoding
        for k, v in updates.items():
            if v is None:
                md.pop(k, None)
            else:
                md[k] = v
        self.obj.update_object_metadata(bucket, key, md,
                                        version_id or info.version_id)

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    def select_object_content(self, ctx, bucket, key) -> HTTPResponse:
        """SelectObjectContent: SQL over a CSV/JSON object streamed back
        as AWS event-stream messages (reference pkg/s3select +
        cmd/object-handlers.go SelectObjectContentHandler)."""
        self.authenticate(ctx, "s3:GetObject", bucket, key)
        from ..s3select import SelectRequest
        from ..s3select.select import event_stream
        req = SelectRequest.from_xml(ctx.read_body())
        info = self.obj.get_object_info(bucket, key)
        # decrypt/decompress transparently via the transformed GET path
        # (self.obj may be the hot-object read cache: a cached Select
        # source serves without touching the erasure decode path)
        stream, _size = self._plaintext_stream(bucket, key, info,
                                               ctx.header, GetOptions())
        data = b"".join(stream)
        # device scan plane: compiled-kernel predicate scan through the
        # batch former, CPU evaluator as byte-identical fallback
        body = self.scan.event_stream(req, data) \
            if self.scan is not None else event_stream(req, data)
        return HTTPResponse(
            headers={"Content-Type": "application/octet-stream"},
            stream=body)

    def _enforce_object_lock(self, ctx, bucket: str, key: str,
                             version_id: str, versioned: bool) -> None:
        """WORM enforcement on deletion (enforceRetentionForDeletion,
        cmd/bucket-object-lock.go): only the removal of an actual
        VERSION is gated — a versioned delete without versionId just
        writes a marker."""
        from ..features import objectlock as olock
        if not self.bucket_meta.get(bucket).object_lock_xml:
            return
        if versioned and not version_id:
            return                        # delete marker: always allowed
        try:
            info = self.obj.get_object_info(
                bucket, key, GetOptions(version_id=version_id))
        except oerr.ObjectApiError:
            return
        bypass = self._governance_bypass(ctx, bucket, key)
        reason = olock.check_deletable(info.user_defined or {}, bypass)
        if reason is not None:
            raise S3Error("ObjectLocked", reason)

    def _governance_bypass(self, ctx, bucket: str, key: str) -> bool:
        """True when the request carries the governance-bypass header AND
        the caller holds s3:BypassGovernanceRetention (root implicit)."""
        if ctx.header("x-amz-bypass-governance-retention") != "true":
            return False
        if self.iam is not None and ctx.cred and \
                not self._is_owner(ctx.cred):
            return self.iam.is_allowed(
                ctx.cred, "s3:BypassGovernanceRetention", bucket, key,
                self._policy_conditions(ctx))
        return True

    # --- ?retention / ?legal-hold subresources --------------------------

    def get_object_retention(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetObjectRetention", bucket, key)
        from ..features import objectlock as olock
        info = self.obj.get_object_info(
            bucket, key, GetOptions(version_id=ctx.query1("versionId")))
        xml = olock.retention_xml(info.user_defined or {})
        if not xml:
            raise S3Error("NoSuchObjectLockConfiguration")
        return HTTPResponse().with_xml(
            b'<?xml version="1.0" encoding="UTF-8"?>' + xml.encode())

    def put_object_retention(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObjectRetention", bucket, key)
        from ..features import objectlock as olock
        if not self.bucket_meta.get(bucket).object_lock_xml:
            raise S3Error("InvalidRequest",
                          "bucket is missing ObjectLockConfiguration")
        mode, until = olock.parse_retention_xml(ctx.read_body())
        if mode not in ("GOVERNANCE", "COMPLIANCE") or not until:
            raise S3Error("InvalidArgument", "bad retention document")
        vid = ctx.query1("versionId")
        info = self.obj.get_object_info(bucket, key,
                                        GetOptions(version_id=vid))
        md = dict(info.user_defined or {})
        try:
            olock.parse_iso(until)
        except ValueError:
            raise S3Error("InvalidArgument", "bad date") from None
        reason = olock.check_retention_update(
            md, mode, until, self._governance_bypass(ctx, bucket, key))
        if reason is not None:          # date is pre-validated above, so
            raise S3Error("ObjectLocked", reason)   # always a lock denial
        md[olock.MD_MODE] = mode
        md[olock.MD_RETAIN] = until
        md["content-type"] = info.content_type
        self.obj.update_object_metadata(bucket, key, md,
                                        vid or info.version_id)
        return HTTPResponse()

    def get_object_legal_hold(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:GetObjectLegalHold", bucket, key)
        from ..features import objectlock as olock
        info = self.obj.get_object_info(
            bucket, key, GetOptions(version_id=ctx.query1("versionId")))
        return HTTPResponse().with_xml(
            b'<?xml version="1.0" encoding="UTF-8"?>' +
            olock.legal_hold_xml(info.user_defined or {}).encode())

    def put_object_legal_hold(self, ctx, bucket, key) -> HTTPResponse:
        self.authenticate(ctx, "s3:PutObjectLegalHold", bucket, key)
        from ..features import objectlock as olock
        if not self.bucket_meta.get(bucket).object_lock_xml:
            raise S3Error("InvalidRequest",
                          "bucket is missing ObjectLockConfiguration")
        status = olock.parse_legal_hold_xml(ctx.read_body())
        if status not in ("ON", "OFF"):
            raise S3Error("InvalidArgument", "bad legal hold document")
        vid = ctx.query1("versionId")
        info = self.obj.get_object_info(bucket, key,
                                        GetOptions(version_id=vid))
        md = dict(info.user_defined or {})
        md[olock.MD_HOLD] = status
        md["content-type"] = info.content_type
        self.obj.update_object_metadata(bucket, key, md,
                                        vid or info.version_id)
        return HTTPResponse()

    def _parity_for(self, storage_class: str):
        """Per-request parity from the storage_class config subsystem
        (cmd/config/storageclass: STANDARD / REDUCED_REDUNDANCY map to
        EC:n strings). None = the set's default."""
        if self.config is None or not storage_class:
            return None
        key = "rrs" if storage_class == "REDUCED_REDUNDANCY" \
            else "standard"
        try:
            spec = self.config.get("storage_class", key)
        except Exception:  # noqa: BLE001 — unknown subsystem/key
            return None
        if spec.upper().startswith("EC:"):
            try:
                return max(0, int(spec[3:]))
            except ValueError:
                return None
        return None

    def _enforce_quota(self, bucket: str, incoming: int) -> None:
        q = self.bucket_meta.get_quota(bucket)
        if not q or not q.get("quota"):
            return
        limit = int(q["quota"])
        if self._bucket_usage(bucket) + incoming > limit:
            raise S3Error("QuotaExceeded")

    def _bucket_usage(self, bucket: str) -> int:
        """Bytes used by one bucket: the data-usage crawler's cache when
        one is attached (cmd/bucket-quota.go reads dataUsageCache), else
        a listing walk."""
        if self.usage is not None:
            cached = self.usage.bucket_usage(bucket)
            if cached is not None:
                return cached
        used = 0
        marker = ""
        while True:
            objs, _, trunc = self.obj.list_objects(bucket, "", marker,
                                                   "", 1000)
            used += sum(o.size for o in objs)
            if not trunc or not objs:
                return used
            marker = objs[-1].name

    def _notify(self, event_name: str, bucket: str, key: str) -> None:
        if self.events is not None:
            try:
                self.events.send(event_name, bucket, key)
            except Exception:  # noqa: BLE001 — events are best-effort
                pass
        # the data-update tracker rides every mutation signal (reference
        # cmd/data-update-tracker.go marks its bloom on object writes)
        tracker = getattr(self, "update_tracker", None)
        if tracker is not None and \
                not event_name.startswith("s3:ObjectAccessed"):
            try:
                tracker.mark(bucket, key)
            except Exception:  # noqa: BLE001 — hints are best-effort
                pass
        # LEGACY replication pool only: the active-active plane
        # (minio_tpu/replicate/) rides the engine namespace-change feed
        # instead, so every mutation verb reaches it without per-
        # handler call sites (the old hooks here missed bulk delete and
        # multipart commit)
        if self.replication is not None and key and \
                hasattr(self.replication, "on_put"):
            try:
                if event_name.startswith("s3:ObjectCreated:"):
                    self.replication.on_put(bucket, key)
                elif event_name.startswith("s3:ObjectRemoved:"):
                    self.replication.on_delete(bucket, key)
            except Exception:  # noqa: BLE001 — replication is async
                pass


def _parse_max_keys(v: str) -> int:
    try:
        n = int(v)
    except ValueError:
        raise S3Error("InvalidArgument", "max-keys must be an int")
    if n < 0:
        raise S3Error("InvalidArgument", "max-keys must be >= 0")
    return min(n, 1000)  # 0 is a legal request for an empty listing


def _encode_token(marker: str) -> str:
    return base64.urlsafe_b64encode(marker.encode()).decode()


def _decode_token(token: str) -> str:
    try:
        return base64.urlsafe_b64decode(token.encode()).decode()
    except (binascii.Error, ValueError):
        raise S3Error("InvalidArgument", "bad continuation token")


def _parse_copy_source(src: str) -> tuple[str, str, str]:
    src = urllib.parse.unquote(src)
    vid = ""
    if "?versionId=" in src:
        src, vid = src.split("?versionId=", 1)
    src = src.lstrip("/")
    if "/" not in src:
        raise S3Error("InvalidArgument", "bad x-amz-copy-source")
    bucket, key = src.split("/", 1)
    return bucket, key, "" if vid == "null" else vid


def _parse_tagging_xml(body: bytes) -> dict[str, str]:
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise S3Error("MalformedXML")
    tags: dict[str, str] = {}
    for ts in root.iter():
        if ts.tag.split("}")[-1] == "Tag":
            k = v = None
            for sub in ts:
                st = sub.tag.split("}")[-1]
                if st == "Key":
                    k = sub.text or ""
                elif st == "Value":
                    v = sub.text or ""
            if not k or len(k) > 128 or (v and len(v) > 256):
                raise S3Error("InvalidTagKey" if not k or len(k) > 128
                              else "InvalidTagValue")
            tags[k] = v or ""
    if len(tags) > 50:
        raise S3Error("InvalidArgument", "too many tags")
    return tags
