"""POST policy form upload (browser uploads).

The reference's cmd/postpolicyform.go + PostPolicyBucketHandler: a
multipart/form-data POST to the bucket URL carrying a base64 policy
document, a V4 signature over that policy, form fields, and the file.
Conditions supported: exact ["eq", "$field", v], ["starts-with",
"$field", prefix], and ["content-length-range", lo, hi].
"""

from __future__ import annotations

import base64
import datetime as _dt
import hashlib
import hmac
import json
import re
from typing import Optional

from .s3errors import S3Error


def parse_multipart_form(body: bytes, content_type: str
                         ) -> tuple[dict[str, str], bytes, str]:
    """-> (fields, file_bytes, file_name). Minimal RFC 7578 parser."""
    m = re.search(r'boundary="?([^";]+)"?', content_type)
    if not m:
        raise S3Error("MalformedPOSTRequest", "missing boundary")
    boundary = b"--" + m.group(1).encode()
    fields: dict[str, str] = {}
    file_bytes = b""
    file_name = ""
    parts = body.split(boundary)
    for part in parts:
        part = part.strip(b"\r\n")
        if not part or part == b"--":
            continue
        head, _, payload = part.partition(b"\r\n\r\n")
        disp = ""
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-disposition:"):
                disp = line.decode(errors="replace")
        nm = re.search(r'name="([^"]*)"', disp)
        if not nm:
            continue
        name = nm.group(1)
        if name == "file":
            fn = re.search(r'filename="([^"]*)"', disp)
            file_name = fn.group(1) if fn else ""
            file_bytes = payload
        else:
            fields[name] = payload.decode(errors="replace")
    return fields, file_bytes, file_name


def check_post_policy(policy_b64: str, fields: dict[str, str],
                      file_size: int) -> None:
    """Validate form fields against the decoded policy conditions
    (cmd/postpolicyform.go checkPostPolicy)."""
    try:
        doc = json.loads(base64.b64decode(policy_b64))
    except (ValueError, TypeError):
        raise S3Error("MalformedPOSTRequest", "bad policy") from None
    exp = doc.get("expiration")
    if not exp:
        # A policy without an expiration would be replayable forever.
        raise S3Error("MalformedPOSTRequest", "missing expiration")
    try:
        when = _dt.datetime.fromisoformat(exp.replace("Z", "+00:00"))
    except (ValueError, TypeError):
        raise S3Error("MalformedPOSTRequest", "bad expiration") \
            from None
    if when.tzinfo is None:          # no offset given: treat as UTC
        when = when.replace(tzinfo=_dt.timezone.utc)
    if when < _dt.datetime.now(_dt.timezone.utc):
        raise S3Error("AccessDenied", "policy expired")
    lower = {k.lower(): v for k, v in fields.items()}
    for cond in doc.get("conditions", []):
        if isinstance(cond, dict):
            for k, v in cond.items():
                have = lower.get(k.lower(), "")
                if have != v:
                    raise S3Error("AccessDenied",
                                  f"policy condition failed: {k}")
        elif isinstance(cond, list) and len(cond) == 3:
            op, a, b = cond
            op = str(op).lower()
            if op == "content-length-range":
                if not (int(a) <= file_size <= int(b)):
                    raise S3Error("EntityTooLarge"
                                  if file_size > int(b)
                                  else "EntityTooSmall")
                continue
            field = str(a).lstrip("$").lower()
            have = lower.get(field, "")
            if op == "eq" and have != b:
                raise S3Error("AccessDenied",
                              f"policy condition failed: eq {field}")
            if op == "starts-with" and not have.startswith(b):
                raise S3Error(
                    "AccessDenied",
                    f"policy condition failed: starts-with {field}")


def verify_post_signature(fields: dict[str, str], cred_lookup,
                          region: str):
    """V4 POST signature: signature = HMAC-chain(secret, date/region/s3)
    over the base64 policy (same signing key as SigV4 requests)."""
    from . import signature as sig
    lower = {k.lower(): v for k, v in fields.items()}
    policy = lower.get("policy", "")
    amz_cred = lower.get("x-amz-credential", "")
    amz_date = lower.get("x-amz-date", "")
    got_sig = lower.get("x-amz-signature", "")
    if not (policy and amz_cred and amz_date and got_sig):
        raise S3Error("AccessDenied", "missing POST auth fields")
    try:
        access_key, datestamp, reg, svc, term = amz_cred.split("/")
    except ValueError:
        raise S3Error("AccessDenied", "bad credential field") from None
    cred = cred_lookup(access_key)
    key = sig.signing_key(cred.secret_key, datestamp, reg, svc)
    want = hmac.new(key, policy.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, got_sig):
        raise S3Error("SignatureDoesNotMatch")
    return cred
