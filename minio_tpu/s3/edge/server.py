"""Event-loop HTTP frontend (the reference's cmd/http/ epoll listener).

One (or ``MINIO_TPU_EDGE_WORKERS``, via ``SO_REUSEPORT``) asyncio loop
owns every connection: it accepts, parses request lines + headers, and
holds idle keep-alive connections for the cost of a socket + a small
state object — no thread per connection, so tens of thousands of
mostly-idle clients fit where the threaded frontend held hundreds.

The loop never blocks and never reads a body byte:

  * a connection over the ``MINIO_TPU_EDGE_MAX_CONNS`` budget is shed
    (503, ``Connection: close``) straight from the accept callback;
  * a partial request line/header set that misses the
    ``MINIO_TPU_EDGE_HEADER_S`` deadline (slowloris) is shed the same
    way — a shed, not a stuck thread;
  * a complete header block runs ``AdmissionController.pre_admit``
    inline (staging window + scheduler occupancy — pure arithmetic)
    and sheds saturated data writes without occupying a worker;
  * an admitted request is handed, socket and all, to a bounded pool
    of worker threads where the unchanged blocking handler layer runs.
    The ``maxClients`` budget wait happens there, still before any
    body byte is read. Admitted bodies then read zero-copy
    (``recv_into``) through ``_EdgeBodyReader`` into whatever buffer
    the PUT pipeline hands down — the ``BytePool`` staging rings.

After the response the socket returns to the loop for the next
keep-alive request (pipelined bytes carry over); shed and error paths
close. The threaded frontend (``MINIO_TPU_EDGE=off``) remains the
correctness oracle — both run the same middleware
(``edge/dispatch.py``), so behavior can only differ at the transport.
"""

from __future__ import annotations

import asyncio
import queue
import socket
import threading
import urllib.parse
from http.client import responses as _REASONS
from typing import Optional

from ...utils import knobs, telemetry
from .admission import AdmissionController
from .dispatch import finalize_headers, run_request

SERVER_NAME = "MinIO-TPU"
MAX_HEADER_BYTES = 64 << 10        # request line + headers cap
MAX_HEADER_COUNT = 100             # http.server's _MAXHEADERS parity
_RECV = 1 << 16

_ACCEPTED_TOTAL = telemetry.REGISTRY.counter(
    "minio_tpu_edge_accepted_total",
    "Connections accepted by the event-loop frontend")
_REQUESTS_TOTAL = telemetry.REGISTRY.counter(
    "minio_tpu_edge_requests_total",
    "Requests parsed and dispatched by the event-loop frontend")
# event-loop health: how late the loop runs a timer it armed — the
# single number that says "the loop thread is wedged behind a callback"
# (a blocking call smuggled onto the loop shows up here long before
# clients notice). Sampled every MINIO_TPU_EDGE_LAG_S per loop.
_LOOP_LAG_SECONDS = telemetry.REGISTRY.histogram(
    "minio_tpu_edge_loop_lag_seconds",
    "Event-loop timer lag per loop (scheduled vs actual fire time)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))


def _collect_edge_metrics() -> None:
    srv = _LIVE[0]
    if srv is None:
        return
    g = telemetry.REGISTRY.gauge
    g("minio_tpu_edge_open_conns",
      "Connections currently held by the event-loop frontend").set(
        srv.conn_count())
    st = srv.pool.stats()
    g("minio_tpu_edge_pool_size",
      "Bounded worker-pool capacity behind the event loop").set(
        st["size"])
    g("minio_tpu_edge_pool_busy",
      "Edge worker threads currently running a request").set(
        st["busy"])
    g("minio_tpu_edge_pool_idle",
      "Edge worker threads parked waiting for work").set(st["idle"])
    g("minio_tpu_edge_pool_pending",
      "Jobs queued for the edge worker pool, not yet picked up").set(
        st["pending"])


_LIVE: list = [None]
telemetry.REGISTRY.register_collector(_collect_edge_metrics)


def _http_date() -> str:
    from email.utils import formatdate
    return formatdate(usegmt=True)


class _WorkerPool:
    """Bounded-then-elastic pool of DAEMON threads running the
    blocking handler layer behind the loop (stdlib ThreadPoolExecutor
    threads are non-daemon: a long-poll event stream still serving at
    shutdown would wedge interpreter exit and trip the test
    thread-leak sentinel). Threads spawn lazily up to `size`; when
    every pooled worker is pinned (long-poll event streams hold theirs
    for minutes) a job gets a one-shot overflow thread instead of
    queueing behind a stream — degrading to exactly the threaded
    frontend's thread-per-request behavior, so internode RPC and admin
    routers can never be starved by parked S3 streams."""

    def __init__(self, size: int, name: str = "edge-worker"):
        self.size = max(size, 1)
        self._name = name
        self._q: "queue.Queue" = queue.Queue()
        self._mu = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._pending = 0       # jobs queued, not yet picked up
        self._closed = False

    def submit(self, fn, *args) -> None:
        with self._mu:
            if self._closed:
                return
            # credit accounting: a queued-but-unpicked job consumes an
            # idle worker's credit, so two racing submits cannot both
            # bank on the same idle worker (the loser would queue
            # behind a long-poll that parks it for minutes)
            credits = self._idle - self._pending
            if credits <= 0 and len(self._threads) >= self.size:
                # pool saturated: one-shot overflow thread (exits with
                # the job; never parked in the pool)
                threading.Thread(target=self._run_one, args=(fn, args),
                                 daemon=True,
                                 name=f"{self._name}-ovf").start()
                return
            if credits <= 0:
                t = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"{self._name}-{len(self._threads)}")
                self._threads.append(t)
            else:
                t = None
            self._pending += 1
        self._q.put((fn, args))
        if t is not None:
            t.start()

    @staticmethod
    def _run_one(fn, args) -> None:
        try:
            fn(*args)
        except Exception:  # noqa: BLE001 — per-request isolation
            pass

    def _run(self) -> None:
        while True:
            with self._mu:
                self._idle += 1
            job = self._q.get()
            with self._mu:
                self._idle -= 1
                if job is not None:
                    self._pending -= 1
            if job is None:
                return
            fn, args = job
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — per-request isolation;
                pass           # the request's own error paths answered

    def stats(self) -> dict:
        """Live pool occupancy for the exposition-time collector."""
        with self._mu:
            threads = len(self._threads)
            return {"size": self.size, "threads": threads,
                    "idle": self._idle, "pending": self._pending,
                    "busy": max(threads - self._idle, 0)}

    def close(self, join_s: float = 2.0) -> None:
        with self._mu:
            self._closed = True
            threads = list(self._threads)
        for _ in threads:
            self._q.put(None)
        for t in threads:
            t.join(timeout=join_s)   # daemons: a stuck long-poll can't
            # wedge shutdown, and stop() already closed its socket


class _EdgeBodyReader:
    """Content-Length-bounded blocking request-body reader over the
    loop's leftover header buffer + the raw socket. ``readinto`` is the
    zero-copy seam: the PUT hot loop reads straight into its BytePool
    staging buffer through here. Bytes buffered past the body are the
    next pipelined request — ``leftover()`` hands them back to the
    loop."""

    def __init__(self, sock: socket.socket, buf: bytearray, length: int):
        self._sock = sock
        self._buf = buf
        self.remaining = max(length, 0)

    def read(self, n: int = -1) -> bytes:
        """File-like semantics (the threaded frontend reads through a
        BufferedReader): return exactly `n` bytes unless the stream
        ends early — handlers call read_body(content_length) ONCE."""
        if self.remaining <= 0:
            return b""
        if n is None or n < 0 or n > self.remaining:
            n = self.remaining
        out = bytearray()
        if self._buf:
            take = min(n, len(self._buf))
            out += self._buf[:take]
            del self._buf[:take]
        while len(out) < n:
            try:
                chunk = self._sock.recv(min(n - len(out), _RECV))
            except OSError:
                break
            if not chunk:
                break
            out += chunk
        self.remaining -= len(out)
        return bytes(out)

    def readinto(self, b) -> int:
        """Zero-copy fill of the caller's buffer (full unless EOF —
        BufferedReader.readinto parity for the PUT hot loop)."""
        if self.remaining <= 0:
            return 0
        mv = memoryview(b)
        if len(mv) > self.remaining:
            mv = mv[:self.remaining]
        got = 0
        if self._buf:
            take = min(len(mv), len(self._buf))
            mv[:take] = self._buf[:take]
            del self._buf[:take]
            got = take
        while got < len(mv):
            try:
                n = self._sock.recv_into(mv[got:]) or 0
            except OSError:
                break
            if not n:
                break
            got += n
        self.remaining -= got
        return got

    def drain(self) -> None:
        while self.remaining > 0:
            if not self.read(min(self.remaining, _RECV)):
                break

    def leftover(self) -> bytes:
        return bytes(self._buf)


class _Conn:
    """One connection's loop-side state."""

    __slots__ = ("sock", "addr", "buf", "timer", "closed")

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.buf = bytearray()
        self.timer = None
        self.closed = False


class _EdgeLoop(threading.Thread):
    """One event loop + its listener (SO_REUSEPORT shards accepts
    across loops when MINIO_TPU_EDGE_WORKERS > 1)."""

    def __init__(self, edge: "EdgeServer", lsock: socket.socket,
                 idx: int):
        super().__init__(daemon=True, name=f"edge-loop-{idx}")
        self.edge = edge
        self.idx = idx
        self.lsock = lsock
        self.loop = asyncio.new_event_loop()
        self.conns: set = set()
        self._started = threading.Event()
        self._lag_expected = 0.0

    # -- lifecycle -------------------------------------------------------

    def _arm_lag_sampler(self) -> None:
        """Periodic loop-lag probe: schedule a timer, measure how late
        the loop actually ran it. Loop-thread stalls (a blocking call
        that snuck onto the loop, GC pauses, CPU starvation) surface as
        lag here — the PR 11 edge flew blind on exactly this."""
        interval = knobs.get_float("MINIO_TPU_EDGE_LAG_S")
        if interval <= 0:
            return
        lbl = str(self.idx)

        def tick() -> None:
            if self.edge.closed:
                return
            now = self.loop.time()
            _LOOP_LAG_SECONDS.observe(max(now - self._lag_expected, 0.0),
                                      loop=lbl)
            self._lag_expected = now + interval
            self.loop.call_later(interval, tick)

        self._lag_expected = self.loop.time() + interval
        self.loop.call_later(interval, tick)

    def run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.lsock.setblocking(False)
        self.loop.add_reader(self.lsock.fileno(), self._accept)
        self._arm_lag_sampler()
        self._started.set()
        try:
            self.loop.run_forever()
        finally:
            for conn in list(self.conns):
                self._close(conn)
            try:
                self.loop.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def start_and_wait(self) -> None:
        self.start()
        self._started.wait(5.0)

    def stop(self) -> None:
        def _shutdown():
            try:
                self.loop.remove_reader(self.lsock.fileno())
            except Exception:  # noqa: BLE001 — already removed
                pass
            for conn in list(self.conns):
                self._close(conn)
            self.loop.stop()

        try:
            self.loop.call_soon_threadsafe(_shutdown)
        except RuntimeError:
            pass                    # loop already closed
        self.join(timeout=5.0)

    # -- accept ----------------------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self.lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self.edge.closed:
                sock.close()
                return
            _ACCEPTED_TOTAL.inc()
            if self.edge.conn_count() >= self.edge.max_conns:
                # over the connection budget: shed BEFORE any read —
                # the cheapest possible refusal
                decision = self.edge.admission.shed(
                    "conns", "connection budget exhausted, retry")
                self.edge.record_shed("", "/", decision)
                sock.setblocking(False)
                conn = _Conn(sock, addr)
                self.edge.track(conn, +1)
                self._send_close_raw(
                    conn, self.edge.render_response(decision.response("/")))
                continue
            sock.setblocking(False)
            conn = _Conn(sock, addr)
            self.conns.add(conn)
            self.edge.track(conn, +1)
            self._arm(conn, b"")

    # -- header read state machine ---------------------------------------

    def _arm(self, conn: _Conn, leftover: bytes) -> None:
        """(Re)register a connection for its next request. Runs on the
        loop thread (workers get here via call_soon_threadsafe)."""
        if conn.closed or self.edge.closed:
            self._close(conn)
            return
        conn.buf = bytearray(leftover)
        try:
            conn.sock.setblocking(False)
            self.loop.add_reader(conn.sock.fileno(), self._readable,
                                 conn)
        except (OSError, ValueError):
            self._close(conn)
            return
        self._set_timer(conn)
        if b"\r\n\r\n" in conn.buf:      # pipelined request complete
            self._maybe_process(conn)

    def _set_timer(self, conn: _Conn) -> None:
        if conn.timer is not None:
            conn.timer.cancel()
        if conn.buf:
            # partial request on the wire: the header deadline turns a
            # slowloris trickle into a shed, not a held resource
            conn.timer = self.loop.call_later(
                self.edge.header_deadline_s, self._on_header_deadline,
                conn)
        else:
            conn.timer = self.loop.call_later(
                self.edge.idle_deadline_s, self._on_idle, conn)

    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        was_empty = not conn.buf
        conn.buf += data
        if was_empty:
            self._set_timer(conn)        # idle -> header deadline
        self._maybe_process(conn)

    def _on_idle(self, conn: _Conn) -> None:
        self._close(conn)                # quiet keep-alive reaping

    def _on_header_deadline(self, conn: _Conn) -> None:
        decision = self.edge.admission.shed(
            "deadline", "request headers not received in time")
        self.edge.record_shed("", "/", decision)
        self._send_close_raw(
            conn, self.edge.render_response(decision.response("/")))

    # -- parse + dispatch --------------------------------------------------

    def _maybe_process(self, conn: _Conn) -> None:
        head, sep, rest = bytes(conn.buf).partition(b"\r\n\r\n")
        # size check BEFORE the completeness check: a final recv chunk
        # can deliver the terminator and blow past the cap in one step
        # (threaded-oracle parity: http.server caps line + count too)
        if len(head) > MAX_HEADER_BYTES or \
                head.count(b"\r\n") > MAX_HEADER_COUNT:
            self._send_close_raw(conn, self.edge.render_simple(
                431, b"", close=True))
            return
        if not sep:
            return
        # the request leaves the loop here: no reader, no timer
        try:
            self.loop.remove_reader(conn.sock.fileno())
        except (OSError, ValueError):
            pass
        if conn.timer is not None:
            conn.timer.cancel()
            conn.timer = None
        parsed = self.edge.parse_head(head)
        if parsed is None:
            self._send_close_raw(conn, self.edge.render_simple(
                400, b"", close=True))
            return
        method, target, version, headers = parsed
        te = headers.get("transfer-encoding", "").lower()
        if "chunked" in te:
            # chunked request bodies have no Content-Length: without
            # decoding them we can't find the next request's boundary,
            # so reject and close (prevents request smuggling) —
            # threaded-frontend parity
            body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                    b"<Error><Code>NotImplemented</Code><Message>"
                    b"Transfer-Encoding: chunked is not supported"
                    b"</Message></Error>")
            self._send_close_raw(conn, self.edge.render_simple(
                501, body, close=True,
                content_type="application/xml"))
            return
        try:
            length = int(headers.get("content-length", 0) or 0)
        except ValueError:
            self._send_close_raw(conn, self.edge.render_simple(
                400, b"", close=True))
            return
        split = urllib.parse.urlsplit(target)
        path = split.path
        query = urllib.parse.parse_qs(split.query,
                                      keep_blank_values=True)
        # the loop-side half of admission: pure-arithmetic saturation
        # signals shed HERE, before a worker or a body byte is spent
        if not self.edge.is_router_path(path) and method != "OPTIONS":
            decision = self.edge.admission.pre_admit(
                method, path, query, headers)
            if decision is not None:
                self.edge.record_shed(method, path, decision,
                                      query=query, headers=headers)
                resp = decision.response(path)
                finalize_headers(self.edge.api, headers.get("origin"),
                                 resp, method)
                self._send_close_raw(conn,
                                     self.edge.render_response(resp))
                return
        _REQUESTS_TOTAL.inc()
        self.conns.discard(conn)
        self.edge.pool.submit(
            self.edge.serve_request, self, conn, method, target, path,
            split.query, query, headers, version, length, rest)

    # -- loop-side writes --------------------------------------------------

    def _send_close_raw(self, conn: _Conn, payload: bytes) -> None:
        """Best-effort non-blocking write of a canned response, then
        close (shed/parse-error paths — tiny payloads)."""
        if conn.timer is not None:
            conn.timer.cancel()
            conn.timer = None
        try:
            self.loop.remove_reader(conn.sock.fileno())
        except (OSError, ValueError):
            pass

        async def _send():
            try:
                await asyncio.wait_for(
                    self.loop.sock_sendall(conn.sock, payload), 5.0)
            except Exception:  # noqa: BLE001 — client gone: close only
                pass
            finally:
                self._close(conn)

        self.loop.create_task(_send())

    def _close(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn.timer is not None:
            conn.timer.cancel()
            conn.timer = None
        try:
            self.loop.remove_reader(conn.sock.fileno())
        except (OSError, ValueError, RuntimeError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn in self.conns:
            self.conns.discard(conn)
        self.edge.track(conn, -1)


class EdgeServer:
    """The asyncio frontend: listeners + loops + the worker pool."""

    def __init__(self, api, extra_routers, address: str = "127.0.0.1",
                 port: int = 0):
        self.api = api
        self.admission: AdmissionController = api.admission
        self.extra_routers = extra_routers
        self.max_conns = knobs.get_int("MINIO_TPU_EDGE_MAX_CONNS")
        self.header_deadline_s = knobs.get_float("MINIO_TPU_EDGE_HEADER_S")
        self.idle_deadline_s = knobs.get_float("MINIO_TPU_EDGE_IDLE_S")
        workers = max(1, knobs.get_int("MINIO_TPU_EDGE_WORKERS"))
        pool_size = knobs.get_int("MINIO_TPU_EDGE_POOL")
        if pool_size <= 0:
            import os as _os
            pool_size = 8 * (_os.cpu_count() or 1) + 16
        self.pool = _WorkerPool(pool_size)
        self.closed = False
        self._conn_mu = threading.Lock()
        self._conns = 0
        self._live_conns: set = set()

        self._socks: list[socket.socket] = []
        for i in range(workers):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if workers > 1:
                # one listener per loop: the kernel shards accepts
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind((address, port if i == 0 else self.port))
            if i == 0:
                self._addr = s.getsockname()
            s.listen(knobs.get_int("MINIO_TPU_REQUEST_QUEUE"))
            self._socks.append(s)
        self.loops = [_EdgeLoop(self, s, i)
                      for i, s in enumerate(self._socks)]
        _LIVE[0] = self

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> int:
        return self._addr[1]

    def start(self) -> "EdgeServer":
        for lp in self.loops:
            lp.start_and_wait()
        return self

    def stop(self) -> None:
        self.closed = True
        for lp in self.loops:
            lp.stop()              # removes the accept reader first
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        # force-break any worker still blocked on a socket (long-poll
        # event streams, half-open bodies)
        with self._conn_mu:
            live = list(self._live_conns)
        for conn in live:
            try:
                conn.sock.close()
            except OSError:
                pass
        self.pool.close()

    def conn_count(self) -> int:
        with self._conn_mu:
            return self._conns

    def track(self, conn: _Conn, delta: int) -> None:
        with self._conn_mu:
            self._conns += delta
            if delta > 0:
                self._live_conns.add(conn)
            else:
                self._live_conns.discard(conn)

    def is_router_path(self, path: str) -> bool:
        return any(path.startswith(prefix)
                   for prefix, _fn in self.extra_routers)

    def record_shed(self, method: str, path: str, decision,
                    query: Optional[dict] = None,
                    headers: Optional[dict] = None) -> None:
        """Trace-record a loop-side refusal (conns/deadline/pre-admit):
        these never reach the middleware, so the `mc admin trace`
        stream would otherwise miss exactly the requests an overloaded
        server refuses. Runs on the loop thread — record() is a lock +
        a ring append, cheap by design."""
        trace = getattr(self.api, "trace", None)
        if trace is None:
            return
        try:
            from ..trace import api_name_of
            api = api_name_of(method, path, query or {}, headers or {}) \
                if method else ""
            trace.record(method, path, "", 503, 0.0, api=api,
                         shed_reason=decision.reason)
        except Exception:  # noqa: BLE001 — tracing is passive
            pass

    # -- parsing / rendering ---------------------------------------------

    @staticmethod
    def parse_head(head: bytes):
        """(method, target, version, lower-cased headers) or None."""
        try:
            lines = head.decode("latin-1").split("\r\n")
            method, target, version = lines[0].split(" ", 2)
        except (UnicodeDecodeError, ValueError):
            return None
        if not version.startswith("HTTP/1."):
            return None
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            name, sep, value = line.partition(":")
            if not sep:
                return None
            headers[name.strip().lower()] = value.strip()
        return method, target, version, headers

    @staticmethod
    def render_simple(status: int, body: bytes, close: bool = False,
                      content_type: str = "") -> bytes:
        head = (f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"
                f"Server: {SERVER_NAME}\r\nDate: {_http_date()}\r\n"
                f"Content-Length: {len(body)}\r\n")
        if content_type:
            head += f"Content-Type: {content_type}\r\n"
        if close:
            head += "Connection: close\r\n"
        return head.encode("latin-1") + b"\r\n" + body

    @staticmethod
    def render_response(resp) -> bytes:
        """Serialize a non-streaming HTTPResponse (shed/canned paths)."""
        head = (f"HTTP/1.1 {resp.status} "
                f"{_REASONS.get(resp.status, '')}\r\n"
                f"Server: {SERVER_NAME}\r\nDate: {_http_date()}\r\n")
        if "Content-Length" not in resp.headers:
            head += f"Content-Length: {len(resp.body)}\r\n"
        for k, v in resp.headers.items():
            head += f"{k}: {v}\r\n"
        return head.encode("latin-1") + b"\r\n" + resp.body

    # -- the worker half ---------------------------------------------------

    def serve_request(self, lp: _EdgeLoop, conn: _Conn, method: str,
                      target: str, path: str, raw_query: str,
                      query: dict, headers: dict, version: str,
                      length: int, rest: bytes) -> None:
        """Blocking half of one request: budget admission, body,
        handler, response — then back to the loop (keep-alive) or
        close."""
        from .. import signature as sig
        from ..handlers import HTTPResponse, RequestContext
        from .admission import AdmissionTicket

        sock = conn.sock
        ticket = None
        close_conn = [version.startswith("HTTP/1.0")
                      and headers.get("connection", "").lower()
                      != "keep-alive"
                      or headers.get("connection", "").lower() == "close"]
        try:
            sock.setblocking(True)
            if method == "OPTIONS":
                # CORS preflight (threaded do_OPTIONS parity)
                origin = headers.get("origin", "")
                allow = self.api.cors_allow_origin
                resp = HTTPResponse(
                    status=200 if (origin and allow) else 403)
                if origin and allow:
                    resp.headers.update({
                        "Access-Control-Allow-Origin":
                            origin if allow == "*" else allow,
                        "Access-Control-Allow-Methods":
                            "GET, PUT, POST, DELETE, HEAD",
                        "Access-Control-Allow-Headers": headers.get(
                            "access-control-request-headers", "*"),
                        "Access-Control-Max-Age": "3600",
                    })
                self._write_response(conn, method, headers, resp,
                                     close_conn)
                self._finish(lp, conn, None, close_conn[0])
                return
            if not self.is_router_path(path):
                # the budget half of admission — a bounded wait on the
                # worker, still BEFORE any body byte is read (internode
                # RPC and admin routers bypass the budget like they
                # bypassed the handler semaphore: a saturated S3 plane
                # must not deadlock heal/lock traffic)
                got = self.admission.admit(method, path, query, headers,
                                           pre_checked=True)
                if not isinstance(got, AdmissionTicket):
                    self._write_response(conn, method, headers,
                                         got.response(path), close_conn)
                    close_conn[0] = True
                    self._finish(lp, conn, None, True)
                    return
                ticket = got
            if length > 0 and "100-continue" in headers.get(
                    "expect", "").lower():
                # admitted: NOW invite the body (the threaded frontend
                # 100-continues during parse, before admission — the
                # edge's whole point is deciding first)
                sock.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
            req = sig.Request(method=method, path=path, query=query,
                              headers=headers, raw_query=raw_query)
            body = _EdgeBodyReader(sock, bytearray(rest), length)
            ctx = RequestContext(req, body, length)
            ctx.remote_addr = conn.addr[0] if conn.addr else ""
            ctx.secure = False
            if ticket is not None:
                ctx.admission_ticket = ticket

            def respond(resp):
                self._write_response(conn, method, headers, resp,
                                     close_conn)

            run_request(self.api, self.extra_routers, ctx, method,
                        path, respond, caller=ctx.remote_addr)
            if not close_conn[0]:
                # keep-alive hygiene: unread body bytes would be parsed
                # as the next request; closing paths skip the drain
                # (shedding must unload the server)
                body.drain()
                self._finish(lp, conn, body.leftover(), False)
            else:
                self._finish(lp, conn, None, True)
        except Exception:  # noqa: BLE001 — client gone / transport torn
            self._finish(lp, conn, None, True)
        finally:
            if ticket is not None:
                ticket.release()       # idempotent: the handler (or its
                # streaming-response close) normally released already

    def _finish(self, lp: _EdgeLoop, conn: _Conn,
                leftover: Optional[bytes], close: bool) -> None:
        if close or self.closed or conn.closed:
            if not conn.closed:
                conn.closed = True
                try:
                    conn.sock.close()
                except OSError:
                    pass
                self.track(conn, -1)
            return

        def _rearm():
            conn.closed = False
            lp.conns.add(conn)
            lp._arm(conn, leftover or b"")

        try:
            lp.loop.call_soon_threadsafe(_rearm)
        except RuntimeError:           # loop stopped under us
            try:
                conn.sock.close()
            except OSError:
                pass
            self.track(conn, -1)

    def _write_response(self, conn: _Conn, method: str,
                        req_headers: dict, resp, close_conn: list
                        ) -> None:
        """Serialize one HTTPResponse on the worker's blocking socket —
        chunked framing, HEAD semantics and stream-close discipline
        identical to the threaded frontend."""
        chunked, wants_close = finalize_headers(
            self.api, req_headers.get("origin"), resp, method)
        if wants_close:
            close_conn[0] = True
        head = (f"HTTP/1.1 {resp.status} "
                f"{_REASONS.get(resp.status, '')}\r\n"
                f"Server: {SERVER_NAME}\r\nDate: {_http_date()}\r\n")
        for k, v in resp.headers.items():
            head += f"{k}: {v}\r\n"
        if chunked:
            head += "Transfer-Encoding: chunked\r\n"
        sock = conn.sock
        try:
            sock.sendall(head.encode("latin-1") + b"\r\n")
            if method == "HEAD":
                if resp.stream is not None:
                    resp.stream.close()
                return
            if resp.stream is not None:
                if chunked:
                    for chunk in resp.stream:
                        if chunk:
                            sock.sendall(f"{len(chunk):x}\r\n".encode()
                                         + chunk + b"\r\n")
                    sock.sendall(b"0\r\n\r\n")
                else:
                    for chunk in resp.stream:
                        sock.sendall(chunk)
            elif resp.body:
                sock.sendall(resp.body)
        except (BrokenPipeError, ConnectionResetError):
            close_conn[0] = True
        finally:
            if resp.stream is not None:
                # releases the admission slot a streaming response
                # holds, even when the client hung up mid-body
                close = getattr(resp.stream, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
