"""HTTP edge plane: event-loop frontend + unified admission.

The reference's L1 is a RAM-budgeted concurrency gate (``maxClients``,
cmd/handler-api.go) in front of an epoll listener (cmd/http/): idle
keep-alive connections cost a socket, not a thread, and overload is
shed before the server commits resources to a request. This package is
that layer for the fork:

  * :mod:`admission` — the ONE place every shed decision is made.
    ``AdmissionController`` folds the staging-ring exhaustion window,
    batch-scheduler occupancy, and the RAM/CPU ``maxClients`` budget
    into a single verdict issued BEFORE any request-body byte is read.
    The ``tools/check`` ``admission`` lint rule pins the monopoly: a
    ``SlowDown`` shed or ``requests_shed_total`` increment anywhere
    else in the tree is an error.
  * :mod:`dispatch` — the per-request middleware (routing, telemetry
    spans, latency histograms, trace records) shared by both frontends
    so they cannot drift.
  * :mod:`server` — ``EdgeServer``: asyncio event loops (optionally
    ``SO_REUSEPORT``-sharded) parse request lines + headers and hold
    idle keep-alive connections at near-zero cost; admitted requests
    run the unchanged blocking handler layer on a bounded worker pool,
    reading their bodies zero-copy (``readinto``) into the ``BytePool``
    staging rings the PUT pipeline owns.

The threaded frontend stays available behind ``MINIO_TPU_EDGE=off`` as
the escape hatch and correctness oracle (README "HTTP edge and
admission").
"""

from .admission import AdmissionController, AdmissionTicket, ShedDecision
from .server import EdgeServer

__all__ = ["AdmissionController", "AdmissionTicket", "ShedDecision",
           "EdgeServer"]
