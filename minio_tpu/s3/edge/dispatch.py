"""Per-request middleware shared by BOTH HTTP frontends.

The threaded server (``s3/server.py``) and the event-loop edge
(``edge/server.py``) feed the same request snapshot through this one
pipeline — root span, extra-router matching, ``S3ApiHandlers.handle``,
per-API latency/TTFB histograms, trace records — so the two transports
cannot drift: the threaded server stays a byte-level correctness
oracle for the edge (``MINIO_TPU_EDGE=off``).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ...utils import telemetry
from ..trace import api_name_of

# per-API request latency + time-to-first-byte (reference
# cmd/metrics.go httpRequestsDuration, labelled by api name)
_HTTP_DURATION = telemetry.REGISTRY.histogram(
    "minio_tpu_http_requests_duration_seconds",
    "Full HTTP request latency (headers to last body byte) per API")
_HTTP_TTFB = telemetry.REGISTRY.histogram(
    "minio_tpu_http_ttfb_seconds",
    "Time to first response byte per API")
# status-class outcomes per API — the availability half of the SLO
# engine (the duration histogram has no status label on purpose:
# status×api×buckets would triple the exposition for one consumer)
_HTTP_RESPONSES = telemetry.REGISTRY.counter(
    "minio_tpu_http_responses_total",
    "HTTP responses per API and status class (2xx/3xx/4xx/5xx)")


def run_request(api, extra_routers, ctx, command: str, raw_path: str,
                respond: Callable, caller: str = "") -> int:
    """Route + handle one parsed request; ``respond(resp)`` writes it
    on whatever transport owns the socket. Returns the final status.

    Everything observable rides along: the root span covers routing,
    the handler AND the response body (a streaming GET's drive reads
    happen inside it); per-API histograms and the admin trace ring
    record in the finally. Keep-alive body drainage is the transport's
    job — it depends on close semantics only the transport knows.
    """
    api_name = api_name_of(command, ctx.req.path, ctx.req.query,
                           ctx.req.headers)
    t0 = time.perf_counter()
    status = [500]
    ttfb: list = [None]
    root_holder: list = [None]
    shed_reason = [""]

    def _respond(resp) -> None:
        status[0] = resp.status
        shed_reason[0] = getattr(resp, "shed_reason", "")
        # TTFB: handler work is done, the status line goes out now —
        # streaming body time lands in the full duration
        if ttfb[0] is None:
            ttfb[0] = time.perf_counter() - t0
        if resp.long_poll and root_holder[0] is not None:
            # an idle event stream runs for minutes by design — never
            # "slow"
            root_holder[0].slow_exempt = True
        respond(resp)

    trace_id = ""
    try:
        with telemetry.trace(api_name, method=command,
                             path=ctx.req.path) as root:
            root_holder[0] = root
            if api_name in ("Admin", "Health", "Metrics", "WebUI"):
                # admin surfaces stream on purpose (`mc admin trace`
                # idles for its whole window): keeping them as "slow"
                # would crowd the spans ring with content-free trees.
                # Errors still keep.
                root.slow_exempt = True
            trace_id = root.trace_id
            for prefix, router in extra_routers:
                if raw_path.startswith(prefix):
                    resp = router(ctx)
                    if resp is None:
                        # router declined (e.g. the web UI owns only
                        # exact paths under /minio/): keep matching
                        # later-registered routers
                        continue
                    _respond(resp)
                    if resp.status >= 500:
                        root.error = f"http {resp.status}"
                    return status[0]
            _respond(api.handle(ctx))
            if status[0] >= 500:
                root.error = f"http {status[0]}"
    finally:
        dur = time.perf_counter() - t0
        try:
            _HTTP_DURATION.observe(dur, api=api_name)
            _HTTP_RESPONSES.inc(api=api_name,
                                code_class=f"{status[0] // 100}xx")
            if ttfb[0] is not None:
                _HTTP_TTFB.observe(ttfb[0], api=api_name)
        except Exception:  # noqa: BLE001 — telemetry is passive
            pass
        if api.trace is not None:
            try:
                api.trace.record(command, ctx.req.path,
                                 ctx.req.raw_query, status[0], dur,
                                 caller=caller, api=api_name,
                                 trace_id=trace_id, ttfb_s=ttfb[0],
                                 shed_reason=shed_reason[0],
                                 tenant=getattr(ctx, "tenant", ""))
            except Exception:  # noqa: BLE001 — tracing is passive
                pass
    return status[0]


def finalize_headers(api, origin: Optional[str], resp,
                     command: str) -> tuple[bool, bool]:
    """Transport-independent response-header policy, applied in place:
    CORS reflection, Content-Length vs chunked framing. Returns
    (chunked, close_connection) so both frontends frame and tear down
    identically."""
    allow = api.cors_allow_origin
    if origin and allow and \
            "Access-Control-Allow-Origin" not in resp.headers:
        resp.headers["Access-Control-Allow-Origin"] = (
            origin if allow == "*" else allow)
        resp.headers["Access-Control-Expose-Headers"] = (
            "ETag, x-amz-version-id, x-amz-request-id")
    chunked = resp.stream is not None and \
        "Content-Length" not in resp.headers
    close = resp.headers.get("Connection", "").lower() == "close"
    if resp.stream is None and "Content-Length" not in resp.headers:
        resp.headers["Content-Length"] = str(len(resp.body))
    return chunked, close
