"""The unified admission plane — every shed decision is made HERE.

One controller folds the signals that used to live in three places
(the handlers' staging-exhaustion shed window, the ``maxClients``
semaphore inside ``S3ApiHandlers.handle``, and the scheduler-occupancy
probe the background movers read) into ONE verdict issued before any
request-body byte is read:

  * **staging** — the pipeline's ``BytePool`` rings reported a timeout
    within the shed window: new data writes would only queue into a
    stalled pipeline, so they shed immediately;
  * **scheduler** — the live ``BatchScheduler`` has more blocks queued
    for device batches than ``MINIO_TPU_ADMIT_SCHED_QUEUE`` (0 = off):
    the device former is saturated, admitting more encode work grows
    the queue without growing throughput;
  * **admission** — the RAM/CPU ``maxClients`` budget (reference
    cmd/handler-api.go:46-57) is exhausted and no slot freed within
    ``MINIO_TPU_REQUEST_DEADLINE``;
  * **conns** / **deadline** — edge-only signals (connection budget,
    slowloris header deadline) recorded through the same counter so
    every shed lands in ``minio_tpu_requests_shed_total{reason}``;
  * **tenant** — the multi-tenant QoS plane (``s3/qos.py``, attached
    by the handlers when built) found the request's tenant over one of
    its budgets: request rate, byte budget, or weighted admission
    share. Off by default; when off the probe is never consulted.

Shed responses are built here too: 503 ``SlowDown`` with a
``Retry-After`` hint and ``Connection: close`` — shedding must unload
the server, and keep-alive hygiene would otherwise drain a multi-GiB
request body off the socket at the very moment it is overloaded.

The ``tools/check`` ``admission`` rule enforces the monopoly: any
``S3Error("SlowDown")`` construction or ``requests_shed_total``
reference outside this module fails the lint gate.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ...utils import eventlog, knobs, telemetry

# requests shed with 503 SlowDown, by trigger: "staging" (BytePool
# exhaustion window), "scheduler" (device-batch queue saturation),
# "admission" (the maxClients budget wait timed out), "conns" (edge
# connection budget), "deadline" (edge header/slowloris deadline)
_SHED_TOTAL = telemetry.REGISTRY.counter(
    "minio_tpu_requests_shed_total",
    "Requests shed with 503 SlowDown, by reason")

# the APIs that stage payload bytes through the BytePool rings —
# metadata ops on object paths (tagging, CompleteMultipartUpload)
# never touch staging, and completing an upload under pressure
# RELIEVES it
_DATA_WRITE_APIS = ("PutObject", "UploadPart", "PostObject")


def _collect_admission_metrics() -> None:
    """Exposition-time gauges for the live gate (no polling thread)."""
    c = _LIVE[0]
    if c is None:
        return
    telemetry.REGISTRY.gauge(
        "minio_tpu_admission_capacity",
        "Size of the maxClients admission gate").set(c.capacity)
    telemetry.REGISTRY.gauge(
        "minio_tpu_admission_in_use",
        "Admission slots currently held by in-flight requests").set(
        c.in_use())


_LIVE: list = [None]        # most-recently constructed controller
telemetry.REGISTRY.register_collector(_collect_admission_metrics)


class ShedDecision:
    """One refused request: the reason label plus everything a
    transport needs to answer it (status, Retry-After, close)."""

    __slots__ = ("reason", "message", "retry_after")

    def __init__(self, reason: str, message: str, retry_after: int = 1):
        self.reason = reason
        self.message = message
        self.retry_after = max(int(retry_after), 1)

    def response(self, path: str = "/"):
        """The 503 SlowDown HTTPResponse every frontend serves for this
        decision — Retry-After + Connection: close semantics are pinned
        identical across the edge and the threaded oracle."""
        import uuid
        # lazy import: handlers imports this module at init
        from .. import xmlgen
        from ..handlers import HTTPResponse
        from ..s3errors import S3Error
        err = S3Error("SlowDown", self.message)
        body = xmlgen.error_response(err.code, err.message, path,
                                     str(uuid.uuid4()))
        resp = HTTPResponse(status=err.status)
        resp.with_xml(body)
        resp.headers["Retry-After"] = str(self.retry_after)
        resp.headers["Connection"] = "close"
        # ride the reason to the trace stream (dispatch.run_request
        # records it; the edge's loop-side sheds record directly)
        resp.shed_reason = self.reason
        return resp


class AdmissionTicket:
    """One admitted request's slot. ``release()`` is idempotent — the
    handler's finally AND a streaming response's close both funnel
    here, whichever runs first wins. The ticket binds its semaphore at
    admit time: ``resize()`` may swap the controller's gate mid-request
    and acquire/release must hit the same object."""

    __slots__ = ("_sem", "_released", "_qos", "tenant")

    def __init__(self, sem: Optional[threading.BoundedSemaphore],
                 qos=None, tenant: str = ""):
        self._sem = sem
        self._released = False
        # the QoS slot rides the same ticket: release() returns the
        # tenant's in-flight share exactly once, alongside the budget
        self._qos = qos
        self.tenant = tenant

    def release(self) -> None:
        if not self._released:
            self._released = True
            if self._sem is not None:
                self._sem.release()
            if self._qos is not None and self.tenant:
                self._qos.release(self.tenant)


class AdmissionController:
    """The RAM-budgeted concurrency gate in front of everything.

    ``admit()`` is the full decision (pre-body signals + budget wait);
    ``pre_admit()`` is the non-blocking half the event loop runs inline
    so saturation sheds cost no worker thread. Both run before any body
    byte is read. ``shed()`` records edge-originated refusals (conns,
    deadline) in the same counter family.
    """

    def __init__(self, max_clients: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        # Default is CPU-proportional: each data-path request runs real
        # erasure and hashing work, so admitting far more streams than
        # cores only convoys the GIL and splits the cache working set.
        # The cluster boot overrides this with the full RAM+CPU budget
        # (requests_budget) via resize().
        if max_clients is None:
            max_clients = knobs.get_int("MINIO_TPU_MAX_CLIENTS") \
                or max(4, 4 * (os.cpu_count() or 1))
        self.capacity = max(max_clients, 1)
        self._sem = threading.BoundedSemaphore(self.capacity)
        self.deadline = knobs.get_float("MINIO_TPU_REQUEST_DEADLINE") \
            if deadline_s is None else deadline_s
        # staging-pressure shed window: baselined at construction so
        # pre-existing process-global counters don't trip a fresh
        # controller. The fields race benignly across handler threads
        # and the edge loop (monotonic float/int stores), exactly like
        # the handler-resident window they replaced.
        from ...parallel import pipeline as _pl
        self.shed_window_s = knobs.get_float("MINIO_TPU_SHED_WINDOW_S")
        self._shed_last_exhausted = _pl.pool_pressure()["exhausted"]
        self._shed_until = 0.0
        # scheduler-occupancy signal: the object layer is late-bound by
        # the cluster boot (the controller exists before the drives
        # format); 0 disables the signal
        self.sched_queue_limit = knobs.get_int(
            "MINIO_TPU_ADMIT_SCHED_QUEUE")
        self.layer = None
        # the multi-tenant QoS plane (s3/qos.py), attached by the
        # handlers that own this gate; None = no tenant enforcement
        self.qos = None
        _LIVE[0] = self

    # -- sizing ----------------------------------------------------------

    def resize(self, n: int) -> None:
        """Re-size the gate once topology is known (the reference
        computes maxClients from RAM + drive count)."""
        self.capacity = max(n, 1)
        self._sem = threading.BoundedSemaphore(self.capacity)

    def in_use(self) -> int:
        return self.capacity - self._sem._value

    # -- signal probes ---------------------------------------------------

    @staticmethod
    def is_data_write(method: str, path: str, query: dict,
                      headers: dict) -> bool:
        """True for requests that will stage payload bytes through the
        BytePool rings — the only class the load-pressure signals shed
        (reads and metadata ops are never refused for staging)."""
        if method not in ("PUT", "POST"):
            return False
        if "/" not in path.lstrip("/"):
            return False              # bucket-level op, not a data write
        from ..trace import api_name_of
        return api_name_of(method, path, query, headers) \
            in _DATA_WRITE_APIS

    def _staging_stalled(self) -> bool:
        """True within the shed window after a BytePool get() timeout:
        the pipeline is stalled, new writes would only queue into the
        wreck — keep the retry loop on the client, where it belongs."""
        from ...parallel import pipeline as _pl
        now = time.monotonic()
        exhausted = _pl.pool_pressure()["exhausted"]
        if exhausted > self._shed_last_exhausted:
            self._shed_last_exhausted = exhausted
            self._shed_until = now + self.shed_window_s
        return now < self._shed_until

    def _scheduler_saturated(self) -> bool:
        """True when the device batch former's queue crossed the knob
        threshold (the same queued-blocks probe utils/pressure.py feeds
        the background movers, hardened into an admission signal)."""
        limit = self.sched_queue_limit
        if limit <= 0 or self.layer is None:
            return False
        queued = 0
        layers = getattr(self.layer, "server_sets", None) or [self.layer]
        for z in layers:
            for eng in getattr(z, "sets", ()) or ():
                sched = getattr(eng, "scheduler", None)
                if sched is not None:
                    queued += sched.stats()["queued_blocks"]
                    if queued > limit:
                        return True
        return queued > limit

    # -- the decision ----------------------------------------------------

    def pre_admit(self, method: str, path: str, query: dict,
                  headers: dict) -> Optional[ShedDecision]:
        """The non-blocking half: load-pressure signals that refuse a
        request with ZERO body bytes read and no budget slot taken.
        Cheap enough for the event loop to run inline."""
        if self.qos is not None:
            refusal = self.qos.pre_check(method, path, query, headers)
            if refusal is not None:
                return self.shed("tenant", refusal.message,
                                 refusal.retry_after)
        if not self.is_data_write(method, path, query, headers):
            return None
        if self._staging_stalled():
            retry = self._shed_until - time.monotonic()
            return self.shed(
                "staging", "staging buffers exhausted, retry the request",
                retry_after=-(-retry // 1) if retry > 0 else 1)
        if self._scheduler_saturated():
            return self.shed(
                "scheduler", "device batch queue is saturated, retry "
                "the request")
        return None

    def admit(self, method: str, path: str, query: dict, headers: dict,
              pre_checked: bool = False):
        """The full decision: pre-body signals, then the maxClients
        budget (bounded wait — saturated slots shed with 503, never
        wedge every caller forever). Returns an AdmissionTicket or a
        ShedDecision; either way no body byte has been read."""
        if not pre_checked:
            shed = self.pre_admit(method, path, query, headers)
            if shed is not None:
                return shed
        tenant = ""
        if self.qos is not None:
            got = self.qos.admit_slot(method, path, query, headers,
                                      self.capacity)
            if not isinstance(got, str):
                return self.shed("tenant", got.message, got.retry_after)
            tenant = got
        sem = self._sem
        if not sem.acquire(timeout=self.deadline):
            if tenant:
                self.qos.release(tenant)
            return self.shed("admission",
                             "server is busy, retry the request")
        return AdmissionTicket(sem, qos=self.qos if tenant else None,
                               tenant=tenant)

    def shed(self, reason: str, message: str,
             retry_after: int = 1) -> ShedDecision:
        """Record one refusal (the ONLY requests_shed_total increment
        site in the tree) and hand back the decision to serve."""
        _SHED_TOTAL.inc(reason=reason)
        eventlog.emit("admission.shed", reason=reason)
        return ShedDecision(reason, message, retry_after)
