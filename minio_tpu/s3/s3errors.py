"""S3 API error registry + exception→error-code mapping.

The reference keeps a giant table of APIError structs
(cmd/api-errors.go); here the registry maps code name → (http status,
default message), and `api_error_from()` converts object-layer /
signature exceptions into (code, status, message) for the XML error
response writer.
"""

from __future__ import annotations

from ..object import api_errors as oerr
from ..storage import errors as serr
from .signature import SigError

# code -> (http status, message)
ERROR_TABLE: dict[str, tuple[int, str]] = {
    "AccessDenied": (403, "Access Denied."),
    "BadDigest": (400, "The Content-Md5 you specified did not match what "
                       "we received."),
    "EntityTooSmall": (400, "Your proposed upload is smaller than the "
                            "minimum allowed object size."),
    "EntityTooLarge": (400, "Your proposed upload exceeds the maximum "
                            "allowed object size."),
    "IncompleteBody": (400, "You did not provide the number of bytes "
                            "specified by the Content-Length HTTP header."),
    "InternalError": (500, "We encountered an internal error, please try "
                           "again."),
    "InvalidAccessKeyId": (403, "The Access Key Id you provided does not "
                                "exist in our records."),
    "InvalidArgument": (400, "Invalid Argument"),
    "InvalidBucketName": (400, "The specified bucket is not valid."),
    "InvalidDigest": (400, "The Content-Md5 you specified is not valid."),
    "InvalidRange": (416, "The requested range is not satisfiable"),
    "InvalidPart": (400, "One or more of the specified parts could not be "
                         "found."),
    "InvalidPartOrder": (400, "The list of parts was not in ascending "
                              "order."),
    "InvalidObjectState": (403, "The operation is not valid for the "
                                "current state of the object."),
    "MalformedXML": (400, "The XML you provided was not well-formed or "
                          "did not validate against our published schema."),
    "MalformedDate": (400, "Invalid date format header."),
    "MalformedPOSTRequest": (400, "The body of your POST request is not "
                                  "well-formed multipart/form-data."),
    "MissingContentLength": (411, "You must provide the Content-Length "
                                  "HTTP header."),
    "MissingDateHeader": (400, "AWS authentication requires a valid Date "
                               "or x-amz-date header"),
    "NoSuchBucket": (404, "The specified bucket does not exist"),
    "NoSuchBucketPolicy": (404, "The bucket policy does not exist"),
    "NoSuchKey": (404, "The specified key does not exist."),
    "NoSuchUpload": (404, "The specified multipart upload does not exist. "
                          "The upload ID may be invalid, or the upload may "
                          "have been aborted or completed."),
    "NoSuchVersion": (404, "The specified version does not exist."),
    "NotImplemented": (501, "A header you provided implies functionality "
                            "that is not implemented"),
    "PreconditionFailed": (412, "At least one of the pre-conditions you "
                                "specified did not hold"),
    "XAmzContentSHA256Mismatch": (400, "The provided 'x-amz-content-sha256' "
                                       "header does not match what was "
                                       "computed."),
    "RequestTimeTooSkewed": (403, "The difference between the request time "
                                  "and the server's time is too large."),
    "SignatureDoesNotMatch": (403, "The request signature we calculated "
                                   "does not match the signature you "
                                   "provided. Check your key and signing "
                                   "method."),
    "MethodNotAllowed": (405, "The specified method is not allowed against "
                              "this resource."),
    "BucketAlreadyOwnedByYou": (409, "Your previous request to create the "
                                     "named bucket succeeded and you "
                                     "already own it."),
    "BucketAlreadyExists": (409, "The requested bucket name is not "
                                 "available."),
    "BucketNotEmpty": (409, "The bucket you tried to delete is not empty"),
    "AuthorizationHeaderMalformed": (400, "The authorization header is "
                                          "malformed."),
    "SignatureVersionNotSupported": (400, "The requested signature version "
                                          "is not supported."),
    "CredMalformed": (400, "The credential is malformed."),
    "UnsignedHeaders": (400, "There were headers present in the request "
                             "which were not signed"),
    "InvalidQueryParams": (400, "Query-string authentication requires "
                                "X-Amz-Algorithm, X-Amz-Credential, "
                                "X-Amz-Signature, X-Amz-Date, "
                                "X-Amz-SignedHeaders and X-Amz-Expires "
                                "parameters"),
    "MalformedExpires": (400, "Malformed expires value, should be "
                              "non-negative"),
    "NegativeExpires": (400, "X-Amz-Expires must be non-negative"),
    "MaximumExpires": (400, "X-Amz-Expires must be less than a week"),
    "ExpiredPresignRequest": (403, "Request has expired"),
    "RequestNotReadyYet": (403, "Request is not valid yet"),
    "SlowDown": (503, "Resource requested is unreadable, please reduce "
                      "your request rate"),
    "EntityTooSmallPart": (400, "Your proposed upload is smaller than the "
                                "minimum allowed object size."),
    "InvalidRequest": (400, "Invalid Request"),
    "KeyTooLongError": (400, "Your key is too long"),
    "NoSuchLifecycleConfiguration": (404, "The lifecycle configuration "
                                          "does not exist"),
    "RestoreAlreadyInProgress": (409, "Object restore is already in "
                                      "progress"),
    "XMinioAdminTierNotFound": (404, "The remote tier specified does "
                                     "not exist"),
    "XMinioAdminTierAlreadyExists": (409, "The remote tier specified "
                                          "already exists"),
    "XMinioAdminTierBackendInUse": (409, "The remote tier is referenced "
                                         "by a lifecycle rule or "
                                         "transitioned object"),
    "NoSuchTagSet": (404, "The TagSet does not exist"),
    "NoSuchObjectLockConfiguration": (404, "The specified object does not "
                                           "have a ObjectLock "
                                           "configuration"),
    "ObjectLocked": (400, "Object is WORM protected and cannot be "
                          "overwritten"),
    "ReplicationConfigurationNotFoundError": (
        404, "The replication configuration was not found"),
    "ServerSideEncryptionConfigurationNotFoundError": (
        404, "The server side encryption configuration was not found"),
    "InvalidEncryptionAlgorithmError": (
        400, "The Encryption request you specified is not valid. "
             "Supported value: AES256."),
    "NoSuchCORSConfiguration": (404, "The CORS configuration does not "
                                     "exist"),
    "NotificationNotFound": (404, "The notification configuration does "
                                  "not exist"),
    "QuotaExceeded": (409, "Bucket quota exceeded"),
    "AdminInvalidArgument": (400, "Invalid arguments specified"),
    "XMinioInvalidObjectName": (400, "Object name contains unsupported "
                                     "characters."),
    "StorageFull": (507, "Storage backend has reached its minimum free "
                         "disk threshold."),
    "XMinioServerNotInitialized": (503, "Server not initialized, please "
                                        "try again."),
    "InvalidTokenId": (403, "The security token included in the request "
                            "is invalid"),
    "ExpiredToken": (400, "The provided token has expired."),
    "MissingFields": (400, "Missing fields in request."),
    "InvalidTagKey": (400, "The TagKey you have provided is invalid"),
    "InvalidTagValue": (400, "The TagValue you have provided is invalid"),
    "OperationTimedOut": (503, "A timeout occurred while trying to lock a "
                               "resource, please reduce your request rate"),
    "InvalidRegion": (400, "Region does not match."),
    "MalformedPolicy": (400, "Policy has invalid resource."),
    "InvalidPolicyDocument": (400, "The content of the form does not meet "
                                   "the conditions specified in the policy "
                                   "document."),
}


# ObjectApiError subclasses that never cross the HTTP boundary: they
# are consumed by the background planes (MRF retry, scanner sweep)
# before any handler sees them. The `error-map` check in tools/check
# requires every api_errors class to be either mapped below or listed
# here — an unmapped class surfacing as a bare 500 is the bug class
# this table exists to prevent.
INTERNAL_ONLY = (oerr.HealFailed,)


class S3Error(Exception):
    """An error carrying an explicit S3 error code (raised in handlers)."""

    def __init__(self, code: str, message: str = ""):
        status, default_msg = ERROR_TABLE.get(code, (500, code))
        super().__init__(message or default_msg)
        self.code = code
        self.status = status
        self.message = message or default_msg


def api_error_from(exc: Exception) -> S3Error:
    """Map any exception from the stack below into an S3Error
    (reference toAPIErrorCode, cmd/api-errors.go:1721-)."""
    if isinstance(exc, S3Error):
        return exc
    if isinstance(exc, SigError):
        return S3Error(exc.code if exc.code in ERROR_TABLE
                       else "AccessDenied", str(exc))
    mapping = [
        (oerr.BucketNotFound, "NoSuchBucket"),
        (oerr.BucketNotEmpty, "BucketNotEmpty"),
        (oerr.BucketExists, "BucketAlreadyOwnedByYou"),
        (oerr.BucketNameInvalid, "InvalidBucketName"),
        (oerr.VersionNotFound, "NoSuchVersion"),
        (oerr.ObjectNotFound, "NoSuchKey"),
        (oerr.ObjectNameInvalid, "XMinioInvalidObjectName"),
        (oerr.InvalidUploadID, "NoSuchUpload"),
        (oerr.InvalidPart, "InvalidPart"),
        (oerr.PartTooSmall, "EntityTooSmallPart"),
        (oerr.InsufficientReadQuorum, "SlowDown"),
        (oerr.InsufficientWriteQuorum, "SlowDown"),
        (oerr.InvalidRange, "InvalidRange"),
        (oerr.IncompleteBody, "IncompleteBody"),
        (oerr.ObjectTooLarge, "EntityTooLarge"),
        (oerr.EntityTooLarge, "EntityTooLarge"),
        (oerr.EntityTooSmall, "EntityTooSmall"),
        (oerr.PreConditionFailed, "PreconditionFailed"),
        (oerr.InvalidObjectState, "InvalidObjectState"),
        (oerr.TierNotFound, "XMinioAdminTierNotFound"),
        (oerr.InvalidETag, "InvalidDigest"),
        (oerr.ObjectExistsAsDirectory, "MethodNotAllowed"),
        (oerr.MethodNotAllowed, "MethodNotAllowed"),
        (oerr.SignatureDoesNotMatch, "SignatureDoesNotMatch"),
        (oerr.NotImplementedError_, "NotImplemented"),
        (serr.VolumeNotFound, "NoSuchBucket"),
        (serr.FileNotFound, "NoSuchKey"),
        (serr.DiskFull, "StorageFull"),
    ]
    for etype, code in mapping:
        if isinstance(exc, etype):
            return S3Error(code)
    return S3Error("InternalError", str(exc))
