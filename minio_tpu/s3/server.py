"""S3 HTTP server — wire transport for the handler layer.

The reference's L1 frontend (cmd/http/, cmd/routers.go) is an epoll Go
server with a middleware chain; here :class:`S3Server` mounts one of
two transports over the same ``S3ApiHandlers``:

  * the **event-loop edge** (``s3/edge/``, default) — parses headers on
    an asyncio loop, holds idle keep-alive connections for near-zero
    cost, and admits each request through the unified
    ``AdmissionController`` before any body byte is read;
  * the **threaded frontend** (``MINIO_TPU_EDGE=off``, and always for
    TLS listeners) — a thread-per-connection stdlib server kept as the
    escape hatch and correctness oracle.

Both feed the same request snapshot through the same middleware
(``edge/dispatch.py``). Streaming: response bodies may be chunk
iterators (GET path never buffers the whole object).
"""

from __future__ import annotations

import ssl
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..utils import knobs
from . import signature as sig
from .credentials import Credentials
from .edge import EdgeServer
from .edge.dispatch import finalize_headers, run_request
from .handlers import HTTPResponse, RequestContext, S3ApiHandlers

SERVER_NAME = "MinIO-TPU"


class _DeepBacklogServer(ThreadingHTTPServer):
    """socketserver's default listen backlog is 5: a burst of concurrent
    clients overflows the accept queue and gets connection resets (the
    reference listener accepts with a deep backlog too). The depth is
    the MINIO_TPU_REQUEST_QUEUE knob (shared with the edge listeners)."""
    daemon_threads = True

    def __init__(self, *a, **kw):
        self.request_queue_size = knobs.get_int("MINIO_TPU_REQUEST_QUEUE")
        super().__init__(*a, **kw)


class _BodyReader:
    """Content-Length-bounded request-body reader that can drain what the
    handler left unread (keep-alive connection hygiene)."""

    def __init__(self, raw, length: int):
        self.raw = raw
        self.remaining = max(length, 0)

    def read(self, n: int = -1) -> bytes:
        if self.remaining <= 0:
            return b""
        if n is None or n < 0 or n > self.remaining:
            n = self.remaining
        chunk = self.raw.read(n)
        self.remaining -= len(chunk)
        return chunk

    def readinto(self, b) -> int:
        """Zero-copy into the caller's buffer (the PUT hot loop reads
        straight into its encode buffer through here)."""
        if self.remaining <= 0:
            return 0
        mv = memoryview(b)
        if len(mv) > self.remaining:
            mv = mv[:self.remaining]
        n = self.raw.readinto(mv) or 0
        self.remaining -= n
        return n

    def drain(self) -> None:
        while self.remaining > 0:
            if not self.read(min(self.remaining, 1 << 16)):
                break


def _make_handler_class(api: S3ApiHandlers, extra_routers):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = SERVER_NAME

        def log_message(self, fmt, *args):  # silence default stderr log
            pass

        def _snapshot(self) -> RequestContext:
            parsed = urllib.parse.urlsplit(self.path)
            query = urllib.parse.parse_qs(parsed.query,
                                          keep_blank_values=True)
            headers = {k.lower(): v for k, v in self.headers.items()}
            req = sig.Request(method=self.command, path=parsed.path,
                              query=query, headers=headers,
                              raw_query=parsed.query)
            length = int(headers.get("content-length", 0) or 0)
            ctx = RequestContext(req, _BodyReader(self.rfile, length),
                                 length)
            ctx.remote_addr = self.client_address[0]
            ctx.secure = isinstance(self.connection, ssl.SSLSocket)
            return ctx

        def _respond(self, resp: HTTPResponse) -> None:
            # CORS reflection + framing policy shared with the edge
            # (cmd/generic-handlers.go corsHandler)
            chunked, wants_close = finalize_headers(
                api, self.headers.get("Origin"), resp, self.command)
            if wants_close:
                # honor a handler-requested close (load shedding): the
                # socket is being torn down, so the dispatch loop must
                # also skip draining the request body
                self.close_connection = True
            body = resp.body
            self.send_response(resp.status)
            for k, v in resp.headers.items():
                self.send_header(k, v)
            if chunked:
                self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            if self.command == "HEAD":
                if resp.stream is not None:
                    resp.stream.close()
                return
            try:
                if resp.stream is not None:
                    if chunked:
                        for chunk in resp.stream:
                            if chunk:
                                self.wfile.write(
                                    f"{len(chunk):x}\r\n".encode()
                                    + chunk + b"\r\n")
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        for chunk in resp.stream:
                            self.wfile.write(chunk)
                elif body:
                    self.wfile.write(body)
            except BrokenPipeError:
                pass
            finally:
                if resp.stream is not None:
                    # releases the admission slot a streaming response
                    # holds, even when the client hung up mid-body
                    close = getattr(resp.stream, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:  # noqa: BLE001
                            pass

        def _dispatch(self) -> None:
            # chunked request bodies have no Content-Length: without
            # decoding them we can't find the next request's boundary,
            # so reject and close (prevents request smuggling)
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                self.close_connection = True
                body = (b'<?xml version="1.0" encoding="UTF-8"?>'
                        b"<Error><Code>NotImplemented</Code><Message>"
                        b"Transfer-Encoding: chunked is not supported"
                        b"</Message></Error>")
                self.send_response(501)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                int(self.headers.get("Content-Length", 0) or 0)
            except ValueError:
                self.close_connection = True
                self.send_response(400)
                self.send_header("Content-Length", "0")
                self.send_header("Connection", "close")
                self.end_headers()
                return
            ctx = self._snapshot()
            # routing + telemetry + the admission-gated handler all live
            # in the transport-shared middleware (edge/dispatch.py)
            run_request(api, extra_routers, ctx, self.command, self.path,
                        self._respond, caller=self.client_address[0])
            # keep-alive hygiene: any request-body bytes the handler
            # didn't consume (auth failure, early error, streaming
            # trailer) would otherwise be parsed as the next request.
            # Skipped when the connection is closing anyway (shed
            # responses) — draining a multi-GiB body into a closing
            # socket is exactly the load shedding exists to avoid.
            if not self.close_connection:
                ctx.body_stream.drain()

        def do_OPTIONS(self):
            # CORS preflight
            origin = self.headers.get("Origin", "")
            allow = api.cors_allow_origin
            resp = HTTPResponse(status=200 if (origin and allow) else 403)
            if origin and allow:
                resp.headers.update({
                    "Access-Control-Allow-Origin":
                        origin if allow == "*" else allow,
                    "Access-Control-Allow-Methods":
                        "GET, PUT, POST, DELETE, HEAD",
                    "Access-Control-Allow-Headers":
                        self.headers.get(
                            "Access-Control-Request-Headers", "*"),
                    "Access-Control-Max-Age": "3600",
                })
            self._respond(resp)

        do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _dispatch

    return Handler


class S3Server:
    """S3 endpoint over an object layer — edge or threaded transport.

    extra_routers: list of (path_prefix, fn(ctx) -> HTTPResponse) checked
    before S3 routing — used for /minio/admin, /minio/health, metrics.
    """

    def __init__(self, object_layer, address: str = "127.0.0.1",
                 port: int = 0, region: str = "us-east-1",
                 creds: Optional[Credentials] = None, iam=None,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None):
        self.api = S3ApiHandlers(object_layer, region=region, creds=creds,
                                 iam=iam)
        self.extra_routers: list = []
        self.tls = bool(certfile)
        self._httpd = None
        self._edge: Optional[EdgeServer] = None
        # the edge speaks plaintext only today: TLS listeners keep the
        # threaded frontend (README "HTTP edge and admission")
        if knobs.get_bool("MINIO_TPU_EDGE") and not certfile:
            self._edge = EdgeServer(self.api, self.extra_routers,
                                    address, port)
        else:
            self._httpd = _DeepBacklogServer(
                (address, port),
                _make_handler_class(self.api, self.extra_routers))
            if certfile:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(certfile, keyfile)
                self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                     server_side=True)
        self._thread: Optional[threading.Thread] = None

    @property
    def edge_enabled(self) -> bool:
        return self._edge is not None

    @property
    def port(self) -> int:
        if self._edge is not None:
            return self._edge.port
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        if self._edge is not None:
            host = self._edge._addr[0]
            return f"http://{host}:{self._edge.port}"
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{host}:{port}"

    def register_router(self, prefix: str, fn) -> None:
        self.extra_routers.append((prefix, fn))

    def start(self) -> "S3Server":
        if self._edge is not None:
            self._edge.start()
            return self
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._edge is not None:
            self._edge.stop()
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
