"""The multi-tenant QoS plane — fairness enforced at the ONE gate.

Every authenticated request maps to a **tenant**: the root credential,
a plain IAM user, or — for service accounts and STS temp creds — the
parent user they roll up to (reference cmd/iam.go parentUser). The
mapping costs one Authorization-header parse (the *claimed* access
key, no signature work) so it can run inside ``pre_admit`` on the
event loop; the verified credential confirms it post-auth.

Policy is enforced where every other refusal already lives, the
AdmissionController (its monopoly is lint-gated by the ``admission``
rule), as three per-tenant budgets from one registry document:

  * **weighted admission shares** — a tenant's in-flight slots are
    bounded by its share of the maxClients budget, computed over the
    *active* tenant set so unused capacity is borrowable: a lone
    tenant still gets the whole gate;
  * **request-rate budget** — a token bucket per tenant; an empty
    bucket refuses 503 SlowDown + Retry-After before any body byte;
  * **byte budgets** (rx/tx) — admission *peeks* the rx bucket (an
    exhausted budget refuses pre-body without double-charging), then
    the handler paces the admitted body/response streams through the
    same buckets, so a tenant over budget slows to its rate and the
    backlog sheds at the gate, never in the data path.

Budget docs live in ``QoSRegistry`` — epoch-versioned, persisted to
every pool under ``.minio.sys/qos/config.json`` with regfence lineage
like topology/tier/replicate (split-brain-safe; fsck fork coverage for
free). The same doc shape carries per-**tier** budgets the transition
worker paces pushes through (``scope="tier"``).

The plane is **off by default** (``MINIO_TPU_QOS=off``): every probe
returns before touching a lock, and behavior is byte-identical to a
tree without this module (pinned by the parity test on both
frontends). Per-tenant counters are bounded-cardinality: the tenant
label is drawn from the registered-account set plus three sentinels
("root", "anonymous", "unknown").
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ..object import api_errors
from ..storage.xl_storage import MINIO_META_BUCKET
from ..utils import atomicfile, crashpoint, eventlog, knobs, regfence, \
    telemetry
from ..utils.bandwidth import PacedReader, TokenBucket

QOS_PREFIX = "qos/"
QOS_CONFIG_OBJECT = QOS_PREFIX + "config.json"

# tenant sentinels: requests that resolve outside the IAM tables
TENANT_ROOT = "root"
TENANT_ANONYMOUS = "anonymous"
TENANT_UNKNOWN = "unknown"

SCOPES = ("tenant", "tier")

# per-tenant accounting (bounded by the registered-account set + the
# three sentinels — the label-cardinality rule's bound argument)
_TENANT_REQS = telemetry.REGISTRY.counter(
    "minio_tpu_qos_tenant_requests_total",
    "Requests observed by the QoS plane, per tenant")
_TENANT_RX = telemetry.REGISTRY.counter(
    "minio_tpu_qos_tenant_rx_bytes_total",
    "Request-body bytes metered through per-tenant budgets")
_TENANT_TX = telemetry.REGISTRY.counter(
    "minio_tpu_qos_tenant_tx_bytes_total",
    "Response-body bytes metered through per-tenant budgets")
_TENANT_SHED = telemetry.REGISTRY.counter(
    "minio_tpu_qos_tenant_shed_total",
    "Requests refused by a tenant budget, per tenant and budget kind")
_TENANT_LAG = telemetry.REGISTRY.counter(
    "minio_tpu_qos_tenant_lag_seconds_total",
    "Seconds tenant streams stalled waiting for byte budget")


class QoSConfigError(api_errors.ObjectApiError):
    """Invalid QoS operation (bad budget spec, unknown scope/name)."""


class Budget:
    """One scope entry ("tenant" or "tier") of the registry doc.
    Zero means *default/unlimited*: ``share=0`` falls back to
    ``MINIO_TPU_QOS_DEFAULT_SHARE``, a zero rate never refuses."""

    __slots__ = ("name", "share", "rps", "rx_bps", "tx_bps")

    def __init__(self, name: str, share: float = 0.0, rps: float = 0.0,
                 rx_bps: float = 0.0, tx_bps: float = 0.0):
        self.name = name
        self.share = float(share)
        self.rps = float(rps)
        self.rx_bps = float(rx_bps)
        self.tx_bps = float(tx_bps)

    def to_dict(self) -> dict:
        return {"name": self.name, "share": self.share, "rps": self.rps,
                "rx_bps": self.rx_bps, "tx_bps": self.tx_bps}

    @classmethod
    def from_dict(cls, d: dict) -> "Budget":
        name = str(d.get("name", "")).strip()
        if not name:
            raise QoSConfigError("budget needs a name")
        try:
            vals = {k: float(d.get(k, 0) or 0)
                    for k in ("share", "rps", "rx_bps", "tx_bps")}
        except (TypeError, ValueError):
            raise QoSConfigError(f"budget {name!r}: rates must be numbers")
        for k, v in vals.items():
            if v < 0:
                raise QoSConfigError(f"budget {name!r}: {k} must be >= 0")
        return cls(name=name, **vals)


class QoSRegistry:
    """The persisted budget registry: two scopes ("tenant", "tier"),
    epoch-versioned and written to EVERY pool with regfence lineage —
    the exact durability rule of the topology/tier/replicate
    registries, so fsck's ``registry_epoch_fork`` coverage applies
    unchanged. Mutations persist BEFORE they take effect and roll back
    when the write quorum is missed."""

    def __init__(self, object_layer=None):
        self.obj = object_layer
        self._mu = threading.Lock()
        self.epoch = 0
        self.updated = time.time()
        self.tenants: dict[str, Budget] = {}
        self.tiers: dict[str, Budget] = {}
        self.writer = ""
        self.parent_lineage = ""
        self.lineage = ""

    def _advance_lineage(self) -> None:
        """Chain the fencing hash for the epoch just committed (caller
        holds ``_mu``)."""
        self.parent_lineage = self.lineage
        self.writer = regfence.default_writer()
        self.lineage = regfence.lineage(self.parent_lineage,
                                        self.epoch, self.writer)

    def _table(self, scope: str) -> dict[str, Budget]:
        if scope == "tenant":
            return self.tenants
        if scope == "tier":
            return self.tiers
        raise QoSConfigError(f"unknown QoS scope {scope!r} "
                             f"(expected one of {SCOPES})")

    # ------------------------------------------------------------------
    # registry CRUD
    # ------------------------------------------------------------------

    def set_budget(self, scope: str, budget: Budget) -> int:
        """Register or replace one budget; returns the new epoch."""
        with self._mu:
            table = self._table(scope)
            prev = table.get(budget.name)
            table[budget.name] = budget
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:          # roll the in-memory registry back
                if prev is None:
                    table.pop(budget.name, None)
                else:
                    table[budget.name] = prev
            raise
        self._emit_update(epoch)
        return epoch

    def remove_budget(self, scope: str, name: str) -> int:
        with self._mu:
            table = self._table(scope)
            if name not in table:
                raise QoSConfigError(
                    f"no {scope} budget named {name!r}")
            prev = table.pop(name)
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:
                table[name] = prev
            raise
        self._emit_update(epoch)
        return epoch

    def get(self, scope: str, name: str) -> Optional[Budget]:
        with self._mu:
            return self._table(scope).get(name)

    def list(self, scope: str) -> list[dict]:
        with self._mu:
            return [b.to_dict() for b in
                    sorted(self._table(scope).values(),
                           key=lambda b: b.name)]

    def _emit_update(self, epoch: int) -> None:
        with self._mu:
            tenants, tiers = len(self.tenants), len(self.tiers)
        eventlog.emit("qos.update", epoch=epoch, tenants=tenants,
                      tiers=tiers)

    # ------------------------------------------------------------------
    # persistence (the topology plane's every-pool, fenced-epoch rule)
    # ------------------------------------------------------------------

    def to_dict(self) -> dict:
        with self._mu:
            return {"epoch": self.epoch, "updated": self.updated,
                    "tenants": [b.to_dict()
                                for b in self.tenants.values()],
                    "tiers": [b.to_dict() for b in self.tiers.values()],
                    "writer": self.writer,
                    "parent_lineage": self.parent_lineage,
                    "lineage": self.lineage}

    def _pools(self):
        if self.obj is None:
            return []
        return getattr(self.obj, "server_sets", None) or [self.obj]

    def save(self) -> int:
        """Write the registry to every pool; the configured write
        quorum must land or the mutation is rejected (caller rolls
        back)."""
        pools = self._pools()
        if not pools:
            return 0
        payload = json.dumps(self.to_dict()).encode()
        landed = 0
        last: Optional[Exception] = None
        for z in pools:
            try:
                # one hit per pool (arm :<nth>)
                crashpoint.hit("qos.save.pool")
                z.put_object(MINIO_META_BUCKET, QOS_CONFIG_OBJECT,
                             payload)
                landed += 1
            except Exception as e:  # noqa: BLE001 — per-pool durability
                last = e
        need = regfence.write_quorum(len(pools))
        if landed < need:
            # refusing a minority-side epoch bump (caller rolls back)
            raise QoSConfigError(
                f"qos config epoch {self.epoch} persisted to {landed} "
                f"of {len(pools)} pool(s), need {need}: {last!r}")
        return landed

    def load(self) -> bool:
        """Recover the newest persisted registry (deterministic winner
        across pools); returns True when a doc was found."""
        docs: list[dict] = []
        for z in self._pools():
            try:
                _, stream = z.get_object(MINIO_META_BUCKET,
                                         QOS_CONFIG_OBJECT)
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:     # torn/truncated copy: other pools win
                continue
            docs.append(doc)
        best = regfence.pick_best(docs)
        if best is None:
            return False
        tables: dict[str, dict[str, Budget]] = {"tenants": {},
                                                "tiers": {}}
        for key in tables:
            for d in best.get(key, []):
                try:
                    b = Budget.from_dict(d)
                except QoSConfigError:
                    continue
                tables[key][b.name] = b
        with self._mu:
            self.epoch = int(best.get("epoch", 0))
            self.updated = float(best.get("updated", time.time()))
            self.tenants = tables["tenants"]
            self.tiers = tables["tiers"]
            self.writer = str(best.get("writer", ""))
            self.parent_lineage = str(best.get("parent_lineage", ""))
            self.lineage = str(best.get("lineage", ""))
        return True


class Refusal:
    """One tenant-budget refusal: what the AdmissionController needs to
    shed it (message + Retry-After) plus the accounting facts."""

    __slots__ = ("tenant", "kind", "message", "retry_after")

    def __init__(self, tenant: str, kind: str, message: str,
                 retry_after: int = 1):
        self.tenant = tenant
        self.kind = kind
        self.message = message
        self.retry_after = max(int(retry_after), 1)


def claimed_access_key(headers: dict, query: dict) -> str:
    """The access key a request *claims* (no signature verification):
    enough to pick the budget to charge — a forged claim only ever
    borrows a STRICTER budget and still fails auth afterwards. Header
    names are lower-cased by both frontends (signature.Request
    contract)."""
    auth = headers.get("authorization", "")
    if auth.startswith("AWS4-"):
        i = auth.find("Credential=")
        if i >= 0:
            cred = auth[i + len("Credential="):]
            return cred.split(",", 1)[0].strip().split("/", 1)[0]
        return ""
    if auth.startswith("AWS "):
        return auth[4:].split(":", 1)[0].strip()
    v = query.get("X-Amz-Credential")
    if v:
        return str(v[0]).split("/", 1)[0]
    v = query.get("AWSAccessKeyId")
    if v:
        return str(v[0])
    return ""


class QoSPlane:
    """The live enforcement state the AdmissionController consults.

    Holds the registry, per-tenant token buckets (rebuilt when the
    registry epoch moves), and the in-flight slot ledger behind the
    weighted-share rule. Everything here is pre-body-cheap: the hot
    probes are one dict lookup plus one bucket refill under a lock.
    """

    def __init__(self, registry: Optional[QoSRegistry] = None,
                 iam_lookup=None, root_access_key: str = ""):
        self.registry = registry if registry is not None else QoSRegistry()
        # late-bound: S3ApiHandlers gets its IAMSys after construction
        self._iam_lookup = iam_lookup or (lambda: None)
        self.root_access_key = root_access_key
        self._mu = threading.Lock()
        self._buckets: dict[tuple[str, str], TokenBucket] = {}
        self._gen = -1                     # registry epoch the buckets saw
        self._inflight: dict[str, int] = {}
        self._last_seen: dict[str, float] = {}
        self._shed_emitted: dict[str, float] = {}

    # -- switches --------------------------------------------------------

    @staticmethod
    def enabled() -> bool:
        """Read per request (a knob getter, so tests can flip the env
        mid-process); the default-off path costs one env lookup."""
        return knobs.get_bool("MINIO_TPU_QOS")

    # -- tenant resolution -----------------------------------------------

    def resolve_tenant(self, access_key: str) -> str:
        """Access key -> tenant: root cred -> "root", registered keys
        roll up to their parent account, everything else lands on the
        bounded sentinels."""
        if not access_key:
            return TENANT_ANONYMOUS
        if access_key == self.root_access_key:
            return TENANT_ROOT
        iam = self._iam_lookup()
        if iam is not None:
            account = iam.account_of(access_key)
            if account is not None:
                if account == self.root_access_key:
                    return TENANT_ROOT
                return account
        return TENANT_UNKNOWN

    def tenant_of(self, headers: dict, query: dict) -> str:
        return self.resolve_tenant(claimed_access_key(headers, query))

    def tenant_for_cred(self, cred) -> str:
        """Post-auth confirmation from the VERIFIED credential (same
        value the claimed-key parse produced, derived independently)."""
        if cred is None:
            return TENANT_ANONYMOUS
        if cred.access_key == self.root_access_key:
            return TENANT_ROOT
        account = getattr(cred, "parent_user", "") or cred.access_key
        if account == self.root_access_key:
            return TENANT_ROOT
        return account

    # -- budgets & buckets -----------------------------------------------

    def _budget(self, tenant: str) -> Optional[Budget]:
        return self.registry.get("tenant", tenant)

    def share_of(self, tenant: str) -> float:
        b = self._budget(tenant)
        share = b.share if b is not None and b.share > 0 else \
            knobs.get_float("MINIO_TPU_QOS_DEFAULT_SHARE")
        return max(share, 0.01)

    def _rate_for(self, kind: str, tenant: str) -> float:
        b = self._budget(tenant)
        if kind == "rps":
            rate = b.rps if b is not None else 0.0
            return rate or knobs.get_float("MINIO_TPU_QOS_DEFAULT_RPS")
        if kind == "rx":
            rate = b.rx_bps if b is not None else 0.0
            return rate or knobs.get_float("MINIO_TPU_QOS_DEFAULT_RX_BPS")
        rate = b.tx_bps if b is not None else 0.0
        return rate or knobs.get_float("MINIO_TPU_QOS_DEFAULT_TX_BPS")

    def bucket(self, kind: str, tenant: str) -> TokenBucket:
        """The (kind, tenant) token bucket; the cache is dropped
        whenever the registry epoch moves so budget updates take effect
        on the next request."""
        epoch = self.registry.epoch
        with self._mu:
            if epoch != self._gen:
                self._buckets.clear()
                self._gen = epoch
            b = self._buckets.get((kind, tenant))
            if b is None:
                b = TokenBucket(self._rate_for(kind, tenant))
                self._buckets[(kind, tenant)] = b
            return b

    # -- the admission hooks ---------------------------------------------

    def pre_check(self, method: str, path: str, query: dict,
                  headers: dict) -> Optional[Refusal]:
        """The pre-body budget probe, run once per request from
        ``AdmissionController.pre_admit`` (loop-side on the edge):
        request-rate bucket, then — for requests announcing a body —
        a *peek* of the rx byte bucket. Returns a Refusal or None; no
        body byte has been read either way."""
        if not self.enabled():
            return None
        tenant = self.tenant_of(headers, query)
        _TENANT_REQS.inc(tenant=tenant)
        wait = self.bucket("rps", tenant).try_take(1)
        if wait > 0:
            return self._refuse(tenant, "rate", wait)
        if method in ("PUT", "POST"):
            try:
                length = int(headers.get("content-length", "") or 0)
            except (TypeError, ValueError):
                length = 0
            if length > 0:
                wait = self.bucket("rx", tenant).peek(length)
                if wait > 0:
                    return self._refuse(tenant, "bytes", wait)
        return None

    def admit_slot(self, method: str, path: str, query: dict,
                   headers: dict, capacity: int):
        """The weighted-share gate, run from ``admit`` on every
        request: returns the tenant name (the ticket parks it for
        release/pacing; "" when the plane is off) or a Refusal when
        the tenant is at its bound.

        The bound: each *active* tenant (in flight now, or seen within
        the activity window) is guaranteed ``capacity × share/Σ active
        shares`` slots, floored at 1; whatever the guarantees leave
        unclaimed is borrowable by anyone — a lone tenant's bound is
        the whole gate."""
        if not self.enabled():
            return ""
        tenant = self.tenant_of(headers, query)
        now = time.monotonic()
        horizon = now - knobs.get_float("MINIO_TPU_QOS_ACTIVE_S")
        with self._mu:
            for t in [t for t, seen in self._last_seen.items()
                      if seen < horizon and not self._inflight.get(t)]:
                self._last_seen.pop(t, None)
                self._inflight.pop(t, None)
            self._last_seen[tenant] = now
            active = set(self._last_seen)
            active.add(tenant)
        shares = {t: self.share_of(t) for t in active}
        total_share = sum(shares.values())
        with self._mu:
            guaranteed = {
                t: max(1, int(capacity * shares[t] / total_share))
                for t in active}
            loose = max(0, capacity - sum(guaranteed.values()))
            bound = guaranteed[tenant] + loose
            mine = self._inflight.get(tenant, 0)
            if mine >= bound:
                pass                      # refuse below, outside _mu
            else:
                self._inflight[tenant] = mine + 1
                return tenant
        return self._refuse(tenant, "share",
                            1.0, f"tenant {tenant} is at its admission "
                            "share, retry the request")

    def release(self, tenant: str) -> None:
        if not tenant:
            return
        with self._mu:
            n = self._inflight.get(tenant, 0)
            if n > 1:
                self._inflight[tenant] = n - 1
            else:
                self._inflight.pop(tenant, None)
            self._last_seen[tenant] = time.monotonic()

    def _refuse(self, tenant: str, kind: str, wait: float,
                message: str = "") -> Refusal:
        _TENANT_SHED.inc(tenant=tenant, kind=kind)
        self._note_shed(tenant, kind)
        retry = max(1, int(-(-wait // 1)))
        return Refusal(
            tenant, kind,
            message or f"tenant {tenant} is over its {kind} budget, "
            "retry the request", retry)

    def _note_shed(self, tenant: str, kind: str) -> None:
        """First shed per tenant per window lands in the event journal
        (debounced — budget refusals under sustained overload would
        otherwise flood the ring at the request rate)."""
        now = time.monotonic()
        window = knobs.get_float("MINIO_TPU_QOS_SHED_WINDOW_S")
        with self._mu:
            last = self._shed_emitted.get(tenant, 0.0)
            if now - last < window:
                return
            self._shed_emitted[tenant] = now
        eventlog.emit("tenant.shed", tenant=tenant, reason=kind)

    # -- data-path pacing --------------------------------------------------

    def paced_body(self, tenant: str, body):
        """Wrap an admitted request-body reader: bytes pace through the
        tenant's rx bucket and land in the rx/lag counters."""
        return PacedReader(
            body, self.bucket("rx", tenant),
            on_bytes=lambda n: _TENANT_RX.inc(n, tenant=tenant),
            on_wait=lambda s: _TENANT_LAG.inc(s, tenant=tenant))

    def paced_stream(self, tenant: str, stream):
        """Wrap a response chunk iterator through the tx bucket."""
        return self.bucket("tx", tenant).paced(
            stream,
            on_bytes=lambda n: _TENANT_TX.inc(n, tenant=tenant),
            on_wait=lambda s: _TENANT_LAG.inc(s, tenant=tenant))

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Per-tenant live + cumulative view for the admin surface."""
        with self._mu:
            tenants = set(self._inflight) | set(self._last_seen)
            inflight = dict(self._inflight)
        tenants.update(b["name"] for b in self.registry.list("tenant"))
        out = {}
        for t in sorted(tenants):
            sheds = sum(v for key, v in _TENANT_SHED.series().items()
                        if dict(key).get("tenant") == t)
            out[t] = {
                "inflight": inflight.get(t, 0),
                "share": self.share_of(t),
                "requests": _TENANT_REQS.value(tenant=t),
                "rx_bytes": _TENANT_RX.value(tenant=t),
                "tx_bytes": _TENANT_TX.value(tenant=t),
                "shed": sheds,
                "lag_s": round(_TENANT_LAG.value(tenant=t), 3),
            }
        return out
