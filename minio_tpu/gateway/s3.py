"""S3 gateway: proxy ObjectLayer over an upstream S3 endpoint
(reference cmd/gateway/s3/gateway-s3.go): every ObjectLayer verb maps to
a client call against the backend; this node adds its own auth/IAM,
caching, and policy layers in front."""

from __future__ import annotations

import io
from typing import Iterator, Optional

from ..object import api_errors
from ..object.engine import GetOptions, PutOptions
from ..object.hash_reader import HashReader
from ..storage.datatypes import ObjectInfo, VolInfo, single_version_page
from ..s3.credentials import Credentials
from ..utils.s3client import S3Client, S3ClientError


def _map_err(e: S3ClientError, bucket: str, key: str = "") -> Exception:
    if e.code == "NoSuchBucket" or (e.status == 404 and not key):
        return api_errors.BucketNotFound(bucket)
    if e.code == "NoSuchKey" or e.status == 404:
        return api_errors.ObjectNotFound(bucket, key)
    if e.code == "BucketAlreadyOwnedByYou" or e.code == "BucketAlreadyExists":
        return api_errors.BucketExists(bucket)
    if e.status == 403:
        return api_errors.ObjectApiError(f"upstream denied: {e.code}")
    return api_errors.ObjectApiError(f"upstream error: {e}")


class S3GatewayObjects:
    """ObjectLayer over a remote S3 endpoint."""

    # parts are buffered and re-uploaded whole; local SSE would break
    # part-ETag semantics (the handler checks this capability)
    supports_sse_multipart = False

    def __init__(self, client: S3Client):
        self.c = client

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        try:
            self.c.make_bucket(bucket)
        except S3ClientError as e:
            raise _map_err(e, bucket) from None

    def bucket_exists(self, bucket: str) -> bool:
        return self.c.bucket_exists(bucket)

    def get_bucket_info(self, bucket: str) -> VolInfo:
        if not self.c.bucket_exists(bucket):
            raise api_errors.BucketNotFound(bucket)
        return VolInfo(bucket, 0.0)

    def list_buckets(self) -> list[VolInfo]:
        return [VolInfo(n, t) for n, t in self.c.list_buckets()]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        try:
            self.c.delete_bucket(bucket)
        except S3ClientError as e:
            raise _map_err(e, bucket) from None

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        opts = opts or PutOptions()
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.verify()
            reader.close()
        md = {}
        for k, v in opts.metadata.items():
            lk = k.lower()
            if lk.startswith("x-amz-meta-") or lk in (
                    "content-type", "content-encoding", "cache-control"):
                md[k] = v
        try:
            etag = self.c.put_object(bucket, key, body, md)
        except S3ClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key, size=len(body),
                          etag=etag)

    def get_object_info(self, bucket: str, key: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        try:
            h = self.c.head_object(bucket, key)
        except S3ClientError as e:
            raise _map_err(e, bucket, key) from None
        from email.utils import parsedate_to_datetime
        try:
            mt = parsedate_to_datetime(h.get("last-modified",
                                             "")).timestamp()
        except (TypeError, ValueError):
            mt = 0.0
        return ObjectInfo(
            bucket=bucket, name=key,
            size=int(h.get("content-length", 0) or 0),
            etag=h.get("etag", "").strip('"'), mod_time=mt,
            content_type=h.get("content-type", ""),
            user_defined={k: v for k, v in h.items()
                          if k.startswith("x-amz-meta-")})

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info = self.get_object_info(bucket, key, opts)
        if length < 0:
            length = info.size - offset
        try:
            _, stream = self.c.get_object(bucket, key, offset, length)
        except S3ClientError as e:
            raise _map_err(e, bucket, key) from None
        return info, stream

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        try:
            self.c.delete_object(bucket, key)
        except S3ClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key)

    def delete_objects(self, bucket: str, objects: list[str]):
        out = []
        for o in objects:
            try:
                self.delete_object(bucket, o)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-key result
                out.append(e)
        return out

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        raise api_errors.NotImplementedError_(
            "metadata update through the S3 gateway")

    def has_object_versions(self, bucket: str, key: str) -> bool:
        try:
            self.get_object_info(bucket, key)
            return True
        except api_errors.ObjectApiError:
            return False

    def heal_object(self, bucket: str, key: str, version_id: str = "",
                    deep_scan: bool = False, dry_run: bool = False):
        from ..object.healing import HealResultItem
        return HealResultItem(bucket=bucket, object=key, disks_total=0)

    # -- listing -----------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", delimiter: str = "",
                     max_keys: int = 1000):
        try:
            objs, prefixes, _tok = self.c.list_objects_v2(
                bucket, prefix, delimiter, "", max_keys)
        except S3ClientError as e:
            raise _map_err(e, bucket) from None
        out = [ObjectInfo(bucket=bucket, name=o["key"], size=o["size"],
                          etag=o["etag"], mod_time=o["mod_time"])
               for o in objs if not marker or o["key"] > marker]
        return out, prefixes, bool(_tok)

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000,
                             version_marker: str = "",
                             delimiter: str = ""):
        objs, pfx, trunc = self.list_objects(bucket, prefix, marker,
                                             delimiter, max_keys)
        return single_version_page(objs, trunc, pfx)

    # -- multipart (buffered passthrough) ----------------------------------

    def new_multipart_upload(self, bucket, key, opts=None) -> str:
        import uuid as _uuid
        self.get_bucket_info(bucket)
        uid = str(_uuid.uuid4())
        self._mpu = getattr(self, "_mpu", {})
        self._mpu[uid] = {"bucket": bucket, "key": key, "parts": {},
                          "metadata": dict((opts or PutOptions()).metadata)}
        return uid

    def get_multipart_info(self, bucket, key, uid) -> dict:
        return dict(self._up(bucket, key, uid).get("metadata", {}))

    def _up(self, bucket, key, uid):
        mpu = getattr(self, "_mpu", {}).get(uid)
        if mpu is None or mpu["bucket"] != bucket or mpu["key"] != key:
            raise api_errors.InvalidUploadID(uid)
        return mpu

    def put_object_part(self, bucket, key, uid, part_number, reader,
                        size=-1):
        import hashlib as _hl
        mpu = self._up(bucket, key, uid)
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.close()
        etag = _hl.md5(body).hexdigest()
        from ..storage.datatypes import ObjectPartInfo
        mpu["parts"][part_number] = (etag, body)
        return ObjectPartInfo(number=part_number, etag=etag,
                              size=len(body), actual_size=len(body))

    def list_object_parts(self, bucket, key, uid, part_marker=0,
                          max_parts=1000):
        from ..storage.datatypes import ObjectPartInfo
        mpu = self._up(bucket, key, uid)
        return [ObjectPartInfo(number=n, etag=e, size=len(b),
                               actual_size=len(b))
                for n, (e, b) in sorted(mpu["parts"].items())
                if n > part_marker][:max_parts]

    def list_multipart_uploads(self, bucket, key=""):
        return [{"object": m["key"], "upload_id": uid, "initiated": 0.0}
                for uid, m in getattr(self, "_mpu", {}).items()
                if m["bucket"] == bucket and (not key or m["key"] == key)]

    def abort_multipart_upload(self, bucket, key, uid) -> None:
        self._up(bucket, key, uid)
        self._mpu.pop(uid, None)

    def complete_multipart_upload(self, bucket, key, uid, parts):
        mpu = self._up(bucket, key, uid)
        body = b""
        for cp in parts:
            stored = mpu["parts"].get(cp.part_number)
            if stored is None or stored[0] != cp.etag.strip('"'):
                raise api_errors.InvalidPart(cp.part_number)
            body += stored[1]
        info = self.put_object(bucket, key, body,
                               opts=PutOptions(metadata=mpu["metadata"]))
        self._mpu.pop(uid, None)
        return info

    def storage_info(self) -> dict:
        return {"total": 0, "free": 0, "used": 0, "online_disks": 1,
                "offline_disks": 0, "sets": 0, "drives_per_set": 0,
                "backend": "gateway-s3"}

    def close(self) -> None:
        pass


class S3Gateway:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, region: str = "us-east-1"):
        self.client = S3Client(host, port,
                               Credentials(access_key, secret_key),
                               region)

    def object_layer(self) -> S3GatewayObjects:
        return S3GatewayObjects(self.client)
