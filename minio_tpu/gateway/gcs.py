"""GCS gateway: ObjectLayer over Google Cloud Storage's JSON API
(reference cmd/gateway/gcs/gateway-gcs.go, 1508 LoC: OAuth2 JSON API,
compose-based multipart, GCS error mapping).

Two modes:

* **JSON API** (the reference's mode, default here when a service
  account or token is given): hand-rolled REST client over
  ``/storage/v1`` + ``/upload/storage/v1`` with OAuth2 service-account
  JWT-bearer grants (RS256 via `cryptography`, no SDK). Multipart
  uploads mirror the reference's durable scheme — parts live as
  ``minio.sys.tmp/multipart/v1/<uploadID>/<NNNNN>.<etag>`` objects with
  a ``gcs.json`` session meta, and completion COMPOSES them (groups of
  <= 32, the GCS compose limit) into intermediate objects and then the
  final key (gateway-gcs.go:1267 CompleteMultipartUpload).
* **XML interop** (fallback, `hmac_key`/`hmac_secret`): GCS's S3-dialect
  surface over the existing S3 client — useful where only HMAC
  interoperability keys exist.
"""

from __future__ import annotations

import base64
import hashlib
import io
import json
import time
import urllib.parse
import uuid as _uuid
from typing import Iterator, Optional

from ..object import api_errors
from ..object.engine import GetOptions, PutOptions
from ..object.hash_reader import HashReader
from ..storage.datatypes import ObjectInfo, ObjectPartInfo, VolInfo, single_version_page
from ..s3.credentials import Credentials
from ..utils.s3client import S3Client
from .s3 import S3GatewayObjects

GCS_SYS_TMP = "minio.sys.tmp/"
_MPU_PATH = GCS_SYS_TMP + "multipart/v1"
_MPU_META = "gcs.json"
_MPU_META_VERSION = "1"
MAX_COMPONENTS = 32                    # GCS compose limit
MIN_PART_SIZE = 5 << 20                # parts except last (reference)
_SCOPE = "https://www.googleapis.com/auth/devstorage.read_write"


class GCSError(Exception):
    def __init__(self, status: int, reason: str, message: str):
        super().__init__(f"{status} {reason}: {message}")
        self.status = status
        self.reason = reason


def _map_err(e: GCSError, bucket: str, key: str = "",
             upload_id: str = "", deleting: bool = False) -> Exception:
    """gcsToObjectError (gateway-gcs.go:131) by status/reason. GCS uses
    409 both for "bucket exists" (insert) and "bucket not empty"
    (delete) — `deleting` disambiguates like the reference's
    per-message switch."""
    if e.reason in ("required", "keyInvalid", "forbidden") or \
            e.status == 403:
        return api_errors.ObjectApiError(f"gcs denied: {e}")
    if e.status == 404 or e.reason == "notFound":
        if upload_id:
            return api_errors.InvalidUploadID(upload_id)
        if key:
            return api_errors.ObjectNotFound(bucket, key)
        return api_errors.BucketNotFound(bucket)
    if e.status == 409 or e.reason == "conflict":
        if deleting:
            return api_errors.BucketNotEmpty(bucket)
        return api_errors.BucketExists(bucket)
    if e.reason == "invalid" or e.status == 400:
        return api_errors.ObjectApiError(f"gcs invalid argument: {e}")
    return api_errors.ObjectApiError(f"gcs error: {e}")


# ---------------------------------------------------------------------------
# OAuth2: service-account JWT-bearer grant
# ---------------------------------------------------------------------------

def _b64url(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()


def sa_token_source(client_email: str, private_key_pem: bytes,
                    token_uri: str, scope: str = _SCOPE):
    """Callable -> (access_token, expires_at): signs an RS256 JWT with
    the service-account key and exchanges it at the token endpoint
    (the google-oauth flow the reference's SDK performs)."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    key = serialization.load_pem_private_key(private_key_pem,
                                             password=None)

    def fetch() -> tuple[str, float]:
        now = time.time()
        header = _b64url(json.dumps({"alg": "RS256",
                                     "typ": "JWT"}).encode())
        claims = _b64url(json.dumps({
            "iss": client_email, "scope": scope, "aud": token_uri,
            "iat": int(now), "exp": int(now) + 3600}).encode())
        signing_input = f"{header}.{claims}".encode()
        sig = key.sign(signing_input, padding.PKCS1v15(),
                       hashes.SHA256())
        assertion = f"{header}.{claims}.{_b64url(sig)}"
        body = urllib.parse.urlencode({
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": assertion}).encode()
        import http.client
        u = urllib.parse.urlsplit(token_uri)
        conn_cls = http.client.HTTPSConnection if u.scheme == "https" \
            else http.client.HTTPConnection
        conn = conn_cls(u.hostname, u.port, timeout=30)
        try:
            conn.request("POST", u.path or "/", body=body, headers={
                "Content-Type": "application/x-www-form-urlencoded"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise GCSError(resp.status, "oauth",
                               data[:200].decode("utf-8", "replace"))
            out = json.loads(data)
        finally:
            conn.close()
        return out["access_token"], now + float(
            out.get("expires_in", 3600))

    return fetch


def static_token_source(token: str):
    return lambda: (token, time.time() + 10 * 365 * 86400)


# ---------------------------------------------------------------------------
# JSON API client
# ---------------------------------------------------------------------------

class GCSJsonClient:
    """Minimal GCS JSON API client (storage/v1) over http.client."""

    def __init__(self, token_source, project: str = "",
                 host: str = "storage.googleapis.com", port: int = 443,
                 secure: bool = True):
        self.token_source = token_source
        self.project = project
        self.host, self.port, self.secure = host, port, secure
        self._token = ""
        self._token_exp = 0.0

    def _auth(self) -> str:
        if not self._token or time.time() > self._token_exp - 60:
            self._token, self._token_exp = self.token_source()
        return f"Bearer {self._token}"

    def _conn(self):
        import http.client
        cls = http.client.HTTPSConnection if self.secure else \
            http.client.HTTPConnection
        return cls(self.host, self.port, timeout=60)

    def _request(self, method: str, path: str, query: dict = None,
                 body=b"", headers: dict = None, stream: bool = False):
        qs = urllib.parse.urlencode(query or {})
        url = path + (f"?{qs}" if qs else "")
        hdrs = {"Authorization": self._auth()}
        hdrs.update(headers or {})
        if body and "Content-Length" not in hdrs:
            hdrs["Content-Length"] = str(len(body))
        conn = self._conn()
        try:
            conn.request(method, url, body=body or None, headers=hdrs)
            resp = conn.getresponse()
            if resp.status >= 300:
                raw = resp.read()
                conn.close()
                raise self._error(resp.status, raw)
            if stream:
                def gen():
                    try:
                        while True:
                            chunk = resp.read(1 << 20)
                            if not chunk:
                                return
                            yield chunk
                    finally:
                        conn.close()
                return resp, gen()
            data = resp.read()
            conn.close()
            return resp, data
        except GCSError:
            raise
        except OSError as e:
            conn.close()
            raise GCSError(0, "transport", str(e)) from e

    @staticmethod
    def _error(status: int, raw: bytes) -> GCSError:
        reason, message = "", raw[:200].decode("utf-8", "replace")
        try:
            err = json.loads(raw)["error"]
            message = err.get("message", message)
            errs = err.get("errors") or []
            if errs:
                reason = errs[0].get("reason", "")
        except (ValueError, KeyError, TypeError):
            pass
        return GCSError(status, reason, message)

    @staticmethod
    def _obj_path(bucket: str, name: str) -> str:
        return (f"/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                f"/o/{urllib.parse.quote(name, safe='')}")

    # -- buckets -----------------------------------------------------------

    def list_buckets(self) -> list[dict]:
        items, token = [], ""
        while True:
            q = {"project": self.project}
            if token:
                q["pageToken"] = token
            _, data = self._request("GET", "/storage/v1/b", q)
            out = json.loads(data)
            items += out.get("items", [])
            token = out.get("nextPageToken", "")
            if not token:
                return items

    def insert_bucket(self, bucket: str) -> None:
        self._request(
            "POST", "/storage/v1/b", {"project": self.project},
            body=json.dumps({"name": bucket}).encode(),
            headers={"Content-Type": "application/json"})

    def get_bucket(self, bucket: str) -> dict:
        _, data = self._request(
            "GET", f"/storage/v1/b/{urllib.parse.quote(bucket)}")
        return json.loads(data)

    def delete_bucket(self, bucket: str) -> None:
        self._request(
            "DELETE", f"/storage/v1/b/{urllib.parse.quote(bucket)}")

    # -- objects -----------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     delimiter: str = "", page_token: str = "",
                     max_results: int = 1000,
                     start_offset: str = "") -> dict:
        q: dict = {"maxResults": max_results}
        if prefix:
            q["prefix"] = prefix
        if delimiter:
            q["delimiter"] = delimiter
        if page_token:
            q["pageToken"] = page_token
        if start_offset:
            q["startOffset"] = start_offset
        _, data = self._request(
            "GET", f"/storage/v1/b/{urllib.parse.quote(bucket)}/o", q)
        return json.loads(data)

    def get_object_meta(self, bucket: str, name: str) -> dict:
        _, data = self._request("GET", self._obj_path(bucket, name))
        return json.loads(data)

    def download(self, bucket: str, name: str, offset: int = 0,
                 length: int = -1):
        headers = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        return self._request("GET", self._obj_path(bucket, name),
                             {"alt": "media"}, headers=headers,
                             stream=True)[1]

    def upload(self, bucket: str, name: str, data: bytes,
               content_type: str = "",
               metadata: Optional[dict] = None) -> dict:
        """uploadType=multipart: JSON metadata + media in one call."""
        meta = {"name": name}
        if metadata:
            meta["metadata"] = dict(metadata)
        if content_type:
            meta["contentType"] = content_type
        boundary = f"mt_gcs_{_uuid.uuid4().hex}"
        body = io.BytesIO()
        body.write(f"--{boundary}\r\nContent-Type: application/json; "
                   f"charset=UTF-8\r\n\r\n".encode())
        body.write(json.dumps(meta).encode())
        body.write(f"\r\n--{boundary}\r\nContent-Type: "
                   f"{content_type or 'application/octet-stream'}"
                   f"\r\n\r\n".encode())
        body.write(data)
        body.write(f"\r\n--{boundary}--\r\n".encode())
        _, out = self._request(
            "POST",
            f"/upload/storage/v1/b/{urllib.parse.quote(bucket)}/o",
            {"uploadType": "multipart"}, body=body.getvalue(),
            headers={"Content-Type":
                     f"multipart/related; boundary={boundary}"})
        return json.loads(out)

    def delete_object(self, bucket: str, name: str) -> None:
        self._request("DELETE", self._obj_path(bucket, name))

    def compose(self, bucket: str, dst: str, sources: list[str],
                content_type: str = "",
                metadata: Optional[dict] = None) -> dict:
        dest: dict = {}
        if content_type:
            dest["contentType"] = content_type
        if metadata:
            dest["metadata"] = dict(metadata)
        body = json.dumps({
            "sourceObjects": [{"name": s} for s in sources],
            "destination": dest}).encode()
        _, out = self._request(
            "POST", self._obj_path(bucket, dst) + "/compose",
            body=body, headers={"Content-Type": "application/json"})
        return json.loads(out)

    def patch_metadata(self, bucket: str, name: str,
                       metadata: dict) -> dict:
        _, out = self._request(
            "PATCH", self._obj_path(bucket, name),
            body=json.dumps({"metadata": metadata}).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(out)


# ---------------------------------------------------------------------------
# ObjectLayer over the JSON API
# ---------------------------------------------------------------------------

def _rfc3339_ts(s: str) -> float:
    import datetime as _dt
    try:
        return _dt.datetime.fromisoformat(
            s.replace("Z", "+00:00")).timestamp()
    except (TypeError, ValueError):
        return 0.0


def _to_info(bucket: str, item: dict) -> ObjectInfo:
    md5_b64 = item.get("md5Hash", "")
    if md5_b64:
        etag = base64.b64decode(md5_b64).hex()
    else:                                # composite objects have no md5
        etag = item.get("etag", "").strip('"')
    user = {f"x-amz-meta-{k}": v
            for k, v in (item.get("metadata") or {}).items()}
    return ObjectInfo(
        bucket=bucket, name=item.get("name", ""),
        size=int(item.get("size", 0)), etag=etag,
        mod_time=_rfc3339_ts(item.get("updated",
                                      item.get("timeCreated", ""))),
        content_type=item.get("contentType", ""), user_defined=user)


def _mpu_meta_name(uid: str) -> str:
    return f"{_MPU_PATH}/{uid}/{_MPU_META}"


def _mpu_part_name(uid: str, part_number: int, etag: str) -> str:
    return f"{_MPU_PATH}/{uid}/{part_number:05d}.{etag}"


def _mpu_compose_name(uid: str, n: int) -> str:
    return f"{GCS_SYS_TMP}tmp/{uid}/composed-object-{n:05d}"


class GCSJsonGatewayObjects:
    """ObjectLayer over the GCS JSON API (the reference's gateway)."""

    supports_sse_multipart = False

    def __init__(self, client: GCSJsonClient):
        self.c = client

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        try:
            self.c.insert_bucket(bucket)
        except GCSError as e:
            raise _map_err(e, bucket) from None

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self.c.get_bucket(bucket)
            return True
        except GCSError as e:
            # only "it is not there" reads as False — an auth failure
            # or outage must not look like a missing bucket (callers
            # auto-create on 404)
            if e.status == 404 or e.reason == "notFound":
                return False
            raise _map_err(e, bucket) from None

    def get_bucket_info(self, bucket: str) -> VolInfo:
        try:
            b = self.c.get_bucket(bucket)
        except GCSError as e:
            raise _map_err(e, bucket) from None
        return VolInfo(bucket, _rfc3339_ts(b.get("timeCreated", "")))

    def list_buckets(self) -> list[VolInfo]:
        try:
            return [VolInfo(b["name"],
                            _rfc3339_ts(b.get("timeCreated", "")))
                    for b in self.c.list_buckets()]
        except GCSError as e:
            raise _map_err(e, "") from None

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        try:
            self.c.delete_bucket(bucket)
        except GCSError as e:
            raise _map_err(e, bucket, deleting=True) from None

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        opts = opts or PutOptions()
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.verify()
            reader.close()
        ct = ""
        meta = {}
        for k, v in opts.metadata.items():
            lk = k.lower()
            if lk == "content-type":
                ct = v
            elif lk.startswith("x-amz-meta-"):
                meta[lk[len("x-amz-meta-"):]] = v
        try:
            item = self.c.upload(bucket, key, body, ct, meta)
        except GCSError as e:
            raise _map_err(e, bucket, key) from None
        return _to_info(bucket, item)

    def get_object_info(self, bucket: str, key: str,
                        opts: Optional[GetOptions] = None
                        ) -> ObjectInfo:
        try:
            return _to_info(bucket, self.c.get_object_meta(bucket,
                                                           key))
        except GCSError as e:
            raise _map_err(e, bucket, key) from None

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info = self.get_object_info(bucket, key, opts)
        if length < 0:
            length = info.size - offset
        try:
            if info.size == 0 or length <= 0:
                return info, iter(())
            return info, self.c.download(bucket, key, offset, length)
        except GCSError as e:
            raise _map_err(e, bucket, key) from None

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        try:
            self.c.delete_object(bucket, key)
        except GCSError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key)

    def delete_objects(self, bucket: str, objects: list[str]):
        out = []
        for o in objects:
            try:
                self.delete_object(bucket, o)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-key result
                out.append(e)
        return out

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        meta = {k[len("x-amz-meta-"):] if
                k.lower().startswith("x-amz-meta-") else k: v
                for k, v in metadata.items()
                if k.lower() != "content-type"}
        try:
            self.c.patch_metadata(bucket, key, meta)
        except GCSError as e:
            raise _map_err(e, bucket, key) from None

    def has_object_versions(self, bucket: str, key: str) -> bool:
        try:
            self.get_object_info(bucket, key)
            return True
        except api_errors.ObjectApiError:
            return False

    def heal_object(self, bucket: str, key: str, version_id: str = "",
                    deep_scan: bool = False, dry_run: bool = False):
        from ..object.healing import HealResultItem
        return HealResultItem(bucket=bucket, object=key, disks_total=0)

    # -- listing -----------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", delimiter: str = "",
                     max_keys: int = 1000):
        objs: list[ObjectInfo] = []
        prefixes: list[str] = []
        token = ""
        try:
            while True:
                out = self.c.list_objects(
                    bucket, prefix, delimiter, token,
                    max_keys + 1, start_offset=marker)
                for item in out.get("items", []):
                    name = item.get("name", "")
                    # the reference hides its own multipart staging
                    # area from listings (gateway-gcs.go ListObjects)
                    if name.startswith(GCS_SYS_TMP) and \
                            not prefix.startswith(GCS_SYS_TMP):
                        continue
                    if marker and name <= marker:
                        continue
                    objs.append(_to_info(bucket, item))
                for p in out.get("prefixes", []):
                    if p.startswith(GCS_SYS_TMP) and \
                            not prefix.startswith(GCS_SYS_TMP):
                        continue
                    if p not in prefixes:
                        prefixes.append(p)
                token = out.get("nextPageToken", "")
                if not token or len(objs) + len(prefixes) > max_keys:
                    break
        except GCSError as e:
            raise _map_err(e, bucket) from None
        truncated = bool(token) or len(objs) + len(prefixes) > max_keys
        combined = sorted(objs, key=lambda o: o.name)[:max_keys]
        return combined, sorted(prefixes), truncated

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000,
                             version_marker: str = "",
                             delimiter: str = ""):
        objs, pfx, trunc = self.list_objects(bucket, prefix, marker,
                                             delimiter, max_keys)
        return single_version_page(objs, trunc, pfx)

    # -- multipart: compose-based (gateway-gcs.go:988-1380) ----------------

    def new_multipart_upload(self, bucket, key, opts=None) -> str:
        uid = _uuid.uuid4().hex
        meta = dict((opts or PutOptions()).metadata)
        session = {"version": _MPU_META_VERSION, "bucket": bucket,
                   "object": key, "metadata": meta}
        try:
            self.c.upload(bucket, _mpu_meta_name(uid),
                          json.dumps(session).encode(),
                          "application/json")
        except GCSError as e:
            raise _map_err(e, bucket, key) from None
        return uid

    def _session(self, bucket, key, uid) -> dict:
        try:
            stream = self.c.download(bucket, _mpu_meta_name(uid))
            session = json.loads(b"".join(stream))
        except (GCSError, ValueError):
            raise api_errors.InvalidUploadID(uid) from None
        if session.get("version") != _MPU_META_VERSION or \
                session.get("bucket") != bucket or \
                session.get("object") != key:
            raise api_errors.InvalidUploadID(uid)
        return session

    def get_multipart_info(self, bucket, key, uid) -> dict:
        return dict(self._session(bucket, key, uid).get("metadata",
                                                        {}))

    def put_object_part(self, bucket, key, uid, part_number, reader,
                        size=-1):
        self._session(bucket, key, uid)
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.close()
        etag = hashlib.md5(body).hexdigest()
        try:
            self.c.upload(bucket, _mpu_part_name(uid, part_number,
                                                 etag), body)
        except GCSError as e:
            raise _map_err(e, bucket, key, uid) from None
        return ObjectPartInfo(number=part_number, etag=etag,
                              size=len(body), actual_size=len(body))

    def _list_all(self, bucket: str, prefix: str) -> list[dict]:
        """Every item under a prefix, following page tokens (staging
        areas can exceed one page)."""
        items: list[dict] = []
        token = ""
        while True:
            out = self.c.list_objects(bucket, prefix=prefix,
                                      page_token=token,
                                      max_results=1000)
            items += out.get("items", [])
            token = out.get("nextPageToken", "")
            if not token:
                return items

    def list_object_parts(self, bucket, key, uid, part_marker=0,
                          max_parts=1000):
        self._session(bucket, key, uid)
        out = []
        try:
            items = self._list_all(bucket, f"{_MPU_PATH}/{uid}/")
        except GCSError as e:
            raise _map_err(e, bucket, key, uid) from None
        for item in items:
            base = item["name"].rsplit("/", 1)[-1]
            if base == _MPU_META or "." not in base:
                continue
            num_s, etag = base.split(".", 1)
            out.append(ObjectPartInfo(
                number=int(num_s), etag=etag,
                size=int(item.get("size", 0)),
                actual_size=int(item.get("size", 0))))
        out.sort(key=lambda p: p.number)
        return [p for p in out if p.number > part_marker][:max_parts]

    def list_multipart_uploads(self, bucket, key=""):
        try:
            items = self._list_all(bucket, f"{_MPU_PATH}/")
        except GCSError as e:
            raise _map_err(e, bucket) from None
        ups = []
        for item in items:
            name = item["name"]
            if not name.endswith("/" + _MPU_META):
                continue
            uid = name.split("/")[-2]
            try:
                session = json.loads(b"".join(
                    self.c.download(bucket, name)))
            except (GCSError, ValueError):
                continue
            if key and session.get("object") != key:
                continue
            ups.append({"object": session.get("object", ""),
                        "upload_id": uid,
                        "initiated": _rfc3339_ts(
                            item.get("timeCreated", ""))})
        return ups

    def _cleanup_mpu(self, bucket: str, uid: str) -> None:
        for prefix in (f"{_MPU_PATH}/{uid}/",
                       f"{GCS_SYS_TMP}tmp/{uid}/"):
            # re-list until empty: deletes invalidate page tokens, and
            # a staging area can exceed one page
            for _round in range(64):
                try:
                    items = self.c.list_objects(
                        bucket, prefix=prefix,
                        max_results=1000).get("items", [])
                except GCSError:
                    break
                if not items:
                    break
                for item in items:
                    try:
                        self.c.delete_object(bucket, item["name"])
                    except GCSError:
                        pass

    def abort_multipart_upload(self, bucket, key, uid) -> None:
        self._session(bucket, key, uid)
        self._cleanup_mpu(bucket, uid)

    def complete_multipart_upload(self, bucket, key, uid, parts):
        session = self._session(bucket, key, uid)
        meta = session.get("metadata", {})
        ct = ""
        user_meta = {}
        for k, v in meta.items():
            lk = k.lower()
            if lk == "content-type":
                ct = v
            elif lk.startswith("x-amz-meta-"):
                user_meta[lk[len("x-amz-meta-"):]] = v

        names = []
        sizes = []
        for cp in parts:
            name = _mpu_part_name(uid, cp.part_number,
                                  cp.etag.strip('"'))
            try:
                item = self.c.get_object_meta(bucket, name)
            except GCSError:
                raise api_errors.InvalidPart(cp.part_number) from None
            names.append(name)
            sizes.append(int(item.get("size", 0)))
        # parts except the last must be >= 5 MiB (gateway-gcs.go:1317)
        for i, size in enumerate(sizes[:-1]):
            if size < MIN_PART_SIZE:
                raise api_errors.PartTooSmall(
                    f"part {parts[i].part_number}: {size} bytes "
                    f"(parts except the last need "
                    f">= {MIN_PART_SIZE})")

        try:
            # compose in groups of <= 32, then compose the composes
            if len(names) > MAX_COMPONENTS:
                groups = []
                for i in range(0, len(names), MAX_COMPONENTS):
                    cname = _mpu_compose_name(uid, i // MAX_COMPONENTS)
                    self.c.compose(bucket, cname,
                                   names[i:i + MAX_COMPONENTS], ct,
                                   user_meta)
                    groups.append(cname)
                names = groups
            item = self.c.compose(bucket, key, names, ct, user_meta)
        except GCSError as e:
            raise _map_err(e, bucket, key, uid) from None
        self._cleanup_mpu(bucket, uid)
        info = _to_info(bucket, item)
        # S3 multipart ETags are <md5-of-md5s>-<n>; GCS composites
        # carry crc32c only, so synthesize the S3 shape like the
        # reference's minio.ComputeCompleteMultipartMD5
        md5s = b"".join(bytes.fromhex(cp.etag.strip('"'))
                        for cp in parts)
        info.etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        return info

    def storage_info(self) -> dict:
        return {"total": 0, "free": 0, "used": 0, "online_disks": 1,
                "offline_disks": 0, "sets": 0, "drives_per_set": 0,
                "backend": "gateway-gcs"}

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# XML-interop fallback (the r4 dialect preset, kept behind hmac creds)
# ---------------------------------------------------------------------------

class GCSXmlGatewayObjects(S3GatewayObjects):
    """ObjectLayer over GCS's XML interoperability API (HMAC keys)."""

    def storage_info(self) -> dict:
        out = super().storage_info()
        out["backend"] = "gateway-gcs-xml"
        return out


class GCSGateway:
    """`minio gateway gcs` factory.

    JSON API mode (the reference's): pass `credentials_json` (a
    service-account key file's contents or path) or a pre-fetched
    `token`, plus `project`. XML interop mode: pass `hmac_key` +
    `hmac_secret` from the GCS interoperability settings (the r4
    `access_key`/`secret_key` names still work).
    """

    def __init__(self, project: str = "",
                 credentials_json: str = "", token: str = "",
                 hmac_key: str = "", hmac_secret: str = "",
                 host: str = "storage.googleapis.com", port: int = 443,
                 secure: bool = True, token_uri: str = "",
                 access_key: str = "", secret_key: str = "",
                 region: str = "auto"):
        hmac_key = hmac_key or access_key
        hmac_secret = hmac_secret or secret_key
        if credentials_json or token:
            if token:
                source = static_token_source(token)
            else:
                import os
                if os.path.exists(credentials_json):
                    with open(credentials_json) as f:
                        credentials_json = f.read()
                sa = json.loads(credentials_json)
                source = sa_token_source(
                    sa["client_email"],
                    sa["private_key"].encode(),
                    token_uri or sa.get(
                        "token_uri",
                        "https://oauth2.googleapis.com/token"))
                project = project or sa.get("project_id", "")
            self._client = GCSJsonClient(source, project, host, port,
                                         secure)
            self._mode = "json"
        elif hmac_key:
            self._client = S3Client(host, port,
                                    Credentials(hmac_key, hmac_secret),
                                    region, secure=secure)
            self._mode = "xml"
        else:
            raise ValueError(
                "gateway gcs needs credentials_json/token (JSON API) "
                "or hmac_key/hmac_secret (XML interop)")

    def object_layer(self):
        if self._mode == "json":
            return GCSJsonGatewayObjects(self._client)
        return GCSXmlGatewayObjects(self._client)
