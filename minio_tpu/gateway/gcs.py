"""GCS gateway: ObjectLayer over Google Cloud Storage's XML API
(reference cmd/gateway/gcs/gateway-gcs.go drives the JSON API with
OAuth; GCS's documented XML interoperability surface speaks the S3
dialect with HMAC service-account keys — which this build already
implements natively, so the gateway rides the existing S3 client
pointed at storage.googleapis.com with path-style addressing).

This is the pragmatic tpu-build mapping: one authenticated transport
(SigV4/HMAC) covers both AWS-compatible and GCS backends; the
JSON-API-only features (customer metadata via PATCH, compose) fall
back to the S3-dialect equivalents GCS exposes.
"""

from __future__ import annotations

from ..s3.credentials import Credentials
from ..utils.s3client import S3Client
from .s3 import S3GatewayObjects


class GCSGatewayObjects(S3GatewayObjects):
    """ObjectLayer over GCS (XML interoperability API)."""

    def storage_info(self) -> dict:
        out = super().storage_info()
        out["backend"] = "gateway-gcs"
        return out


class GCSGateway:
    """`minio gateway gcs` factory: HMAC key + secret from the GCS
    interoperability settings; host override for testing/private
    endpoints."""

    def __init__(self, access_key: str, secret_key: str,
                 host: str = "storage.googleapis.com",
                 port: int = 443, secure: bool = True,
                 region: str = "auto"):
        self.client = S3Client(host, port,
                               Credentials(access_key, secret_key),
                               region, secure=secure)

    def object_layer(self) -> GCSGatewayObjects:
        return GCSGatewayObjects(self.client)
