"""Gateway backends: alternate ObjectLayers over foreign storage
(reference cmd/gateway-interface.go + cmd/gateway/{nas,s3,...}).

A gateway returns an ObjectLayer; the whole S3/IAM/admin stack mounts on
top unchanged. `new_gateway(kind, ...)` is the registry
(cmd/gateway-main.go)."""

from __future__ import annotations


def new_gateway(kind: str, **kw):
    if kind == "nas":
        from .nas import NASGateway
        return NASGateway(**kw).object_layer()
    if kind == "s3":
        from .s3 import S3Gateway
        return S3Gateway(**kw).object_layer()
    if kind == "azure":
        from .azure import AzureGateway
        return AzureGateway(**kw).object_layer()
    if kind == "gcs":
        from .gcs import GCSGateway
        return GCSGateway(**kw).object_layer()
    if kind == "hdfs":
        from .hdfs import HDFSGateway
        return HDFSGateway(**kw).object_layer()
    raise ValueError(f"unknown gateway kind {kind!r} "
                     "(supported: nas, s3, azure, gcs, hdfs)")
