"""Azure Blob gateway: ObjectLayer over an Azure storage account
(reference cmd/gateway/azure/gateway-azure.go:1-1752): buckets map to
containers, objects to block blobs, multipart parts to staged
uncommitted blocks committed by Put Block List — the azure-native
multipart the reference uses, so an 8 GiB upload never buffers
server-side.

The REST transport (utils/azureclient.py) signs with SharedKey and has
an injectable connection factory; tests run the whole gateway against
an in-process blob server.
"""

from __future__ import annotations

import base64
import hashlib
import uuid as _uuid
from email.utils import parsedate_to_datetime
from typing import Iterator, Optional

from ..object import api_errors
from ..object.engine import GetOptions, PutOptions
from ..object.hash_reader import HashReader
from ..storage.datatypes import ObjectInfo, ObjectPartInfo, VolInfo, single_version_page
from ..utils.azureclient import AzureBlobClient, AzureClientError


def _map_err(e: AzureClientError, bucket: str, key: str = "") -> Exception:
    if e.code == "ContainerNotFound" or (e.status == 404 and not key):
        return api_errors.BucketNotFound(bucket)
    if e.code == "BlobNotFound" or e.status == 404:
        return api_errors.ObjectNotFound(bucket, key)
    if e.code == "ContainerAlreadyExists":
        return api_errors.BucketExists(bucket)
    if e.status == 403:
        return api_errors.ObjectApiError(f"azure denied: {e.code}")
    return api_errors.ObjectApiError(f"azure error: {e}")


def _block_id(upload_id: str, part_number: int, sub: int) -> str:
    """Deterministic sortable block id (the reference encodes part +
    sub-part into fixed-width base64 ids so Put Block List commits in
    part order)."""
    raw = f"{upload_id[:8]}-{part_number:05d}-{sub:05d}"
    return base64.b64encode(raw.encode()).decode()


def _http_date_ts(value: str) -> float:
    try:
        return parsedate_to_datetime(value).timestamp()
    except (TypeError, ValueError):
        return 0.0


class AzureGatewayObjects:
    """ObjectLayer over Azure Blob Storage."""

    supports_sse_multipart = False
    MAX_BLOCK = 100 << 20          # service max block size

    def __init__(self, client: AzureBlobClient):
        self.c = client
        # upload-id -> {bucket, key, metadata, parts: {n: (etag, [ids], size)}}
        self._mpu: dict[str, dict] = {}

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        try:
            self.c.create_container(bucket)
        except AzureClientError as e:
            raise _map_err(e, bucket) from None

    def bucket_exists(self, bucket: str) -> bool:
        return self.c.container_exists(bucket)

    def get_bucket_info(self, bucket: str) -> VolInfo:
        if not self.c.container_exists(bucket):
            raise api_errors.BucketNotFound(bucket)
        return VolInfo(bucket, 0.0)

    def list_buckets(self) -> list[VolInfo]:
        return [VolInfo(n, 0.0) for n in self.c.list_containers()]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        try:
            self.c.delete_container(bucket)
        except AzureClientError as e:
            raise _map_err(e, bucket) from None

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # -- objects -----------------------------------------------------------

    # bodies above this stage as blocks instead of one in-memory PUT
    STREAM_THRESHOLD = 16 << 20
    STAGE_CHUNK = 8 << 20

    @staticmethod
    def _encode_meta_key(key: str) -> str:
        """S3 metadata/control keys (x-amz-meta-*, X-Amz-Tagging,
        object-lock headers, etag) are not valid Azure metadata
        identifiers; base32 keeps them reversible without loss (the
        reference's s3MetaToAzureProperties does a lossier mangle)."""
        enc = base64.b32encode(key.lower().encode()).decode()
        return "k" + enc.rstrip("=").lower()

    @staticmethod
    def _decode_meta_key(name: str) -> Optional[str]:
        if not name.startswith("k"):
            return None
        enc = name[1:].upper()
        enc += "=" * (-len(enc) % 8)
        try:
            return base64.b32decode(enc).decode()
        except Exception:  # noqa: BLE001 — foreign metadata
            return None

    @classmethod
    def _meta_split(cls, metadata: dict) -> tuple[dict, str]:
        """user metadata -> (azure metadata dict, content type). EVERY
        key except content-type round-trips (tagging, object-lock,
        legal-hold and custom metadata must survive the gateway)."""
        meta, ctype = {}, ""
        for k, v in (metadata or {}).items():
            lk = k.lower()
            if lk == "content-type":
                ctype = v
            else:
                meta[cls._encode_meta_key(lk)] = str(v)
        return meta, ctype

    @classmethod
    def _meta_join(cls, headers: dict) -> dict:
        user = {}
        for k, v in headers.items():
            if not k.startswith("x-ms-meta-"):
                continue
            name = k[len("x-ms-meta-"):]
            decoded = cls._decode_meta_key(name)
            user[decoded if decoded is not None
                 else f"x-amz-meta-{name}"] = v
        return user

    def _read_all(self, reader, size: int) -> bytes:
        if isinstance(reader, (bytes, bytearray)):
            return bytes(reader)
        if not isinstance(reader, HashReader):
            reader = HashReader(reader, size)
        body = reader.read() if size < 0 else reader.read(size)
        reader.verify()
        reader.close()
        return body

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        opts = opts or PutOptions()
        if not isinstance(reader, (bytes, bytearray)) and \
                (size < 0 or size > self.STREAM_THRESHOLD):
            return self._put_object_streamed(bucket, key, reader, size,
                                             opts)
        body = self._read_all(reader, size)
        etag = hashlib.md5(body).hexdigest()
        md = dict(opts.metadata)
        md["etag"] = etag            # service ETags are not md5: pin it
        meta, ctype = self._meta_split(md)
        try:
            self.c.put_blob(bucket, key, body, meta, ctype)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key, size=len(body),
                          etag=etag)

    def _put_object_streamed(self, bucket: str, key: str, reader,
                             size: int, opts: PutOptions) -> ObjectInfo:
        """Large/unknown-size PUT: stage STAGE_CHUNK blocks, commit via
        Put Block List — constant memory, like the multipart path."""
        if not isinstance(reader, HashReader):
            reader = HashReader(reader, size)
        uid = _uuid.uuid4().hex
        ids: list[str] = []
        md5 = hashlib.md5()
        total = 0
        try:
            while True:
                chunk = reader.read(self.STAGE_CHUNK)
                if not chunk:
                    break
                md5.update(chunk)
                total += len(chunk)
                bid = _block_id(uid, 0, len(ids))
                self.c.put_block(bucket, key, bid, chunk)
                ids.append(bid)
            reader.verify()
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        finally:
            reader.close()
        etag = md5.hexdigest()
        md = dict(opts.metadata)
        md["etag"] = etag
        meta, ctype = self._meta_split(md)
        try:
            if not ids:              # empty object
                self.c.put_blob(bucket, key, b"", meta, ctype)
            else:
                self.c.put_block_list(bucket, key, ids, meta, ctype)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key, size=total,
                          etag=etag)

    def get_object_info(self, bucket: str, key: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        try:
            h = self.c.get_blob_props(bucket, key)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        user = self._meta_join(h)
        etag = user.pop("etag", "") or h.get("etag", "").strip('"')
        return ObjectInfo(
            bucket=bucket, name=key,
            size=int(h.get("content-length", 0) or 0),
            etag=etag,
            mod_time=_http_date_ts(h.get("last-modified", "")),
            content_type=h.get("content-type", ""),
            user_defined=user)

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info = self.get_object_info(bucket, key, opts)
        if length < 0:
            length = info.size - offset
        if length <= 0:
            return info, iter(())
        try:
            # full-object reads go without a Range header (a range of
            # "bytes=0--1" on a zero-byte blob is a 416 on real Azure)
            if offset == 0 and length >= info.size:
                _h, stream = self.c.get_blob(bucket, key)
            else:
                _h, stream = self.c.get_blob(bucket, key, offset,
                                             length)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return info, stream

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        try:
            self.c.delete_blob(bucket, key)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key)

    def delete_objects(self, bucket: str, objects: list[str]):
        out = []
        for key in objects:
            try:
                self.delete_object(bucket, key)
                out.append(None)
            except api_errors.ObjectApiError as e:
                out.append(e)
        return out

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        info, stream = self.get_object(bucket, key)
        body = b"".join(stream)
        return self.put_object(bucket, key, body,
                               opts=PutOptions(metadata=metadata))

    def has_object_versions(self, bucket: str, key: str) -> bool:
        try:
            self.get_object_info(bucket, key)
            return True
        except api_errors.ObjectApiError:
            return False

    def heal_object(self, bucket: str, key: str, version_id: str = "",
                    deep_scan: bool = False, dry_run: bool = False):
        from ..object.healing import HealResultItem
        self.get_object_info(bucket, key)
        return HealResultItem(bucket=bucket, object=key)

    # -- listing -----------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", delimiter: str = "",
                     max_keys: int = 1000):
        """S3 markers are key names; Azure markers are opaque
        continuation tokens. A token cache maps the last key of each
        served page to Azure's token; on a cache miss (server restart,
        foreign marker) the gateway pages from the start and skips up
        to the marker — slower but correct against real Azure (feeding
        a key name into Azure's marker parameter is a 400)."""
        self.get_bucket_info(bucket)
        cache = getattr(self, "_list_tokens", None)
        if cache is None:
            cache = self._list_tokens = {}
        # start from the cached page token for this marker (may be ""
        # on a miss => page from the start); ALWAYS filter keys <=
        # marker, so a mid-page cut resumes correctly either way
        token = cache.get((bucket, prefix, delimiter, marker), "") \
            if marker else ""

        objs: list[ObjectInfo] = []
        prefixes: list[str] = []
        truncated = False
        while True:
            page_token = token
            try:
                blobs, pfx, next_tok = self.c.list_blobs(
                    bucket, prefix, delimiter, page_token,
                    max_results=max(max_keys, 1000))
            except AzureClientError as e:
                raise _map_err(e, bucket) from None
            for p in pfx:
                if marker and p <= marker:
                    continue
                if p not in prefixes:
                    prefixes.append(p)
            kept = 0
            for b in blobs:
                if marker and b["name"] <= marker:
                    continue
                kept += 1
                meta_etag = self._decode_etag_meta(b.get("metadata"))
                objs.append(ObjectInfo(
                    bucket=bucket, name=b["name"], size=b["size"],
                    etag=meta_etag or b["etag"],
                    mod_time=_http_date_ts(b["last_modified"])))
            if len(objs) + len(prefixes) >= max_keys:
                cut = max_keys - len(prefixes)
                dropped = len(objs) - cut
                objs = objs[:cut]
                truncated = bool(next_tok) or dropped > 0
                if objs and truncated:
                    # the next page re-fetches from THIS page's token
                    # and skips past the last served key
                    cache[(bucket, prefix, delimiter,
                           objs[-1].name)] = page_token
                if len(cache) > 4096:
                    cache.clear()      # bounded; misses just rescan
                break
            if not next_tok:
                break
            token = next_tok
        return objs, prefixes, truncated

    @classmethod
    def _decode_etag_meta(cls, meta: Optional[dict]) -> str:
        """Pinned md5 ETag out of a listing's blob metadata."""
        for name, v in (meta or {}).items():
            if cls._decode_meta_key(name) == "etag":
                return v
        return ""

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000,
                             version_marker: str = "",
                             delimiter: str = ""):
        objs, pfx, trunc = self.list_objects(bucket, prefix, marker,
                                             delimiter,
                                             max_keys=max_keys)
        return single_version_page(objs, trunc, pfx)

    # -- multipart: azure-native staged blocks -----------------------------

    def new_multipart_upload(self, bucket, key, opts=None) -> str:
        self.get_bucket_info(bucket)
        uid = str(_uuid.uuid4())
        self._mpu[uid] = {"bucket": bucket, "key": key, "parts": {},
                          "metadata": dict(
                              (opts or PutOptions()).metadata)}
        return uid

    def get_multipart_info(self, bucket, key, uid) -> dict:
        return dict(self._up(bucket, key, uid).get("metadata", {}))

    def _up(self, bucket, key, uid):
        mpu = self._mpu.get(uid)
        if mpu is None or mpu["bucket"] != bucket or mpu["key"] != key:
            raise api_errors.InvalidUploadID(uid)
        return mpu

    def put_object_part(self, bucket, key, uid, part_number, reader,
                        size=-1):
        mpu = self._up(bucket, key, uid)
        body = self._read_all(reader, size)   # verify()s declared size
        etag = hashlib.md5(body).hexdigest()
        ids = []
        try:
            for sub in range(0, max(len(body), 1), self.MAX_BLOCK):
                bid = _block_id(uid, part_number, sub // self.MAX_BLOCK)
                self.c.put_block(bucket, key, bid,
                                 body[sub:sub + self.MAX_BLOCK])
                ids.append(bid)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        mpu["parts"][part_number] = (etag, ids, len(body))
        return ObjectPartInfo(number=part_number, etag=etag,
                              size=len(body), actual_size=len(body))

    def list_object_parts(self, bucket, key, uid, part_marker=0,
                          max_parts=1000):
        mpu = self._up(bucket, key, uid)
        return [ObjectPartInfo(number=n, etag=e, size=sz,
                               actual_size=sz)
                for n, (e, _ids, sz) in sorted(mpu["parts"].items())
                if n > part_marker][:max_parts]

    def list_multipart_uploads(self, bucket, key=""):
        return [{"object": m["key"], "upload_id": uid, "initiated": 0.0}
                for uid, m in self._mpu.items()
                if m["bucket"] == bucket and (not key or m["key"] == key)]

    def abort_multipart_upload(self, bucket, key, uid) -> None:
        self._up(bucket, key, uid)
        self._mpu.pop(uid, None)

    def complete_multipart_upload(self, bucket, key, uid, parts):
        mpu = self._up(bucket, key, uid)
        block_ids: list[str] = []
        total = 0
        for cp in parts:
            stored = mpu["parts"].get(cp.part_number)
            if stored is None or stored[0] != cp.etag.strip('"'):
                raise api_errors.InvalidPart(cp.part_number)
            block_ids.extend(stored[1])
            total += stored[2]
        part_etags = "".join(mpu["parts"][cp.part_number][0]
                             for cp in parts)
        etag = hashlib.md5(bytes.fromhex(part_etags)).hexdigest() \
            + f"-{len(parts)}"
        md = dict(mpu["metadata"])
        md["etag"] = etag
        meta, ctype = self._meta_split(md)
        try:
            self.c.put_block_list(bucket, key, block_ids, meta, ctype)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        self._mpu.pop(uid, None)
        return ObjectInfo(bucket=bucket, name=key, size=total, etag=etag)

    # -- misc --------------------------------------------------------------

    def storage_info(self) -> dict:
        return {"total": 0, "free": 0, "used": 0, "online_disks": 1,
                "offline_disks": 0, "sets": 0, "drives_per_set": 0,
                "backend": "gateway-azure"}

    def close(self) -> None:
        pass


class AzureGateway:
    """Gateway factory (reference cmd/gateway-main.go `minio gateway
    azure` registration shape)."""

    def __init__(self, account: str, key_b64: str, host: str,
                 port: int = 10000, secure: bool = False):
        self.client = AzureBlobClient(account, key_b64, host, port,
                                      secure)

    def object_layer(self) -> AzureGatewayObjects:
        return AzureGatewayObjects(self.client)
