"""Azure Blob gateway: ObjectLayer over an Azure storage account
(reference cmd/gateway/azure/gateway-azure.go:1-1752): buckets map to
containers, objects to block blobs, multipart parts to staged
uncommitted blocks committed by Put Block List — the azure-native
multipart the reference uses, so an 8 GiB upload never buffers
server-side.

The REST transport (utils/azureclient.py) signs with SharedKey and has
an injectable connection factory; tests run the whole gateway against
an in-process blob server.
"""

from __future__ import annotations

import base64
import hashlib
import uuid as _uuid
from email.utils import parsedate_to_datetime
from typing import Iterator, Optional

from ..object import api_errors
from ..object.engine import GetOptions, PutOptions
from ..object.hash_reader import HashReader
from ..storage.datatypes import ObjectInfo, ObjectPartInfo, VolInfo
from ..utils.azureclient import AzureBlobClient, AzureClientError


def _map_err(e: AzureClientError, bucket: str, key: str = "") -> Exception:
    if e.code == "ContainerNotFound" or (e.status == 404 and not key):
        return api_errors.BucketNotFound(bucket)
    if e.code == "BlobNotFound" or e.status == 404:
        return api_errors.ObjectNotFound(bucket, key)
    if e.code == "ContainerAlreadyExists":
        return api_errors.BucketExists(bucket)
    if e.status == 403:
        return api_errors.ObjectApiError(f"azure denied: {e.code}")
    return api_errors.ObjectApiError(f"azure error: {e}")


def _block_id(upload_id: str, part_number: int, sub: int) -> str:
    """Deterministic sortable block id (the reference encodes part +
    sub-part into fixed-width base64 ids so Put Block List commits in
    part order)."""
    raw = f"{upload_id[:8]}-{part_number:05d}-{sub:05d}"
    return base64.b64encode(raw.encode()).decode()


def _http_date_ts(value: str) -> float:
    try:
        return parsedate_to_datetime(value).timestamp()
    except (TypeError, ValueError):
        return 0.0


class AzureGatewayObjects:
    """ObjectLayer over Azure Blob Storage."""

    supports_sse_multipart = False
    MAX_BLOCK = 100 << 20          # service max block size

    def __init__(self, client: AzureBlobClient):
        self.c = client
        # upload-id -> {bucket, key, metadata, parts: {n: (etag, [ids], size)}}
        self._mpu: dict[str, dict] = {}

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        try:
            self.c.create_container(bucket)
        except AzureClientError as e:
            raise _map_err(e, bucket) from None

    def bucket_exists(self, bucket: str) -> bool:
        return self.c.container_exists(bucket)

    def get_bucket_info(self, bucket: str) -> VolInfo:
        if not self.c.container_exists(bucket):
            raise api_errors.BucketNotFound(bucket)
        return VolInfo(bucket, 0.0)

    def list_buckets(self) -> list[VolInfo]:
        return [VolInfo(n, 0.0) for n in self.c.list_containers()]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        try:
            self.c.delete_container(bucket)
        except AzureClientError as e:
            raise _map_err(e, bucket) from None

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # -- objects -----------------------------------------------------------

    @staticmethod
    def _meta_split(metadata: dict) -> tuple[dict, str]:
        """user metadata -> (x-ms-meta dict, content type); S3 metadata
        keys are not valid C# identifiers, so prefix-strip like the
        reference's s3MetaToAzureProperties."""
        meta, ctype = {}, ""
        for k, v in (metadata or {}).items():
            lk = k.lower()
            if lk == "content-type":
                ctype = v
            elif lk.startswith("x-amz-meta-"):
                meta[lk[len("x-amz-meta-"):].replace("-", "_")] = v
        return meta, ctype

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        opts = opts or PutOptions()
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.verify()
            reader.close()
        meta, ctype = self._meta_split(opts.metadata)
        try:
            self.c.put_blob(bucket, key, body, meta, ctype)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key, size=len(body),
                          etag=hashlib.md5(body).hexdigest())

    def get_object_info(self, bucket: str, key: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        try:
            h = self.c.get_blob_props(bucket, key)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        user = {f"x-amz-meta-{k[len('x-ms-meta-'):]}": v
                for k, v in h.items() if k.startswith("x-ms-meta-")}
        return ObjectInfo(
            bucket=bucket, name=key,
            size=int(h.get("content-length", 0) or 0),
            etag=h.get("etag", "").strip('"'),
            mod_time=_http_date_ts(h.get("last-modified", "")),
            content_type=h.get("content-type", ""),
            user_defined=user)

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info = self.get_object_info(bucket, key, opts)
        if length < 0:
            length = info.size - offset
        try:
            _h, stream = self.c.get_blob(bucket, key, offset, length)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return info, stream

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        try:
            self.c.delete_blob(bucket, key)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key)

    def delete_objects(self, bucket: str, objects: list[str]):
        out = []
        for key in objects:
            try:
                self.delete_object(bucket, key)
                out.append(None)
            except api_errors.ObjectApiError as e:
                out.append(e)
        return out

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        info, stream = self.get_object(bucket, key)
        body = b"".join(stream)
        return self.put_object(bucket, key, body,
                               opts=PutOptions(metadata=metadata))

    def has_object_versions(self, bucket: str, key: str) -> bool:
        try:
            self.get_object_info(bucket, key)
            return True
        except api_errors.ObjectApiError:
            return False

    def heal_object(self, bucket: str, key: str, version_id: str = "",
                    deep_scan: bool = False, dry_run: bool = False):
        from ..object.healing import HealResultItem
        self.get_object_info(bucket, key)
        return HealResultItem(bucket=bucket, object=key)

    # -- listing -----------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", delimiter: str = "",
                     max_keys: int = 1000):
        self.get_bucket_info(bucket)
        try:
            blobs, prefixes, next_marker = self.c.list_blobs(
                bucket, prefix, delimiter, marker, max_keys)
        except AzureClientError as e:
            raise _map_err(e, bucket) from None
        objs = [ObjectInfo(bucket=bucket, name=b["name"],
                           size=b["size"], etag=b["etag"],
                           mod_time=_http_date_ts(b["last_modified"]))
                for b in blobs]
        return objs, prefixes, bool(next_marker)

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000):
        objs, _p, _t = self.list_objects(bucket, prefix, marker,
                                         max_keys=max_keys)
        return objs

    # -- multipart: azure-native staged blocks -----------------------------

    def new_multipart_upload(self, bucket, key, opts=None) -> str:
        self.get_bucket_info(bucket)
        uid = str(_uuid.uuid4())
        self._mpu[uid] = {"bucket": bucket, "key": key, "parts": {},
                          "metadata": dict(
                              (opts or PutOptions()).metadata)}
        return uid

    def get_multipart_info(self, bucket, key, uid) -> dict:
        return dict(self._up(bucket, key, uid).get("metadata", {}))

    def _up(self, bucket, key, uid):
        mpu = self._mpu.get(uid)
        if mpu is None or mpu["bucket"] != bucket or mpu["key"] != key:
            raise api_errors.InvalidUploadID(uid)
        return mpu

    def put_object_part(self, bucket, key, uid, part_number, reader,
                        size=-1):
        mpu = self._up(bucket, key, uid)
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.close()
        etag = hashlib.md5(body).hexdigest()
        ids = []
        try:
            for sub in range(0, max(len(body), 1), self.MAX_BLOCK):
                bid = _block_id(uid, part_number, sub // self.MAX_BLOCK)
                self.c.put_block(bucket, key, bid,
                                 body[sub:sub + self.MAX_BLOCK])
                ids.append(bid)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        mpu["parts"][part_number] = (etag, ids, len(body))
        return ObjectPartInfo(number=part_number, etag=etag,
                              size=len(body), actual_size=len(body))

    def list_object_parts(self, bucket, key, uid, part_marker=0,
                          max_parts=1000):
        mpu = self._up(bucket, key, uid)
        return [ObjectPartInfo(number=n, etag=e, size=sz,
                               actual_size=sz)
                for n, (e, _ids, sz) in sorted(mpu["parts"].items())
                if n > part_marker][:max_parts]

    def list_multipart_uploads(self, bucket, key=""):
        return [{"object": m["key"], "upload_id": uid, "initiated": 0.0}
                for uid, m in self._mpu.items()
                if m["bucket"] == bucket and (not key or m["key"] == key)]

    def abort_multipart_upload(self, bucket, key, uid) -> None:
        self._up(bucket, key, uid)
        self._mpu.pop(uid, None)

    def complete_multipart_upload(self, bucket, key, uid, parts):
        mpu = self._up(bucket, key, uid)
        block_ids: list[str] = []
        total = 0
        for cp in parts:
            stored = mpu["parts"].get(cp.part_number)
            if stored is None or stored[0] != cp.etag.strip('"'):
                raise api_errors.InvalidPart(cp.part_number)
            block_ids.extend(stored[1])
            total += stored[2]
        meta, ctype = self._meta_split(mpu["metadata"])
        try:
            self.c.put_block_list(bucket, key, block_ids, meta, ctype)
        except AzureClientError as e:
            raise _map_err(e, bucket, key) from None
        self._mpu.pop(uid, None)
        part_etags = "".join(mpu["parts"][cp.part_number][0]
                             for cp in parts)
        etag = hashlib.md5(bytes.fromhex(part_etags)).hexdigest() \
            + f"-{len(parts)}"
        return ObjectInfo(bucket=bucket, name=key, size=total, etag=etag)

    # -- misc --------------------------------------------------------------

    def storage_info(self) -> dict:
        return {"total": 0, "free": 0, "used": 0, "online_disks": 1,
                "offline_disks": 0, "sets": 0, "drives_per_set": 0,
                "backend": "gateway-azure"}

    def close(self) -> None:
        pass


class AzureGateway:
    """Gateway factory (reference cmd/gateway-main.go `minio gateway
    azure` registration shape)."""

    def __init__(self, account: str, key_b64: str, host: str,
                 port: int = 10000, secure: bool = False):
        self.client = AzureBlobClient(account, key_b64, host, port,
                                      secure)

    def object_layer(self) -> AzureGatewayObjects:
        return AzureGatewayObjects(self.client)
