"""NAS gateway: S3 API over a shared filesystem mount.

The reference's cmd/gateway/nas (121 LoC) is the FS backend pointed at a
network mount — same here: the gateway IS FSObjects over the given path,
multi-instance-safe to the degree the underlying mount's rename/fsync
semantics allow (identical caveat to the reference)."""

from __future__ import annotations

from ..object.fs import FSObjects


class NASGateway:
    def __init__(self, path: str):
        self.path = path

    def object_layer(self) -> FSObjects:
        return FSObjects(self.path)
