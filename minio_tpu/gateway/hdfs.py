"""HDFS gateway: ObjectLayer over WebHDFS (reference
cmd/gateway/hdfs/gateway-hdfs.go drives the native Hadoop RPC via a Go
client; the documented WebHDFS REST surface carries the same verbs
over HTTP — the right transport for a dependency-free build, and
offline-testable against an in-process namenode).

Layout mirrors the reference: buckets are directories under the HDFS
root, objects are files at <root>/<bucket>/<key>. Redirected two-step
writes (namenode 307 -> datanode) are followed automatically.
"""

from __future__ import annotations

import hashlib
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator, Optional

from ..object import api_errors
from ..object.engine import GetOptions, PutOptions
from ..object.hash_reader import HashReader
from ..storage.datatypes import ObjectInfo, ObjectPartInfo, VolInfo, single_version_page


class WebHDFSError(Exception):
    def __init__(self, status: int, exception: str, message: str = ""):
        super().__init__(f"{status} {exception}: {message}")
        self.status = status
        self.exception = exception


class WebHDFSClient:
    """Minimal WebHDFS v1 client (op=MKDIRS/CREATE/OPEN/LISTSTATUS/
    GETFILESTATUS/DELETE)."""

    def __init__(self, host: str, port: int = 9870, user: str = "minio",
                 timeout: float = 30.0):
        self.base = f"http://{host}:{port}/webhdfs/v1"
        self.user = user
        self.timeout = timeout

    def _url(self, path: str, op: str, **params) -> str:
        q = {"op": op, "user.name": self.user}
        q.update({k: str(v) for k, v in params.items()})
        return (self.base + urllib.parse.quote(path) + "?"
                + urllib.parse.urlencode(q))

    def _call(self, method: str, path: str, op: str, data: bytes = b"",
              follow_redirect: bool = False, body_on_hop0: bool = True,
              want_stream: bool = False, **params):
        """One WebHDFS op. With follow_redirect and body_on_hop0=False
        the documented two-step write runs: the namenode hop carries NO
        body, only the redirected datanode hop uploads the data."""
        url = self._url(path, op, **params)
        for hop in range(4):
            send_body = bool(data) and (hop > 0 or body_on_hop0)
            req = urllib.request.Request(
                url, data=data if send_body else None, method=method)
            try:
                resp = urllib.request.urlopen(req, timeout=self.timeout)
            except urllib.error.HTTPError as e:
                if e.code in (301, 302, 307) and follow_redirect:
                    url = e.headers.get("Location", "")
                    continue
                body = e.read()
                try:
                    ex = json.loads(body)["RemoteException"]
                    raise WebHDFSError(e.code, ex.get("exception", ""),
                                       ex.get("message", "")) from None
                except (ValueError, KeyError):
                    raise WebHDFSError(e.code, "HTTP",
                                       body[:200].decode(
                                           errors="replace")) from None
            except urllib.error.URLError as e:
                # connection-level failures (refused, broken pipe) must
                # map like HTTP ones, not escape as raw URLError
                raise WebHDFSError(0, "Unreachable",
                                   str(e.reason)) from None
            if data and not send_body:
                # the endpoint accepted without redirecting (HttpFS
                # proxies data directly): it never saw the payload —
                # returning success here would write an empty file
                resp.read()
                resp.close()
                body_on_hop0 = True
                continue
            if want_stream:
                return resp
            with resp:
                return resp.read()
        raise WebHDFSError(310, "TooManyRedirects", url)

    def mkdirs(self, path: str) -> bool:
        out = json.loads(self._call("PUT", path, "MKDIRS"))
        return bool(out.get("boolean"))

    def create(self, path: str, data: bytes,
               overwrite: bool = True) -> None:
        self._call("PUT", path, "CREATE", data=data,
                   follow_redirect=True, body_on_hop0=False,
                   overwrite=str(overwrite).lower())

    def open(self, path: str, offset: int = 0, length: int = -1,
             chunk: int = 1 << 20):
        """Streamed read: yields chunks from the (redirected) datanode
        response — a multi-GB object never materializes whole."""
        params = {}
        if offset:
            params["offset"] = offset
        if length >= 0:
            params["length"] = length
        resp = self._call("GET", path, "OPEN", follow_redirect=True,
                          want_stream=True, **params)

        def gen():
            try:
                while True:
                    piece = resp.read(chunk)
                    if not piece:
                        return
                    yield piece
            finally:
                resp.close()

        return gen()

    def status(self, path: str) -> dict:
        return json.loads(self._call("GET", path,
                                     "GETFILESTATUS"))["FileStatus"]

    def list_status(self, path: str) -> list[dict]:
        out = json.loads(self._call("GET", path, "LISTSTATUS"))
        return out["FileStatuses"]["FileStatus"]

    def delete(self, path: str, recursive: bool = False) -> bool:
        out = json.loads(self._call("DELETE", path, "DELETE",
                                    recursive=str(recursive).lower()))
        return bool(out.get("boolean"))


def _map_err(e: WebHDFSError, bucket: str, key: str = "") -> Exception:
    if e.exception == "FileNotFoundException" or e.status == 404:
        if key:
            return api_errors.ObjectNotFound(bucket, key)
        return api_errors.BucketNotFound(bucket)
    return api_errors.ObjectApiError(f"hdfs error: {e}")


class HDFSGatewayObjects:
    """ObjectLayer over a WebHDFS namespace rooted at `root`."""

    supports_sse_multipart = False

    def __init__(self, client: WebHDFSClient, root: str = "/minio"):
        self.c = client
        self.root = root.rstrip("/")
        try:
            self.c.mkdirs(self.root)
        except WebHDFSError:
            pass
        self._mpu: dict[str, dict] = {}

    def _p(self, bucket: str, key: str = "") -> str:
        return f"{self.root}/{bucket}" + (f"/{key}" if key else "")

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        # single-status existence check: listing ALL buckets would turn
        # a transient root LISTSTATUS failure into a silently-accepted
        # duplicate create
        if self.bucket_exists(bucket):
            raise api_errors.BucketExists(bucket)
        try:
            self.c.mkdirs(self._p(bucket))
        except WebHDFSError as e:
            raise _map_err(e, bucket) from None

    def bucket_exists(self, bucket: str) -> bool:
        try:
            return self.c.status(self._p(bucket))["type"] == "DIRECTORY"
        except WebHDFSError:
            return False

    def get_bucket_info(self, bucket: str) -> VolInfo:
        try:
            st = self.c.status(self._p(bucket))
        except WebHDFSError as e:
            raise _map_err(e, bucket) from None
        return VolInfo(bucket, st.get("modificationTime", 0) / 1e3)

    def list_buckets(self) -> list[VolInfo]:
        try:
            entries = self.c.list_status(self.root)
        except WebHDFSError:
            return []
        return [VolInfo(e["pathSuffix"],
                        e.get("modificationTime", 0) / 1e3)
                for e in entries if e.get("type") == "DIRECTORY"]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self.get_bucket_info(bucket)
        # S3 semantics: only FILES make a bucket non-empty (leftover
        # empty directories from deleted keys don't count)
        if not force and next(self._walk(bucket), None) is not None:
            raise api_errors.BucketNotEmpty(bucket)
        try:
            self.c.delete(self._p(bucket), recursive=True)
        except WebHDFSError as e:
            raise _map_err(e, bucket) from None

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        self.get_bucket_info(bucket)
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.verify()
            reader.close()
        if "/" in key:
            parent = key.rsplit("/", 1)[0]
            try:
                self.c.mkdirs(self._p(bucket, parent))
            except WebHDFSError:
                pass
        try:
            self.c.create(self._p(bucket, key), body)
        except WebHDFSError as e:
            raise _map_err(e, bucket, key) from None
        # ETag must match what HEAD/GET/LIST will report (HDFS keeps no
        # md5 xattr; a PUT-only md5 would 412 every If-Match later)
        return self.get_object_info(bucket, key)

    def get_object_info(self, bucket: str, key: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        try:
            st = self.c.status(self._p(bucket, key))
        except WebHDFSError as e:
            raise _map_err(e, bucket, key) from None
        if st.get("type") == "DIRECTORY":
            raise api_errors.ObjectNotFound(bucket, key)
        return ObjectInfo(
            bucket=bucket, name=key, size=int(st.get("length", 0)),
            etag=f"hdfs-{st.get('modificationTime', 0)}"
                 f"-{st.get('length', 0)}",
            mod_time=st.get("modificationTime", 0) / 1e3)

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info = self.get_object_info(bucket, key, opts)
        if length < 0:
            length = info.size - offset
        if length <= 0:
            return info, iter(())
        try:
            stream = self.c.open(self._p(bucket, key), offset, length)
        except WebHDFSError as e:
            raise _map_err(e, bucket, key) from None
        return info, stream

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        self.get_object_info(bucket, key)
        try:
            self.c.delete(self._p(bucket, key))
        except WebHDFSError as e:
            raise _map_err(e, bucket, key) from None
        return ObjectInfo(bucket=bucket, name=key)

    def delete_objects(self, bucket: str, objects: list[str]):
        out = []
        for key in objects:
            try:
                self.delete_object(bucket, key)
                out.append(None)
            except api_errors.ObjectApiError as e:
                out.append(e)
        return out

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        return self.get_object_info(bucket, key)   # HDFS: no xattrs kept

    def has_object_versions(self, bucket: str, key: str) -> bool:
        try:
            self.get_object_info(bucket, key)
            return True
        except api_errors.ObjectApiError:
            return False

    def heal_object(self, bucket: str, key: str, version_id: str = "",
                    deep_scan: bool = False, dry_run: bool = False):
        from ..object.healing import HealResultItem
        self.get_object_info(bucket, key)
        return HealResultItem(bucket=bucket, object=key)

    # -- listing (recursive LISTSTATUS walk) --------------------------------

    def _walk(self, bucket: str, dir_path: str = ""
              ) -> Iterator[tuple[str, dict]]:
        try:
            entries = self.c.list_status(self._p(bucket, dir_path))
        except WebHDFSError:
            return
        # S3 key order: a directory's subtree keys all start with
        # "name/", so sort dirs AS "name/" — a plain pathSuffix sort
        # would emit "a/..." before sibling file "a!" and break marker
        # pagination
        def order(e: dict) -> str:
            s = e["pathSuffix"]
            return s + "/" if e.get("type") == "DIRECTORY" else s

        for e in sorted(entries, key=order):
            name = (f"{dir_path}/{e['pathSuffix']}" if dir_path
                    else e["pathSuffix"])
            if e.get("type") == "DIRECTORY":
                yield from self._walk(bucket, name)
            else:
                yield name, e

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", delimiter: str = "",
                     max_keys: int = 1000):
        self.get_bucket_info(bucket)
        objs: list[ObjectInfo] = []
        prefixes: list[str] = []
        seen: set[str] = set()
        truncated = False
        # start the walk at the deepest directory of the prefix: a
        # bucket-wide walk would LISTSTATUS every directory only to
        # string-filter the results
        start_dir = prefix.rsplit("/", 1)[0] if "/" in prefix else ""
        for name, st in self._walk(bucket, start_dir):
            if not name.startswith(prefix) or (marker and
                                               name <= marker):
                continue
            if delimiter:
                rest = name[len(prefix):]
                d = rest.find(delimiter)
                if d >= 0:
                    p = prefix + rest[:d + len(delimiter)]
                    if p not in seen:
                        seen.add(p)
                        prefixes.append(p)
                        if len(objs) + len(prefixes) >= max_keys:
                            truncated = True
                            break
                    continue
            objs.append(ObjectInfo(
                bucket=bucket, name=name, size=int(st.get("length", 0)),
                etag=f"hdfs-{st.get('modificationTime', 0)}"
                     f"-{st.get('length', 0)}",
                mod_time=st.get("modificationTime", 0) / 1e3))
            if len(objs) + len(prefixes) >= max_keys:
                truncated = True
                break
        return objs, prefixes, truncated

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000,
                             version_marker: str = "",
                             delimiter: str = ""):
        objs, pfx, trunc = self.list_objects(bucket, prefix, marker,
                                             delimiter,
                                             max_keys=max_keys)
        return single_version_page(objs, trunc, pfx)

    # -- multipart (buffered parts, like the S3-proxy gateway) --------------

    def new_multipart_upload(self, bucket, key, opts=None) -> str:
        import uuid as _uuid
        self.get_bucket_info(bucket)
        uid = str(_uuid.uuid4())
        self._mpu[uid] = {"bucket": bucket, "key": key, "parts": {},
                          "metadata": dict(
                              (opts or PutOptions()).metadata)}
        return uid

    def get_multipart_info(self, bucket, key, uid) -> dict:
        return dict(self._up(bucket, key, uid).get("metadata", {}))

    def _up(self, bucket, key, uid):
        mpu = self._mpu.get(uid)
        if mpu is None or mpu["bucket"] != bucket or mpu["key"] != key:
            raise api_errors.InvalidUploadID(uid)
        return mpu

    def put_object_part(self, bucket, key, uid, part_number, reader,
                        size=-1):
        mpu = self._up(bucket, key, uid)
        if isinstance(reader, (bytes, bytearray)):
            body = bytes(reader)
        else:
            if not isinstance(reader, HashReader):
                reader = HashReader(reader, size)
            body = reader.read() if size < 0 else reader.read(size)
            reader.verify()
            reader.close()
        etag = hashlib.md5(body).hexdigest()
        mpu["parts"][part_number] = (etag, body)
        return ObjectPartInfo(number=part_number, etag=etag,
                              size=len(body), actual_size=len(body))

    def list_object_parts(self, bucket, key, uid, part_marker=0,
                          max_parts=1000):
        mpu = self._up(bucket, key, uid)
        return [ObjectPartInfo(number=n, etag=e, size=len(b),
                               actual_size=len(b))
                for n, (e, b) in sorted(mpu["parts"].items())
                if n > part_marker][:max_parts]

    def list_multipart_uploads(self, bucket, key=""):
        return [{"object": m["key"], "upload_id": uid, "initiated": 0.0}
                for uid, m in self._mpu.items()
                if m["bucket"] == bucket and (not key or m["key"] == key)]

    def abort_multipart_upload(self, bucket, key, uid) -> None:
        self._up(bucket, key, uid)
        self._mpu.pop(uid, None)

    def complete_multipart_upload(self, bucket, key, uid, parts):
        mpu = self._up(bucket, key, uid)
        body = b""
        for cp in parts:
            stored = mpu["parts"].get(cp.part_number)
            if stored is None or stored[0] != cp.etag.strip('"'):
                raise api_errors.InvalidPart(cp.part_number)
            body += stored[1]
        info = self.put_object(bucket, key, body,
                               opts=PutOptions(metadata=mpu["metadata"]))
        self._mpu.pop(uid, None)
        return info

    # -- misc --------------------------------------------------------------

    def storage_info(self) -> dict:
        return {"total": 0, "free": 0, "used": 0, "online_disks": 1,
                "offline_disks": 0, "sets": 0, "drives_per_set": 0,
                "backend": "gateway-hdfs"}

    def close(self) -> None:
        pass


class HDFSGateway:
    def __init__(self, host: str, port: int = 9870,
                 root: str = "/minio", user: str = "minio"):
        self.client = WebHDFSClient(host, port, user)
        self.root = root

    def object_layer(self) -> HDFSGatewayObjects:
        return HDFSGatewayObjects(self.client, self.root)
