"""Host (numpy) reference Reed-Solomon codec — the byte-identity oracle.

This is the CPU fallback and the oracle the TPU kernels (ops/rs_tpu.py) are
tested against, playing the role the reference's kernel-matrix tests play
(reference: cmd/erasure-encode_test.go / erasure-decode_test.go matrices).

Shard layout convention everywhere in this framework:
    a block of `size` bytes splits into k = data_shards shards of
    shard_len = ceil(size / k) bytes, zero-padded at the tail (same
    semantics as the reference codec's Split: pad-to-equal-shards).
"""

from __future__ import annotations

import numpy as np

from . import gf256, rs_matrix


def split(data: bytes | np.ndarray, data_shards: int) -> np.ndarray:
    """Split a byte block into (k, shard_len) with zero padding."""
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.asarray(data, dtype=np.uint8)
    if buf.size == 0:
        raise ValueError("cannot split empty data")
    shard_len = -(-buf.size // data_shards)
    out = np.zeros((data_shards, shard_len), dtype=np.uint8)
    out.reshape(-1)[:buf.size] = buf
    return out


def encode(shards: np.ndarray, parity_shards: int) -> np.ndarray:
    """shards: (k, L) data shards -> (k+m, L) all shards."""
    k, length = shards.shape
    pm = rs_matrix.parity_matrix(k, parity_shards)
    parity = gf256.gf_matmul(pm, shards)
    return np.concatenate([shards, parity], axis=0)


def encode_block(data: bytes | np.ndarray, data_shards: int,
                 parity_shards: int) -> np.ndarray:
    return encode(split(data, data_shards), parity_shards)


def reconstruct(shards: dict[int, np.ndarray], data_shards: int,
                parity_shards: int, shard_len: int,
                data_only: bool = False) -> np.ndarray:
    """Rebuild missing shards from the survivors.

    shards: {index: bytes-array} of the available shards (each (L,) uint8).
    Returns the full (n, L) shard matrix. With data_only=True, missing
    *parity* rows are left zero-filled — callers must only consume the data
    rows in that mode (GET path); heal paths must use data_only=False.
    """
    n = data_shards + parity_shards
    present = 0
    for i in shards:
        present |= 1 << i
    d, used = rs_matrix.decode_matrix(data_shards, parity_shards, present)
    stack = np.stack([np.asarray(shards[i], dtype=np.uint8) for i in used])
    if stack.shape[1] != shard_len:
        raise ValueError("shard length mismatch")
    data = gf256.gf_matmul(d, stack)
    out = np.zeros((n, shard_len), dtype=np.uint8)
    out[:data_shards] = data
    for i, s in shards.items():
        out[i] = s
    if not data_only:
        missing_parity = [i for i in range(data_shards, n) if i not in shards]
        if missing_parity:
            pm = rs_matrix.parity_matrix(data_shards, parity_shards)
            parity = gf256.gf_matmul(pm, data)
            for i in missing_parity:
                out[i] = parity[i - data_shards]
    return out


def verify(shards: np.ndarray, data_shards: int) -> bool:
    """Check parity consistency of a full (n, L) shard matrix."""
    n = shards.shape[0]
    pm = rs_matrix.parity_matrix(data_shards, n - data_shards)
    parity = gf256.gf_matmul(pm, shards[:data_shards])
    return bool((parity == shards[data_shards:]).all())


def join(shards: np.ndarray, data_shards: int, size: int) -> bytes:
    """Concatenate data shards and trim padding back to `size` bytes."""
    return shards[:data_shards].reshape(-1)[:size].tobytes()
