"""Pure-Python HighwayHash (fallback when the native library is absent).

Same algorithm as native/highwayhash.cpp; validated by the same
known-answer tests (HH64 published vectors + the reference's magic bitrot
key, which is HH256(zero_key, first 100 pi decimals) — reference constant
at cmd/bitrot.go:31). Slow — correctness fallback only.
"""

from __future__ import annotations

M64 = (1 << 64) - 1

_MUL0 = (0xdbe6d5d5fe4cce2f, 0xa4093822299f31d0,
         0x13198a2e03707344, 0x243f6a8885a308d3)
_MUL1 = (0x3bd39e10cb0ef593, 0xc0acf169b5f18a8c,
         0xbe5466cf34e90c6c, 0x452821e638d01377)


def _rot32(x: int) -> int:
    return ((x >> 32) | (x << 32)) & M64


class HighwayHash:
    def __init__(self, key: bytes):
        assert len(key) == 32
        k = [int.from_bytes(key[i * 8:(i + 1) * 8], "little") for i in range(4)]
        self.mul0 = list(_MUL0)
        self.mul1 = list(_MUL1)
        self.v0 = [self.mul0[i] ^ k[i] for i in range(4)]
        self.v1 = [self.mul1[i] ^ _rot32(k[i]) for i in range(4)]
        self._buf = b""

    # -- core permutation ---------------------------------------------------
    @staticmethod
    def _zipper_merge(v1: int, v0: int) -> tuple[int, int]:
        add0 = ((((v0 & 0xff000000) | (v1 & 0xff00000000)) >> 24)
                | (((v0 & 0xff0000000000) | (v1 & 0xff000000000000)) >> 16)
                | (v0 & 0xff0000) | ((v0 & 0xff00) << 32)
                | ((v1 & 0xff00000000000000) >> 8) | ((v0 << 56) & M64))
        add1 = ((((v1 & 0xff000000) | (v0 & 0xff00000000)) >> 24)
                | (v1 & 0xff0000) | ((v1 & 0xff0000000000) >> 16)
                | ((v1 & 0xff00) << 24) | ((v0 & 0xff000000000000) >> 8)
                | ((v1 & 0xff) << 48) | (v0 & 0xff00000000000000))
        return add1, add0

    def _update(self, lanes: list[int]) -> None:
        v0, v1, mul0, mul1 = self.v0, self.v1, self.mul0, self.mul1
        for i in range(4):
            v1[i] = (v1[i] + mul0[i] + lanes[i]) & M64
            mul0[i] ^= ((v1[i] & 0xffffffff) * (v0[i] >> 32)) & M64
            v0[i] = (v0[i] + mul1[i]) & M64
            mul1[i] ^= ((v0[i] & 0xffffffff) * (v1[i] >> 32)) & M64
        for dst, src, (hi, lo) in ((v0, v1, (1, 0)), (v0, v1, (3, 2)),
                                   (v1, v0, (1, 0)), (v1, v0, (3, 2))):
            add1, add0 = self._zipper_merge(src[hi], src[lo])
            dst[lo] = (dst[lo] + add0) & M64
            dst[hi] = (dst[hi] + add1) & M64

    def _update_packet(self, p: bytes) -> None:
        self._update([int.from_bytes(p[i * 8:(i + 1) * 8], "little")
                      for i in range(4)])

    def _update_remainder(self, b: bytes) -> None:
        n = len(b)
        mod4 = n & 3
        remainder = b[n & ~3:]
        packet = bytearray(32)
        for i in range(4):
            self.v0[i] = (self.v0[i] + ((n << 32) + n)) & M64
        # rotate v1 lanes' 32-bit halves left by n
        if n:
            for i in range(4):
                h0 = self.v1[i] & 0xffffffff
                h1 = self.v1[i] >> 32
                h0 = ((h0 << n) | (h0 >> (32 - n))) & 0xffffffff
                h1 = ((h1 << n) | (h1 >> (32 - n))) & 0xffffffff
                self.v1[i] = (h1 << 32) | h0
        packet[:n & ~3] = b[:n & ~3]
        if n & 16:
            base = n & ~3
            for i in range(4):
                # signed offset into the full buffer (reaches back into
                # already-copied bytes when mod4 < 4)
                packet[28 + i] = b[base + mod4 + i - 4]
        elif mod4:
            packet[16] = remainder[0]
            packet[17] = remainder[mod4 >> 1]
            packet[18] = remainder[mod4 - 1]
        self._update_packet(bytes(packet))

    def _permute_and_update(self) -> None:
        v = self.v0
        self._update([_rot32(v[2]), _rot32(v[3]), _rot32(v[0]), _rot32(v[1])])

    # -- public streaming API ----------------------------------------------
    def update(self, data: bytes) -> None:
        buf = self._buf + data
        full = len(buf) & ~31
        for i in range(0, full, 32):
            self._update_packet(buf[i:i + 32])
        self._buf = buf[full:]

    def _clone(self) -> "HighwayHash":
        h = HighwayHash.__new__(HighwayHash)
        h.v0, h.v1 = list(self.v0), list(self.v1)
        h.mul0, h.mul1 = list(self.mul0), list(self.mul1)
        h._buf = self._buf
        return h

    def digest64(self) -> int:
        h = self._clone()
        if h._buf:
            h._update_remainder(h._buf)
        for _ in range(4):
            h._permute_and_update()
        return (h.v0[0] + h.v1[0] + h.mul0[0] + h.mul1[0]) & M64

    def digest256(self) -> bytes:
        h = self._clone()
        if h._buf:
            h._update_remainder(h._buf)
        for _ in range(10):
            h._permute_and_update()
        def modred(a3u, a2, a1, a0):
            a3 = a3u & 0x3FFFFFFFFFFFFFFF
            m1 = a1 ^ (((a3 << 1) | (a2 >> 63)) & M64) ^ (((a3 << 2) | (a2 >> 62)) & M64)
            m0 = a0 ^ ((a2 << 1) & M64) ^ ((a2 << 2) & M64)
            return m1 & M64, m0 & M64
        h1, h0 = modred((h.v1[1] + h.mul1[1]) & M64, (h.v1[0] + h.mul1[0]) & M64,
                        (h.v0[1] + h.mul0[1]) & M64, (h.v0[0] + h.mul0[0]) & M64)
        h3, h2 = modred((h.v1[3] + h.mul1[3]) & M64, (h.v1[2] + h.mul1[2]) & M64,
                        (h.v0[3] + h.mul0[3]) & M64, (h.v0[2] + h.mul0[2]) & M64)
        return b"".join(x.to_bytes(8, "little") for x in (h0, h1, h2, h3))
