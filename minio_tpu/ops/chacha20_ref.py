"""Host ChaCha20 + Poly1305 (RFC 8439) — the SSE byte-identity oracle.

Two implementations of the same cipher, pinned against each other and
against the RFC 8439 test vectors (tests/test_chacha.py):

  * ``_block_scalar`` — a literal per-block transcription of the RFC
    (pure ints, one 64-byte block at a time). Slow; exists so the
    vectorized paths have an independent reference.
  * ``keystream`` / ``xor_stream`` — numpy-vectorized over blocks: the
    16-word state is built for ALL counters at once and the 20 rounds
    run as u32 array ops. This is the CPU data path the device kernel
    (ops/chacha20_jax.py) must match byte-for-byte.

Poly1305 runs on Python big ints (the 130-bit field makes numpy
awkward and the tag input is one 64 KiB package, not the hot loop).

These are PRIMITIVES: policy — nonce derivation, package framing, AAD
discipline — lives in features/crypto.py, and the crypto-hygiene lint
(tools/check) rejects any other caller.
"""

from __future__ import annotations

import numpy as np

_CONST = np.frombuffer(b"expa" b"nd 3" b"2-by" b"te k",
                       dtype="<u4").copy()

# quarter-round schedule: 4 column rounds then 4 diagonal rounds
_QROUNDS = ((0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
            (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14))


def key_words(key: bytes) -> np.ndarray:
    """32-byte key -> (8,) little-endian u32 words."""
    if len(key) != 32:
        raise ValueError("ChaCha20 key must be 256 bits")
    return np.frombuffer(key, dtype="<u4").copy()


def nonce_words(nonce: bytes) -> np.ndarray:
    """12-byte nonce -> (3,) little-endian u32 words."""
    if len(nonce) != 12:
        raise ValueError("ChaCha20 nonce must be 96 bits")
    return np.frombuffer(nonce, dtype="<u4").copy()


# ---------------------------------------------------------------------------
# scalar reference (RFC 8439 §2.3 literal)
# ---------------------------------------------------------------------------

def _rotl32(x: int, n: int) -> int:
    x &= 0xFFFFFFFF
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _block_scalar(key: bytes, nonce: bytes, counter: int) -> bytes:
    """One 64-byte keystream block, pure ints."""
    init = list(_CONST.tolist()) + list(key_words(key).tolist()) + \
        [counter & 0xFFFFFFFF] + list(nonce_words(nonce).tolist())
    x = list(init)

    def qr(a, b, c, d):
        x[a] = (x[a] + x[b]) & 0xFFFFFFFF
        x[d] = _rotl32(x[d] ^ x[a], 16)
        x[c] = (x[c] + x[d]) & 0xFFFFFFFF
        x[b] = _rotl32(x[b] ^ x[c], 12)
        x[a] = (x[a] + x[b]) & 0xFFFFFFFF
        x[d] = _rotl32(x[d] ^ x[a], 8)
        x[c] = (x[c] + x[d]) & 0xFFFFFFFF
        x[b] = _rotl32(x[b] ^ x[c], 7)

    for _ in range(10):
        for a, b, c, d in _QROUNDS:
            qr(a, b, c, d)
    out = [(x[i] + init[i]) & 0xFFFFFFFF for i in range(16)]
    return b"".join(w.to_bytes(4, "little") for w in out)


# ---------------------------------------------------------------------------
# vectorized keystream (the CPU data path)
# ---------------------------------------------------------------------------

def _rounds_vec(state: np.ndarray) -> np.ndarray:
    """(16, N) u32 initial states -> (16, N) output states (rounds +
    feed-forward add)."""
    x = state.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):
            for a, b, c, d in _QROUNDS:
                x[a] += x[b]
                t = x[d] ^ x[a]
                x[d] = (t << np.uint32(16)) | (t >> np.uint32(16))
                x[c] += x[d]
                t = x[b] ^ x[c]
                x[b] = (t << np.uint32(12)) | (t >> np.uint32(20))
                x[a] += x[b]
                t = x[d] ^ x[a]
                x[d] = (t << np.uint32(8)) | (t >> np.uint32(24))
                x[c] += x[d]
                t = x[b] ^ x[c]
                x[b] = (t << np.uint32(7)) | (t >> np.uint32(25))
        x += state
    return x


def keystream(key: bytes, nonce: bytes, counter: int,
              nblocks: int) -> np.ndarray:
    """(nblocks*64,) u8 keystream starting at block `counter`."""
    if nblocks <= 0:
        return np.zeros(0, dtype=np.uint8)
    state = np.empty((16, nblocks), dtype=np.uint32)
    state[0:4] = _CONST[:, None]
    state[4:12] = key_words(key)[:, None]
    state[12] = (counter + np.arange(nblocks,
                                     dtype=np.uint64)) & 0xFFFFFFFF
    state[13:16] = nonce_words(nonce)[:, None]
    out = _rounds_vec(state)
    # serialize column-major: block j is out[:, j]'s 16 LE words
    return np.ascontiguousarray(out.T).astype("<u4").view(
        np.uint8).reshape(-1)


def xor_stream(data, key: bytes, nonce: bytes,
               counter: int = 1) -> bytes:
    """ChaCha20-encrypt/decrypt `data` (bytes/memoryview/uint8 array)
    with the keystream starting at block `counter` (RFC 8439 payload
    convention: counter 1; counter 0 is the Poly1305 one-time key)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data.astype(np.uint8,
                                                             copy=False)
    n = buf.shape[0]
    if n == 0:
        return b""
    ks = keystream(key, nonce, counter, -(-n // 64))
    return (buf ^ ks[:n]).tobytes()


def xor_stream_into(arr: np.ndarray, key: bytes, nonce: bytes,
                    counter: int = 1) -> None:
    """In-place variant over a uint8 array (the engine's staging-ring
    rows encrypt without a copy on the CPU fallback path)."""
    n = arr.shape[0]
    if n:
        ks = keystream(key, nonce, counter, -(-n // 64))
        np.bitwise_xor(arr, ks[:n], out=arr)


# ---------------------------------------------------------------------------
# Poly1305 (RFC 8439 §2.5) + the AEAD construction, detached-tag form
# ---------------------------------------------------------------------------

_P1305 = (1 << 130) - 5
_CLAMP = 0x0ffffffc0ffffffc0ffffffc0fffffff


def poly1305_mac(msg: bytes, key: bytes) -> bytes:
    """16-byte Poly1305 tag of `msg` under a 32-byte one-time key."""
    if len(key) != 32:
        raise ValueError("Poly1305 key must be 256 bits")
    r = int.from_bytes(key[:16], "little") & _CLAMP
    s = int.from_bytes(key[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i:i + 16]
        acc = ((acc + int.from_bytes(blk, "little")
                + (1 << (8 * len(blk)))) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def poly1305_key_gen(key: bytes, nonce: bytes) -> bytes:
    """Per-(key, nonce) one-time Poly1305 key: the first 32 bytes of
    ChaCha20 block 0 (RFC 8439 §2.6)."""
    return _block_scalar(key, nonce, 0)[:32]


def _pad16(n: int) -> bytes:
    return b"\x00" * (-n % 16)


def tag_detached(key: bytes, nonce: bytes, aad: bytes,
                 ct: bytes) -> bytes:
    """Poly1305 tag over an ALREADY-encrypted payload — the seam the
    device path uses: ciphertext comes back from the device, the tag
    is computed host-side before commit (no laundered auth)."""
    mac_data = (aad + _pad16(len(aad)) + ct + _pad16(len(ct))
                + len(aad).to_bytes(8, "little")
                + len(ct).to_bytes(8, "little"))
    return poly1305_mac(mac_data, poly1305_key_gen(key, nonce))


def seal_detached(key: bytes, nonce: bytes, aad: bytes,
                  pt: bytes) -> tuple[bytes, bytes]:
    """ChaCha20-Poly1305 seal, (ciphertext, tag) detached."""
    ct = xor_stream(pt, key, nonce, counter=1)
    return ct, tag_detached(key, nonce, aad, ct)


def open_detached(key: bytes, nonce: bytes, aad: bytes, ct: bytes,
                  tag: bytes) -> bytes:
    """Verify-then-decrypt; raises ValueError on tag mismatch BEFORE
    any plaintext is produced."""
    import hmac
    want = tag_detached(key, nonce, aad, ct)
    if not hmac.compare_digest(want, tag):
        raise ValueError("Poly1305 tag mismatch")
    return xor_stream(ct, key, nonce, counter=1)
