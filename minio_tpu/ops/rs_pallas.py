"""Fused Pallas TPU kernel for GF(2^8) coding matmuls.

The XLA path (rs_tpu.gf_matmul_xla) materializes the 8x bit-plane expansion
of the shard bytes in HBM — 8x the memory traffic of the payload. This
kernel fuses unpack -> binary matmul -> mod2 -> pack inside VMEM so HBM
sees only input bytes and output bytes:

    grid = (batch, S/TS)
    per step: load (k, TS) bytes -> bit-expand to (8k, TS) in VMEM
              -> MXU dot with the (8r, 8k) 0/1 matrix -> f32 (8r, TS)
              -> &1 -> pack -> store (r, TS) bytes

Layout note (measured on v5e): the natural bit row order i*8+p (byte i,
bit p) forces a sublane *interleave* when stacking the 8 shifted planes —
Mosaic lowers that as an expensive relayout. We instead keep bit-planes
contiguous ("plane-major": row p*k+i) and permute the coding matrix's
rows/columns to match — algebraically identical, zero extra cost (the
permutation is applied to the tiny matrix on the host/trace side).

All in-kernel tensors are 2D: Mosaic (as of jax 0.9) rejects 3D reshapes
like (1,8)->(8,1,1), and rejects uint8 shifts / int8 dot operands, so the
unpack runs in int32 and the matmul in bf16 with f32 accumulation
(contraction <= 128 keeps every partial sum exactly representable).

Replaces the reference's SIMD table-lookup kernels (its codec library's
AVX2 galMulSlice path) with an MXU-shaped formulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane-dimension tile: bytes of shard processed per grid step.
_TS = 16384


@functools.lru_cache(maxsize=64)
def _plane_major_perms(r: int, k: int) -> tuple[np.ndarray, np.ndarray]:
    """Permutations mapping canonical bit layout (byte-major, row i*8+p) to
    plane-major (row p*k+i) for an (r x k) byte matrix's GF(2) expansion."""
    rperm = np.array([j * 8 + q for q in range(8) for j in range(r)])
    cperm = np.array([i * 8 + p for p in range(8) for i in range(k)])
    return rperm, cperm


def _kernel(m2_ref, data_ref, out_ref, *, k: int, r: int):
    x = data_ref[0].astype(jnp.int32)                      # (k, TS)
    planes = [((x >> p) & 1) for p in range(8)]
    bits = jnp.concatenate(planes, axis=0)                 # (8k, TS) plane-major
    acc = jnp.dot(m2_ref[...], bits.astype(jnp.bfloat16),
                  preferred_element_type=jnp.float32)      # (8r, TS)
    ob = acc.astype(jnp.int32) & 1                         # plane-major rows
    out = ob[0:r]
    for q in range(1, 8):
        out = out | (ob[q * r:(q + 1) * r] << q)
    out_ref[0] = out.astype(jnp.uint8)


def _run(m2p: jnp.ndarray, data: jnp.ndarray, r: int, k: int) -> jnp.ndarray:
    b, _, s = data.shape  # s is a multiple of _TS
    grid = (b, s // _TS)
    return pl.pallas_call(
        functools.partial(_kernel, k=k, r=r),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r * 8, k * 8), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, _TS), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, _TS), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, s), jnp.uint8),
    )(m2p, data)


def gf_matmul_pallas_dev(m2: jnp.ndarray, shards: jnp.ndarray,
                         r: int, k: int) -> jnp.ndarray:
    """Apply bit-expanded matrix m2 ((8r,8k), canonical byte-major layout,
    any numeric dtype) to (..., k, S) uint8 shard bytes."""
    rperm, cperm = _plane_major_perms(r, k)
    m2p = m2.astype(jnp.bfloat16)[rperm][:, cperm]
    lead = shards.shape[:-2]
    s = shards.shape[-1]
    data = shards.reshape(-1, k, s)
    pad = (-s) % _TS
    if pad:
        data = jnp.pad(data, ((0, 0), (0, 0), (0, pad)))
    out = _run(m2p, data, r, k)
    if pad:
        out = out[..., :s]
    return out.reshape(*lead, r, s)


def gf_matmul_pallas(matrix: np.ndarray, shards: jnp.ndarray) -> jnp.ndarray:
    """Apply a host (r,k) GF(2^8) matrix to (..., k, S) shard bytes."""
    from . import rs_tpu
    r, k = matrix.shape
    m2 = jnp.asarray(rs_tpu._bit_expand_cached(
        np.ascontiguousarray(matrix, dtype=np.uint8).tobytes(), (r, k)),
        jnp.bfloat16)
    return gf_matmul_pallas_dev(m2, jnp.asarray(shards, jnp.uint8), r, k)
