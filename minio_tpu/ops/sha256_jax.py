"""Batched SHA-256 on device: all N streams advance in lockstep.

The reference uses sha256-simd (SHA-NI/AVX512 assembly) for content
hashes and the sha256 bitrot algorithm (cmd/bitrot.go:43-44,
pkg/hash/reader.go:31). A hash is sequential per stream; batching across
the B×n shard files of a PutObject batch is what maps it to the VPU —
the same shape as the HighwayHash kernel (ops/highwayhash_jax.py), but
simpler: SHA-256 is pure uint32 (rotates, xors, adds — no 64-bit lanes,
no multiplies, so none of the XLA algsimp pathologies either).

Graph-size discipline (single-core CPU hosts pay LLVM time per op): the
64 compression rounds and the 48 schedule extensions run as fori_loops
with dynamic indexing, so the compiled body is one round, not 64.

Bit-identity with hashlib.sha256 is enforced across padding branches by
tests/test_sha256_jax.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

U32 = jnp.uint32

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)


def _ror(x, r: int):
    return (x >> U32(r)) | (x << U32(32 - r))


def _block_words(block_u8: jnp.ndarray) -> jnp.ndarray:
    """(N, 64) uint8 -> (16, N) u32 big-endian words."""
    b = block_u8.astype(U32).reshape(block_u8.shape[0], 16, 4)
    w = (b[:, :, 0] << U32(24)) | (b[:, :, 1] << U32(16)) | \
        (b[:, :, 2] << U32(8)) | b[:, :, 3]
    return w.T                                     # (16, N)


def _unrolled() -> bool:
    """Unroll the 112 per-block inner steps on TPU (loop trip overhead
    costs ~70 ms/batch otherwise); keep fori_loops on the CPU backend
    where each unrolled op is real single-core LLVM compile time."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _one_round(abcdefgh, wi, ki):
    a, b, c, d, e, f, g, h = abcdefgh
    s1 = _ror(e, 6) ^ _ror(e, 11) ^ _ror(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + s1 + ch + ki + wi
    s0 = _ror(a, 2) ^ _ror(a, 13) ^ _ror(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    return (t1 + s0 + maj, a, b, c, d + t1, e, f, g)


def _compress(state: jnp.ndarray, w16: jnp.ndarray,
              unroll: bool) -> jnp.ndarray:
    """state (8, N), w16 (16, N) -> new state (8, N)."""
    n = w16.shape[1]
    st = tuple(state[i] for i in range(8))

    if unroll:
        ws = [w16[i] for i in range(16)]
        for i in range(16, 64):
            w15, w2 = ws[i - 15], ws[i - 2]
            s0 = _ror(w15, 7) ^ _ror(w15, 18) ^ (w15 >> U32(3))
            s1 = _ror(w2, 17) ^ _ror(w2, 19) ^ (w2 >> U32(10))
            ws.append(ws[i - 16] + s0 + ws[i - 7] + s1)
        for i in range(64):
            st = _one_round(st, ws[i], U32(int(_K[i])))
        return state + jnp.stack(st)

    w = jnp.zeros((64, n), U32).at[:16].set(w16)

    def extend(i, w):
        w15 = lax.dynamic_slice_in_dim(w, i - 15, 1)[0]
        w2 = lax.dynamic_slice_in_dim(w, i - 2, 1)[0]
        w16_ = lax.dynamic_slice_in_dim(w, i - 16, 1)[0]
        w7 = lax.dynamic_slice_in_dim(w, i - 7, 1)[0]
        s0 = _ror(w15, 7) ^ _ror(w15, 18) ^ (w15 >> U32(3))
        s1 = _ror(w2, 17) ^ _ror(w2, 19) ^ (w2 >> U32(10))
        return lax.dynamic_update_slice_in_dim(
            w, (w16_ + s0 + w7 + s1)[None], i, 0)

    w = lax.fori_loop(16, 64, extend, w)
    kv = jnp.asarray(_K)

    def round_(i, abcdefgh):
        wi = lax.dynamic_slice_in_dim(w, i, 1)[0]
        ki = lax.dynamic_slice_in_dim(kv, i, 1)[0]
        return _one_round(abcdefgh, wi, ki)

    out = lax.fori_loop(0, 64, round_, st)
    return state + jnp.stack(out)


@functools.partial(jax.jit, static_argnums=(1,))
def _sha256_impl(data: jnp.ndarray, length: int) -> jnp.ndarray:
    n = data.shape[0]
    # standard padding: 0x80, zeros, 64-bit bit-length big-endian
    padded_len = ((length + 8) // 64 + 1) * 64
    pad = jnp.zeros((n, padded_len - length), jnp.uint8)
    pad = pad.at[:, 0].set(0x80)
    bitlen = length * 8
    tail = np.frombuffer(bitlen.to_bytes(8, "big"), np.uint8)
    pad = pad.at[:, -8:].set(jnp.asarray(tail)[None, :])
    msg = jnp.concatenate([data[:, :length], pad], axis=1)

    n_blocks = padded_len // 64
    # (N, blocks, 64) -> (blocks, 16, N) big-endian words
    blocks = msg.reshape(n, n_blocks, 64)
    state = jnp.broadcast_to(jnp.asarray(_H0)[:, None], (8, n)).astype(U32)
    unroll = _unrolled()

    def body(st, blk):                       # blk: (N, 64)
        return _compress(st, _block_words(blk), unroll), None

    state, _ = lax.scan(body, state,
                        jnp.transpose(blocks, (1, 0, 2)))
    # (8, N) u32 -> (N, 32) big-endian bytes
    b = jnp.stack([(state >> U32(24)) & U32(0xff),
                   (state >> U32(16)) & U32(0xff),
                   (state >> U32(8)) & U32(0xff),
                   state & U32(0xff)], axis=-1)   # (8, N, 4)
    return jnp.transpose(b, (1, 0, 2)).reshape(n, 32).astype(jnp.uint8)


def sha256_batch(data) -> jax.Array:
    """SHA-256 of every row of an (N, L) uint8 array -> (N, 32) digests,
    bit-identical to hashlib.sha256."""
    data = jnp.asarray(data, jnp.uint8)
    if data.ndim != 2:
        raise ValueError("data must be (N, L)")
    return _sha256_impl(data, data.shape[1])
