"""Reed-Solomon encode/decode matrix construction.

Matches the construction the reference uses (klauspost/reedsolomon `New`
default path, as wrapped by the reference's cmd/erasure-coding.go:56):
a (total x data) Vandermonde matrix vm[r, c] = r**c over GF(2^8), made
systematic by right-multiplying with the inverse of its top (data x data)
square. The top k rows become the identity; rows k..n-1 are the parity rows.

Decode matrices for arbitrary erasure patterns are derived from the same
encode matrix and cached per (k, m, missing-bitmask).
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


@functools.lru_cache(maxsize=256)
def vandermonde(rows: int, cols: int) -> np.ndarray:
    m = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            m[r, c] = gf256.gf_exp(r, c)
    m.setflags(write=False)
    return m


@functools.lru_cache(maxsize=256)
def encode_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Full (n x k) systematic encode matrix; top k rows are identity."""
    total = data_shards + parity_shards
    if data_shards <= 0 or parity_shards <= 0:
        raise ValueError("data and parity shard counts must be positive")
    if total > 256:
        raise ValueError("too many shards: max 256")
    vm = vandermonde(total, data_shards)
    top_inv = gf256.gf_mat_inv(vm[:data_shards])
    em = gf256.gf_matmul(vm, top_inv)
    # sanity: systematic
    assert (em[:data_shards] == np.eye(data_shards, dtype=np.uint8)).all()
    em.setflags(write=False)
    return em


@functools.lru_cache(maxsize=256)
def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (m x k) parity rows of the encode matrix."""
    return encode_matrix(data_shards, parity_shards)[data_shards:]


@functools.lru_cache(maxsize=4096)
def decode_matrix(data_shards: int, parity_shards: int,
                  present_mask: int) -> tuple[np.ndarray, tuple[int, ...]]:
    """Matrix reconstructing ALL k data shards from k surviving shards.

    present_mask: bitmask over all n shards; bit i set = shard i readable.
    Mirrors the reference codec's reconstruction row selection: scan shards
    in index order and take the first k present ones.

    Returns (D, used) where D is (k x k) over GF(2^8) and `used` lists the
    k shard indices (in scan order) whose bytes form the input rows.
    """
    n = data_shards + parity_shards
    used: list[int] = []
    for i in range(n):
        if present_mask >> i & 1:
            used.append(i)
            if len(used) == data_shards:
                break
    if len(used) < data_shards:
        raise ValueError(
            f"too few shards: need {data_shards}, have {len(used)}")
    em = encode_matrix(data_shards, parity_shards)
    sub = em[used]  # (k x k)
    d = gf256.gf_mat_inv(sub)
    d.setflags(write=False)
    return d, tuple(used)


@functools.lru_cache(maxsize=4096)
def missing_data_matrix(data_shards: int, parity_shards: int,
                        present_mask: int
                        ) -> tuple[np.ndarray, tuple[int, ...],
                                   tuple[int, ...]]:
    """Matrix producing ONLY the missing data shards from k survivors.

    The degraded-GET kernel: a GET never needs to materialize data shards
    it already read, so the device matmul should be (|missing data| x k),
    not the full (k x k) decode (reference ReconstructData fills only
    missing blocks too, cmd/erasure-coding.go:89-102 semantics). With 3
    of 12 data shards lost this is a 4x smaller matmul than decode_matrix.

    Returns (Dm, used, missing_data): Dm is (|missing_data| x k);
    Dm @ shards[used] yields shards[missing_data] in index order.
    """
    d, used = decode_matrix(data_shards, parity_shards, present_mask)
    missing = tuple(i for i in range(data_shards)
                    if not (present_mask >> i & 1))
    dm = np.ascontiguousarray(d[list(missing)])
    dm.setflags(write=False)
    return dm, used, missing


@functools.lru_cache(maxsize=4096)
def recover_matrix(data_shards: int, parity_shards: int,
                   present_mask: int) -> tuple[np.ndarray, tuple[int, ...], tuple[int, ...]]:
    """Matrix producing exactly the MISSING shards (data and parity) from k
    surviving shards — the heal kernel's one-matmul form.

    Returns (R, used, missing): R is (|missing| x k); R @ shards[used]
    yields shards[missing] in index order.
    """
    n = data_shards + parity_shards
    d, used = decode_matrix(data_shards, parity_shards, present_mask)
    missing = tuple(i for i in range(n) if not (present_mask >> i & 1))
    em = encode_matrix(data_shards, parity_shards)
    # rows of em for missing shards, composed with the data-recovery matrix
    r = gf256.gf_matmul(em[list(missing)], d)
    r.setflags(write=False)
    return r, used, missing


def recover_rows(data_shards: int, parity_shards: int,
                 present_mask: int, rows) -> tuple[np.ndarray, list[int]]:
    """Recover matrix filtered to the requested shard indices: returns
    (matrix (R x k) uint8 C-contiguous, shard index per output row).
    Empty/None `rows` keeps every missing shard. The ONE copy of the
    heal row-selection invariant, shared by the single-device codec and
    the mesh heal step."""
    rec, _used, rec_missing = recover_matrix(data_shards, parity_shards,
                                             present_mask)
    if rows:
        keep = [r for r, idx in enumerate(rec_missing) if idx in rows]
    else:
        keep = list(range(len(rec_missing)))
    idxs = [rec_missing[r] for r in keep]
    rec = np.ascontiguousarray(np.asarray(rec, dtype=np.uint8)[keep])
    return rec, idxs
