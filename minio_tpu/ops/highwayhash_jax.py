"""Batched HighwayHash-256 on TPU: u32-pair emulation of the 64-bit lanes.

The reference's bitrot default is HighwayHash256 (cmd/bitrot.go:48-53,
streaming framing cmd/bitrot-streaming.go:46-58) computed per shard block
with AVX2 assembly. A hash is strictly sequential in its packet stream, so
a TPU can't parallelize *within* one shard — but a PutObject batch hashes
B×n independent shard blocks, and the VPU runs all of them in lockstep.

Layout choices that matter on the VPU:
  * no 64-bit integer lanes -> every u64 is a (lo, hi) pair of uint32
    arrays; adds carry via unsigned compare, 32x32->64 multiplies via
    16-bit split (with optimization barriers on the shifted operands —
    XLA's algebraic simplifier cycles on mul(shr(x)) patterns).
  * the state's four u64 lanes are kept permanently split into even
    (0, 2) and odd (1, 3) lane pairs, because the zipper-merge step mixes
    lanes pairwise: with the split representation every packet round is
    purely elementwise (no stack/reshape relayouts inside the scan).
  * streams fold into sublane GROUPS: a (2, N) state uses 2 of 8 VPU
    sublanes; reshaping to (2·G, N/G) with G stream groups stacked along
    sublanes fills the register file (G=4 on TPU -> full 8-sublane
    utilization). Packet words are pre-permuted once outside the scan so
    every round takes contiguous static slices.
  * packet rounds are unrolled _UNROLL-fold per lax.scan step to amortize
    loop overhead; the CPU backend keeps G=1 and a small unroll (each op
    is real single-core LLVM compile time there).

Bit-identity with the scalar implementation (ops/highwayhash_py.py, itself
pinned to the published HighwayHash vectors) is enforced by
tests/test_highwayhash_jax.py over lengths covering every remainder path,
and the grouped TPU layout is algebraically the same elementwise program
under a row relabeling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_MUL0 = (0xdbe6d5d5fe4cce2f, 0xa4093822299f31d0,
         0x13198a2e03707344, 0x243f6a8885a308d3)
_MUL1 = (0x3bd39e10cb0ef593, 0xc0acf169b5f18a8c,
         0xbe5466cf34e90c6c, 0x452821e638d01377)

# packets unrolled per scan step / sublane stream-groups, per backend.
_UNROLL_TPU = 16
_UNROLL_CPU = 2
_GROUPS_TPU = 4
_GROUPS_CPU = 1


def _unroll() -> int:
    try:
        return _UNROLL_TPU if jax.default_backend() == "tpu" \
            else _UNROLL_CPU
    except Exception:
        return _UNROLL_CPU


def _groups() -> int:
    try:
        return _GROUPS_TPU if jax.default_backend() == "tpu" \
            else _GROUPS_CPU
    except Exception:
        return _GROUPS_CPU


U32 = jnp.uint32


def _word_perm(g: int) -> np.ndarray:
    """Row permutation for (8·g, n/g) per-packet words laid out w-major
    (row = word·g + group) -> [lo_e | hi_e | lo_o | hi_o] blocks of 2g
    rows each, lane-major then group within a block.

    Little-endian u64 lane j: lo = word 2j, hi = word 2j+1.
    """
    def block(words):
        return [w * g + grp for w in words for grp in range(g)]
    return np.array(block([0, 4]) + block([1, 5])
                    + block([2, 6]) + block([3, 7]))


# -- u64 emulation on (lo, hi) uint32 pairs ---------------------------------
# A "u64 vector" is a tuple (lo, hi) of identically-shaped uint32 arrays.

def _add64(a, b):
    lo = a[0] + b[0]
    carry = (lo < a[0]).astype(U32)
    return lo, a[1] + b[1] + carry


def _xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _or64(a, b):
    return a[0] | b[0], a[1] | b[1]


def _and64c(a, mask64: int):
    ml = U32(mask64 & 0xffffffff)
    mh = U32((mask64 >> 32) & 0xffffffff)
    return a[0] & ml, a[1] & mh


def _shl64c(a, s: int):
    if s == 0:
        return a
    if s >= 32:
        return jnp.zeros_like(a[0]), a[0] << U32(s - 32)
    return a[0] << U32(s), (a[1] << U32(s)) | (a[0] >> U32(32 - s))


def _shr64c(a, s: int):
    if s == 0:
        return a
    if s >= 32:
        return a[1] >> U32(s - 32), jnp.zeros_like(a[1])
    return (a[0] >> U32(s)) | (a[1] << U32(32 - s)), a[1] >> U32(s)


def _mul32(a32, b32):
    """(u32 a) * (u32 b) -> u64 pair, via 16-bit split.

    The high halves pass through an optimization barrier: XLA's algebraic
    simplifier cycles endlessly on `mul(shr(x, c), y)` patterns (circular
    rewrite; on big unrolled graphs the CPU compile never finishes), and
    the barrier hides the shift from the multiply."""
    m16 = U32(0xffff)
    al, ah = a32 & m16, lax.optimization_barrier(a32 >> U32(16))
    bl, bh = b32 & m16, lax.optimization_barrier(b32 >> U32(16))
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = lh + hl
    c_mid = (mid < lh).astype(U32)
    lo = ll + (mid << U32(16))
    c_lo = (lo < ll).astype(U32)
    hi = hh + (mid >> U32(16)) + (c_mid << U32(16)) + c_lo
    return lo, hi


def _zipper_merge(v1, v0):
    """Per-u64-lane byte shuffle of a (hi_lane=v1, lo_lane=v0) pair.

    v1/v0 are u64 pairs; returns (add1, add0) u64 pairs. Transcribed from
    highwayhash_py.HighwayHash._zipper_merge.
    """
    def t(x, mask, sh):
        m = _and64c(x, mask)
        return _shl64c(m, sh) if sh >= 0 else _shr64c(m, -sh)

    add0 = t(v0, 0xff000000, -24)
    for term in (t(v1, 0xff00000000, -24),
                 t(v0, 0xff0000000000, -16),
                 t(v1, 0xff000000000000, -16),
                 t(v0, 0xff0000, 0),
                 t(v0, 0xff00, 32),
                 t(v1, 0xff00000000000000, -8),
                 _shl64c(v0, 56)):
        add0 = _or64(add0, term)
    add1 = t(v1, 0xff000000, -24)
    for term in (t(v0, 0xff00000000, -24),
                 t(v1, 0xff0000, 0),
                 t(v1, 0xff0000000000, -16),
                 t(v1, 0xff00, 24),
                 t(v0, 0xff000000000000, -8),
                 t(v1, 0xff, 48),
                 t(v0, 0xff00000000000000, 0)):
        add1 = _or64(add1, term)
    return add1, add0


# -- state -------------------------------------------------------------------
# State: 8 u64 pairs of (2·G, N/G) u32 arrays — {v0,v1,mul0,mul1} ×
# {even lanes (0,2), odd lanes (1,3)}; within a pair, rows 0:G hold the
# low lane's G stream groups, rows G:2G the high lane's.

def _const_pair(vals2, g: int, cols: int):
    lo = np.repeat(np.array([v & 0xffffffff for v in vals2], np.uint32), g)
    hi = np.repeat(np.array([v >> 32 for v in vals2], np.uint32), g)
    return (jnp.broadcast_to(jnp.asarray(lo)[:, None], (2 * g, cols)),
            jnp.broadcast_to(jnp.asarray(hi)[:, None], (2 * g, cols)))


def _init_state(key: bytes, g: int, cols: int):
    k = [int.from_bytes(key[i * 8:(i + 1) * 8], "little") for i in range(4)]
    rot = [((v >> 32) | (v << 32)) & ((1 << 64) - 1) for v in k]
    st = {}
    for tag, lanes in (("e", (0, 2)), ("o", (1, 3))):
        mul0 = _const_pair([_MUL0[i] for i in lanes], g, cols)
        mul1 = _const_pair([_MUL1[i] for i in lanes], g, cols)
        st["mul0" + tag] = mul0
        st["mul1" + tag] = mul1
        st["v0" + tag] = _xor64(
            mul0, _const_pair([k[i] for i in lanes], g, cols))
        st["v1" + tag] = _xor64(
            mul1, _const_pair([rot[i] for i in lanes], g, cols))
    return st


def _update(st, pe, po):
    """One packet round. pe/po: u64 pairs of (2G, N/G) — even/odd lanes."""
    v0e, v0o = st["v0e"], st["v0o"]
    v1e, v1o = st["v1e"], st["v1o"]
    mul0e, mul0o = st["mul0e"], st["mul0o"]
    mul1e, mul1o = st["mul1e"], st["mul1o"]

    v1e = _add64(v1e, _add64(mul0e, pe))
    v1o = _add64(v1o, _add64(mul0o, po))
    mul0e = _xor64(mul0e, _mul32(v1e[0], v0e[1]))
    mul0o = _xor64(mul0o, _mul32(v1o[0], v0o[1]))
    v0e = _add64(v0e, mul1e)
    v0o = _add64(v0o, mul1o)
    mul1e = _xor64(mul1e, _mul32(v0e[0], v1e[1]))
    mul1o = _xor64(mul1o, _mul32(v0o[0], v1o[1]))
    add1, add0 = _zipper_merge(v1o, v1e)
    v0e = _add64(v0e, add0)
    v0o = _add64(v0o, add1)
    add1, add0 = _zipper_merge(v0o, v0e)
    v1e = _add64(v1e, add0)
    v1o = _add64(v1o, add1)
    return {"v0e": v0e, "v0o": v0o, "v1e": v1e, "v1o": v1o,
            "mul0e": mul0e, "mul0o": mul0o, "mul1e": mul1e, "mul1o": mul1o}


def _packet_from_rows(w, g: int):
    """(8G, N/G) u32 in _word_perm order -> (pe, po) u64 pairs."""
    return ((w[0:2 * g], w[2 * g:4 * g]),
            (w[4 * g:6 * g], w[6 * g:8 * g]))


def _rot32half(x, n: int):
    """Rotate each 32-bit half of a u64 pair left by n (remainder step)."""
    if n == 0:
        return x
    return ((x[0] << U32(n)) | (x[0] >> U32(32 - n)),
            (x[1] << U32(n)) | (x[1] >> U32(32 - n)))


def _words_grouped(packets_u8: jnp.ndarray, g: int) -> jnp.ndarray:
    """(N, P, 32) uint8 packets -> (P, 8G, N/G) u32 in _word_perm order."""
    n, p, _ = packets_u8.shape
    words = lax.bitcast_convert_type(
        packets_u8.reshape(n, p, 8, 4), U32)      # (N, P, 8) LE words
    words = jnp.transpose(words, (1, 2, 0))       # (P, 8, N)
    words = words.reshape(p, 8, g, n // g).reshape(p, 8 * g, n // g)
    return words[:, _word_perm(g), :]


def _update_remainder(st, tail_u8, n_bytes: int, g: int):
    """tail_u8: (N, R) uint8 with R = n_bytes = L mod 32 (may be 0)."""
    if n_bytes == 0:
        return st
    N = tail_u8.shape[0]
    st = dict(st)
    inc = ((n_bytes << 32) + n_bytes)
    for tag in ("e", "o"):
        st["v0" + tag] = _add64(st["v0" + tag],
                                _const_pair([inc, inc], g, N // g))
        st["v1" + tag] = _rot32half(st["v1" + tag], n_bytes)

    mod4 = n_bytes & 3
    base = n_bytes & ~3
    packet = jnp.zeros((N, 32), jnp.uint8)
    if base:
        packet = packet.at[:, :base].set(tail_u8[:, :base])
    if n_bytes & 16:
        for i in range(4):
            packet = packet.at[:, 28 + i].set(tail_u8[:, base + mod4 + i - 4])
    elif mod4:
        rem = tail_u8[:, base:]
        packet = packet.at[:, 16].set(rem[:, 0])
        packet = packet.at[:, 17].set(rem[:, mod4 >> 1])
        packet = packet.at[:, 18].set(rem[:, mod4 - 1])
    w = _words_grouped(packet[:, None, :], g)[0]
    pe, po = _packet_from_rows(w, g)
    return _update(st, pe, po)


def _swap_blocks(x, g: int):
    """Swap the two lane blocks (rows 0:G <-> G:2G) of one array."""
    return jnp.concatenate([x[g:], x[:g]])


def _permute_and_update(st, g: int):
    # packet lanes = v0 lanes [2,3,0,1] with 32-bit halves swapped:
    # within each even/odd pair that is a lane-block swap + lo/hi swap.
    v0e, v0o = st["v0e"], st["v0o"]
    pe = (_swap_blocks(v0e[1], g), _swap_blocks(v0e[0], g))
    po = (_swap_blocks(v0o[1], g), _swap_blocks(v0o[0], g))
    return _update(st, pe, po)


def _finalize256(st, g: int):
    """-> (8, N) u32: the 32-byte digest as 8 little-endian words, rows
    in word order, columns in original stream order."""
    st = lax.fori_loop(0, 10, lambda i, s: _permute_and_update(s, g), st)

    def lane(name, l):
        # u64 lane l: (G, N/G) lo/hi slices of the e/o pair
        tag = "e" if l % 2 == 0 else "o"
        blk = l // 2
        x = st[name + tag]
        return (x[0][blk * g:(blk + 1) * g], x[1][blk * g:(blk + 1) * g])

    def modred(a3, a2, a1, a0):
        a3 = _and64c(a3, 0x3FFFFFFFFFFFFFFF)
        s1 = _or64(_shl64c(a3, 1), _shr64c(a2, 63))
        s2 = _or64(_shl64c(a3, 2), _shr64c(a2, 62))
        m1 = _xor64(_xor64(a1, s1), s2)
        m0 = _xor64(_xor64(a0, _shl64c(a2, 1)), _shl64c(a2, 2))
        return m1, m0

    def sum64(name1, name2, l):
        return _add64(lane(name1, l), lane(name2, l))

    h1, h0 = modred(sum64("v1", "mul1", 1), sum64("v1", "mul1", 0),
                    sum64("v0", "mul0", 1), sum64("v0", "mul0", 0))
    h3, h2 = modred(sum64("v1", "mul1", 3), sum64("v1", "mul1", 2),
                    sum64("v0", "mul0", 3), sum64("v0", "mul0", 2))
    # each h is a pair of (G, N/G); stack to (8, G, N/G) word-major,
    # then flatten group rows back to N columns
    out = jnp.stack([h0[0], h0[1], h1[0], h1[1],
                     h2[0], h2[1], h3[0], h3[1]])      # (8, G, N/G)
    return out.reshape(8, -1)                          # (8, N) group-major


# -- public op ---------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1, 2))
def _hh256_impl(data: jnp.ndarray, length: int, key: bytes) -> jnp.ndarray:
    n_in = data.shape[0]
    g = _groups()
    pad_rows = (-n_in) % g
    if pad_rows:
        data = jnp.concatenate(
            [data, jnp.zeros((pad_rows, data.shape[1]), jnp.uint8)])
    n = n_in + pad_rows
    full = length // 32
    rem = length % 32
    st = _init_state(key, g, n // g)

    if full:
        words = _words_grouped(
            data[:, :full * 32].reshape(n, full, 32), g)  # (F, 8G, N/G)
        u = min(_unroll(), full)
        main = (full // u) * u

        def body(st, w):
            for j in range(u):
                pe, po = _packet_from_rows(w[j * 8 * g:(j + 1) * 8 * g], g)
                st = _update(st, pe, po)
            return st, None

        st, _ = lax.scan(body, st, words[:main].reshape(
            full // u, u * 8 * g, n // g))
        for j in range(main, full):
            pe, po = _packet_from_rows(words[j], g)
            st = _update(st, pe, po)
    if rem:
        st = _update_remainder(st, data[:, full * 32:length], rem, g)
    out = _finalize256(st, g)                          # (8, N) u32
    # (8, N) -> (N, 8) -> little-endian bytes; the group fold in
    # _finalize256 restored original stream order (groups were split
    # contiguously: stream s lives in group s // (N/G))
    digests = lax.bitcast_convert_type(
        jnp.transpose(out, (1, 0)), jnp.uint8).reshape(n, 32)
    return digests[:n_in]


def hh256_batch(key: bytes, data) -> jax.Array:
    """HighwayHash-256 of every row of an (N, L) uint8 array -> (N, 32).

    Device-batched: all N hashes advance in lockstep on the VPU. Byte-
    identical to the scalar/native implementations for any L (including the
    remainder paths of the reference algorithm).
    """
    data = jnp.asarray(data, jnp.uint8)
    if data.ndim != 2:
        raise ValueError("data must be (N, L)")
    return _hh256_impl(data, data.shape[1], bytes(key))
