"""GF(2^8) arithmetic for Reed-Solomon erasure coding.

Field: GF(2^8) with the Rijndael-unrelated generator polynomial
x^8 + x^4 + x^3 + x^2 + 1 (0x11D), generator element 2 — the same field the
reference's codec dependency (klauspost/reedsolomon, wrapped at
cmd/erasure-coding.go:56 in the reference tree) is built on, so that shard
output is byte-identical.

Everything here is numpy on the host: matrix construction, inversion and the
oracle codec live on CPU; the TPU path (ops/rs_tpu.py) consumes the *binary
expansion* of these matrices and never does table lookups on device.
"""

from __future__ import annotations

import functools

import numpy as np

FIELD_SIZE = 256
_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables over GF(2^8) with generator 2."""
    exp = np.zeros(512, dtype=np.uint8)  # doubled for overflow-free mul
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]
    log[0] = -255 * 255  # log(0) sentinel: any use yields index < 0 — callers guard
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# Full 256x256 multiplication table. 64 KiB; used by the host oracle codec and
# to derive per-constant bit-matrices for the TPU kernels.
_a = np.arange(256, dtype=np.int32)
_MUL_TABLE = np.zeros((256, 256), dtype=np.uint8)
_nz = _a[1:]
_logs = GF_LOG[_nz][:, None] + GF_LOG[_nz][None, :]
_MUL_TABLE[1:, 1:] = GF_EXP[_logs % 255]
del _a, _nz, _logs

# Inverse table: inv[a] = a^(254)
GF_INV = np.zeros(256, dtype=np.uint8)
GF_INV[1:] = GF_EXP[(255 - GF_LOG[np.arange(1, 256)]) % 255]


def gf_mul(a: int, b: int) -> int:
    return int(_MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    return int(_MUL_TABLE[a, GF_INV[b]])


def gf_exp(a: int, n: int) -> int:
    """a**n in GF(2^8); matches the reference codec's exponentiation
    semantics (0**0 == 1, 0**n == 0 for n > 0)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_mul_vec(c: int, v: np.ndarray) -> np.ndarray:
    """Multiply every byte of v by the constant c."""
    return _MUL_TABLE[c][v]


def gf_matmul(m: np.ndarray, x: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product: (r,k) uint8 @ (k,n) uint8 -> (r,n) uint8.

    Host oracle path. XOR-accumulates table-multiplied rows.
    """
    m = np.asarray(m, dtype=np.uint8)
    x = np.asarray(x, dtype=np.uint8)
    r, k = m.shape
    out = np.zeros((r, x.shape[1]), dtype=np.uint8)
    for j in range(k):
        # rows of the constant-multiplication table indexed by m[:, j]
        out ^= _MUL_TABLE[m[:, j][:, None], x[j][None, :]]
    return out


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix via Gauss-Jordan elimination.

    Raises ValueError when singular (mirrors the reference codec's
    errSingular behavior).
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    if m.shape != (n, n):
        raise ValueError("matrix must be square")
    aug = np.zeros((n, 2 * n), dtype=np.uint8)
    aug[:, :n] = m
    aug[np.arange(n), n + np.arange(n)] = 1

    for col in range(n):
        # partial pivot: find a row with nonzero pivot
        pivot = -1
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        # scale pivot row to 1
        inv_p = GF_INV[aug[col, col]]
        aug[col] = _MUL_TABLE[inv_p][aug[col]]
        # eliminate all other rows
        col_vals = aug[:, col].copy()
        col_vals[col] = 0
        nz = np.nonzero(col_vals)[0]
        if nz.size:
            aug[nz] ^= _MUL_TABLE[col_vals[nz][:, None], aug[col][None, :]]
    return aug[:, n:].copy()


@functools.lru_cache(maxsize=512)
def mul_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix B of multiplication-by-c: for byte x with bit vector
    bits(x), bits(c*x) = B @ bits(x) mod 2 (bit 0 = LSB).

    Column p of B is bits(c * 2^p): multiplication by a constant is linear
    over GF(2), which is what lets the whole RS encode become a single
    binary matmul on the MXU (see ops/rs_tpu.py).
    """
    cols = _MUL_TABLE[c][1 << np.arange(8)]  # c * 2^p for p in 0..7
    bits = (cols[None, :] >> np.arange(8)[:, None]) & 1  # [q, p] = bit q of c*2^p
    return bits.astype(np.uint8)


def expand_to_gf2(m: np.ndarray) -> np.ndarray:
    """Expand an (r,k) GF(2^8) matrix into its (r*8, k*8) GF(2) bit-matrix.

    Output layout: row j*8+q is output-bit q of output-byte j; column i*8+p is
    input-bit p of input-byte i.
    """
    m = np.asarray(m, dtype=np.uint8)
    r, k = m.shape
    out = np.zeros((r * 8, k * 8), dtype=np.uint8)
    for j in range(r):
        for i in range(k):
            out[j * 8:(j + 1) * 8, i * 8:(i + 1) * 8] = mul_bitmatrix(int(m[j, i]))
    return out
