"""Batched ChaCha20 keystream-XOR on device (XLA/TPU).

The SSE cipher stage of the fused PUT program (models/pipeline.
sse_put_step): ChaCha20 is pure add-rotate-xor on a 4×4 u32 state, so
it vectorizes over 64-byte blocks exactly like ops/highwayhash_jax.py
vectorizes over hash lanes — the 16 state words become 16 (B, nblocks)
u32 planes and the 20 rounds run as whole-array ops, one batch of
erasure blocks per launch.

Shapes follow the package discipline of features/crypto.py: each batch
row carries P packages of ``pkg_bytes`` plaintext; row i, package p
encrypts under nonce ``nonces[i, p]`` with the block counter restarting
at 1 inside every package (counter 0 is the package's Poly1305 one-time
key, derived HOST-side — tags never launder through this kernel).

Byte-identity oracle: ops/chacha20_ref.keystream / xor_stream
(tests/test_chacha.py pins both against the RFC 8439 vectors and each
other). Like the other ops kernels this module computes only what it is
handed — keys and nonces arrive as pre-derived word arrays from
features/crypto.py, which owns ALL nonce derivation (crypto-hygiene
lint).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .chacha20_ref import _CONST, _QROUNDS

__all__ = ["keystream_u8", "keystream_xor", "xor_packages"]


def _qr(x: list, a: int, b: int, c: int, d: int) -> None:
    def rotl(v, n):
        return (v << jnp.uint32(n)) | (v >> jnp.uint32(32 - n))
    x[a] = x[a] + x[b]
    x[d] = rotl(x[d] ^ x[a], 16)
    x[c] = x[c] + x[d]
    x[b] = rotl(x[b] ^ x[c], 12)
    x[a] = x[a] + x[b]
    x[d] = rotl(x[d] ^ x[a], 8)
    x[c] = x[c] + x[d]
    x[b] = rotl(x[b] ^ x[c], 7)


def _keystream_words(keys: jax.Array, nonces: jax.Array,
                     nblk: int, pkg_blocks: int) -> jax.Array:
    """(B, 8) key words + (B, P, 3) nonce words -> (B, nblk, 16) u32
    output state words (rounds + feed-forward), counter restarting at 1
    per package."""
    b = keys.shape[0]
    pidx = np.arange(nblk) // pkg_blocks            # static gather map
    ctr = jnp.asarray(1 + np.arange(nblk) % pkg_blocks, jnp.uint32)
    bn = nonces[:, pidx, :]                          # (B, nblk, 3)
    init = [jnp.broadcast_to(jnp.uint32(int(_CONST[i])), (b, nblk))
            for i in range(4)]
    init += [jnp.broadcast_to(keys[:, i:i + 1], (b, nblk))
             for i in range(8)]
    init += [jnp.broadcast_to(ctr[None, :], (b, nblk))]
    init += [bn[:, :, i] for i in range(3)]
    state = jnp.stack(init, axis=0)                  # (16, B, nblk)

    # one double round (8 quarter rounds) per fori_loop step: unrolling
    # all 10 inflates the graph ~1600 sequential ops and costs ~17 s of
    # XLA compile per shape; the loop body compiles once
    def _double_round(_, st):
        x = [st[i] for i in range(16)]
        for a, b_, c, d in _QROUNDS:
            _qr(x, a, b_, c, d)
        return jnp.stack(x, axis=0)

    out = jax.lax.fori_loop(0, 10, _double_round, state) + state
    return jnp.moveaxis(out, 0, -1)                  # (B, nblk, 16)


def keystream_u8(keys: jax.Array, nonces: jax.Array, length: int,
                 pkg_bytes: int) -> jax.Array:
    """(B, length) u8 keystream bytes — length = P·pkg_bytes, both
    64-byte multiples. The traced core the fused pipeline steps splice
    into their own jit programs (models/pipeline.sse_put_step XORs this
    against staged plaintext before the RS matmul ever runs)."""
    if pkg_bytes % 64 or length % pkg_bytes:
        raise ValueError("package length must be a 64-byte multiple")
    b = keys.shape[0]
    nblk = length // 64
    words = _keystream_words(jnp.asarray(keys, jnp.uint32),
                             jnp.asarray(nonces, jnp.uint32),
                             nblk, pkg_bytes // 64)
    # little-endian serialization: (B, nblk, 16) u32 -> (B, L) u8
    shifts = jnp.asarray([0, 8, 16, 24], jnp.uint32)
    return ((words[..., None] >> shifts) & jnp.uint32(0xFF)
            ).astype(jnp.uint8).reshape(b, length)


@functools.partial(jax.jit, static_argnums=(3,))
def keystream_xor(data: jax.Array, keys: jax.Array, nonces: jax.Array,
                  pkg_bytes: int) -> jax.Array:
    """(B, P·pkg_bytes) u8 ⊕ per-package ChaCha20 keystreams.

    data:   (B, L) uint8 with L = P * pkg_bytes (pad partial tails with
            anything — the caller slices the real length back out).
    keys:   (B, 8) uint32 — per-row key words (rows from different
            objects coalesce into one launch carrying their own keys).
    nonces: (B, P, 3) uint32 — per-row, per-package nonce words.
    Returns (B, L) uint8 ciphertext (XOR: the same call deciphers).
    """
    b, length = data.shape
    ks = keystream_u8(keys, nonces, length, pkg_bytes)
    return jnp.asarray(data, jnp.uint8) ^ ks


def xor_packages(rows: np.ndarray, keys: np.ndarray,
                 nonces: np.ndarray) -> np.ndarray:
    """Host wrapper for the GET decipher batch: (N, Lp) u8 rows (one
    package each, zero-padded to a 64-byte multiple), (N, 8) key words,
    (N, 3) nonce words -> (N, Lp) u8 in one launch."""
    return np.asarray(keystream_xor(rows, keys, nonces[:, None, :],
                                    rows.shape[1]))
