"""TPU Reed-Solomon kernels: GF(2^8) coding as binary matmul on the MXU.

Design (TPU-first, not a port):

The reference's hot loop multiplies shard bytes by a constant GF(2^8) matrix
using SIMD table lookups (its codec library's AVX2 4-bit-table kernels).
Table lookups are gather-shaped — hostile to the MXU. Instead we use the
fact that multiplication by a constant c in GF(2^8) is *linear over GF(2)*:
there is an 8x8 bit-matrix B_c with bits(c*x) = B_c bits(x) (mod 2).

So the whole (m x k) GF(2^8) coding matrix expands into an (8m x 8k) 0/1
matrix M2 (ops/gf256.expand_to_gf2), and a block of k shards expands into a
(8k x S) 0/1 matrix of bit-planes. Then

    parity_bits = (M2 @ data_bits) mod 2

is one dense matmul — exactly MXU-shaped, batched over blocks with vmap.
XOR-accumulate == integer-accumulate + mod 2, and the contraction length
(8k <= 128 for k <= 16) keeps every partial sum < 2^8, exactly representable
in bf16/f32 accumulation.

Encode, reconstruct, and heal are all the *same* kernel with a different
matrix (parity rows / inverted submatrix / missing-row recovery matrix), so
one compiled program serves PutObject, GetObject-with-missing-shards, and
the healing scanner. Matrices are tiny (<= 128x128) and cached on device.

Two implementations:
  * `gf_matmul_xla`   — pure jnp; XLA fuses unpack/matmul/pack. Baseline.
  * `gf_matmul_pallas`— fused Pallas kernel: bytes stay in VMEM, bit-planes
    never touch HBM. (ops/rs_pallas.py)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import rs_matrix


def _bit_expand_matrix(m: np.ndarray) -> jnp.ndarray:
    """(r,k) GF(2^8) matrix -> (8r, 8k) bf16 0/1 matrix on device."""
    from . import gf256
    return jnp.asarray(gf256.expand_to_gf2(m), dtype=jnp.bfloat16)


def unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(..., k, S) uint8 -> (..., 8k, S) bit-planes, bit p of byte i at row
    8i+p (LSB-first to match gf256.expand_to_gf2 layout)."""
    k = x.shape[-2]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(*x.shape[:-2], k * 8, x.shape[-1])


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., 8r, S) 0/1 uint8 -> (..., r, S) bytes (LSB-first)."""
    r8 = bits.shape[-2]
    r = r8 // 8
    b = bits.reshape(*bits.shape[:-2], r, 8, bits.shape[-1])
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return (b * weights[None, :, None]).sum(axis=-2, dtype=jnp.uint8)


def gf_matmul_xla(m2: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Apply a bit-expanded GF matrix to shard bytes.

    m2:   (8r, 8k) bf16 0/1 — from _bit_expand_matrix
    data: (..., k, S) uint8 shard bytes (batch dims leading)
    ->    (..., r, S) uint8 output shard bytes
    """
    bits = unpack_bits(data).astype(jnp.bfloat16)
    # contraction over 8k (<=128): exact in f32 accumulation
    acc = jnp.einsum(
        "rc,...cs->...rs", m2, bits,
        preferred_element_type=jnp.float32)
    out_bits = acc.astype(jnp.int32) & 1
    return pack_bits(out_bits.astype(jnp.uint8))


# ---------------------------------------------------------------------------
# Public codec ops (jitted, batched)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _encode_impl(data: jnp.ndarray, k: int, m: int, use_pallas: bool) -> jnp.ndarray:
    pm = rs_matrix.parity_matrix(k, m)
    if use_pallas:
        from . import rs_pallas
        parity = rs_pallas.gf_matmul_pallas(pm, data)
    else:
        parity = gf_matmul_xla(_bit_expand_matrix(pm), data)
    return jnp.concatenate([data, parity], axis=-2)


def encode(data, data_shards: int, parity_shards: int, *,
           use_pallas: bool | None = None) -> jax.Array:
    """Batched RS encode.

    data: (B, k, S) or (k, S) uint8 data shards (device or host array).
    Returns (B, n, S) / (n, S) with parity appended — byte-identical to the
    host oracle (rs_ref.encode).
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    if use_pallas is None:
        use_pallas = default_use_pallas()
    return _encode_impl(data, data_shards, parity_shards, use_pallas)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _apply_matrix_impl(matrix_bits: jnp.ndarray, shards: jnp.ndarray,
                       r: int, k: int, use_pallas: bool) -> jnp.ndarray:
    m2 = matrix_bits.astype(jnp.bfloat16)
    if use_pallas:
        from . import rs_pallas
        return rs_pallas.gf_matmul_pallas_dev(m2, shards, r, k)
    return gf_matmul_xla(m2, shards)


def apply_matrix(matrix: np.ndarray, shards, *,
                 use_pallas: bool | None = None) -> jax.Array:
    """out = matrix (x) shards over GF(2^8), batched.

    matrix: (r, k) uint8 host matrix; shards: (..., k, S) uint8.
    The generic op behind reconstruct and heal.
    """
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    if use_pallas is None:
        use_pallas = default_use_pallas()
    m2 = _bit_expand_cached(matrix.tobytes(), matrix.shape)
    return _apply_matrix_impl(m2, shards, matrix.shape[0], matrix.shape[1],
                              use_pallas)


@functools.lru_cache(maxsize=4096)
def _bit_expand_cached(matrix_bytes: bytes, shape: tuple[int, int]) -> np.ndarray:
    """Host-side cache of the GF(2) expansion. Returns numpy (never a device
    array: caching a tracer-stage device constant would leak tracers)."""
    from . import gf256
    m = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(shape)
    return gf256.expand_to_gf2(m)


def reconstruct_data(shards, present_mask: int, data_shards: int,
                     parity_shards: int, *, use_pallas: bool | None = None
                     ) -> jax.Array:
    """Rebuild all k data shards from k survivors.

    shards: (..., k, S) uint8 — the *first k present* shards in index order
    (rs_matrix.decode_matrix's `used` tuple gives the order the caller must
    stack them in).
    """
    d, _used = rs_matrix.decode_matrix(data_shards, parity_shards, present_mask)
    return apply_matrix(np.asarray(d), shards, use_pallas=use_pallas)


def recover_missing(shards, present_mask: int, data_shards: int,
                    parity_shards: int, *, use_pallas: bool | None = None
                    ) -> jax.Array:
    """Produce exactly the missing shards (data+parity) from k survivors —
    the heal kernel: one matmul instead of decode-then-reencode."""
    r, _used, _missing = rs_matrix.recover_matrix(
        data_shards, parity_shards, present_mask)
    return apply_matrix(np.asarray(r), shards, use_pallas=use_pallas)


_DEFAULT_USE_PALLAS: bool | None = None


def default_use_pallas() -> bool:
    """Pallas path on real TPU; XLA path on CPU (tests / virtual mesh)."""
    global _DEFAULT_USE_PALLAS
    if _DEFAULT_USE_PALLAS is None:
        try:
            _DEFAULT_USE_PALLAS = jax.devices()[0].platform == "tpu"
        except Exception:
            _DEFAULT_USE_PALLAS = False
    return _DEFAULT_USE_PALLAS


def set_default_use_pallas(v: bool | None) -> None:
    global _DEFAULT_USE_PALLAS
    _DEFAULT_USE_PALLAS = v
