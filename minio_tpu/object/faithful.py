"""Version-faithful object writes through the public layer verbs.

One home for the "replay a version EXACTLY" discipline that three
planes share — the rebalance pool move pioneered it, the replication
apply and the tier restore now ride the same helper:

  * identity (version id, mod time, etag) is preserved via the
    engine's explicit-identity write forms (``PutOptions.mod_time``,
    ``put_delete_marker``, ``complete_multipart_upload``'s
    version-faithful kwargs);
  * **part boundaries survive**: a multipart object replays through a
    real multipart session (one ``put_object_part`` per source part),
    so the committed part list matches the source and the recomputed
    multipart etag (md5-of-part-md5s ``-N``) equals the source etag by
    construction — a remote site's multipart ETag can be compared
    against the origin byte-for-byte;
  * a transitioned zero-data stub replays as METADATA
    (``put_stub_version``) — never a 0-byte data object;
  * delete markers replay with their version id, mod time and
    replication-origin metadata.

The wire form (:class:`VersionSpec`) is a plain dict round-trip so the
replication HTTP client can carry it in one header.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from ..storage.datatypes import (ObjectInfo, ObjectPartInfo,
                                 is_restored, is_transitioned)
from . import api_errors
from .engine import PutOptions
from .multipart import CompletePart


@dataclasses.dataclass
class VersionSpec:
    """Everything needed to re-create one object version elsewhere,
    minus the bytes themselves."""
    version_id: str = ""
    mod_time: float = 0.0
    etag: str = ""
    size: int = 0
    delete_marker: bool = False
    # user metadata + content-type/content-encoding, internal keys
    # (transition pointers, replication origin) included
    metadata: dict = dataclasses.field(default_factory=dict)
    # [(number, size, actual_size, etag)] — empty/one entry = single part
    parts: list = dataclasses.field(default_factory=list)

    @property
    def transitioned_stub(self) -> bool:
        return is_transitioned(self.metadata) \
            and not is_restored(self.metadata)

    def to_dict(self) -> dict:
        return {"v": self.version_id, "t": self.mod_time, "e": self.etag,
                "s": self.size, "dm": self.delete_marker,
                "md": dict(self.metadata),
                "p": [list(p) for p in self.parts]}

    @classmethod
    def from_dict(cls, d: dict) -> "VersionSpec":
        return cls(version_id=str(d.get("v", "") or ""),
                   mod_time=float(d.get("t", 0.0) or 0.0),
                   etag=str(d.get("e", "") or ""),
                   size=int(d.get("s", 0) or 0),
                   delete_marker=bool(d.get("dm", False)),
                   metadata=dict(d.get("md") or {}),
                   parts=[tuple(p) for p in (d.get("p") or [])])


def spec_of(info: ObjectInfo) -> VersionSpec:
    """The replayable identity of one version's ObjectInfo."""
    md = dict(info.user_defined or {})
    if info.content_type:
        md["content-type"] = info.content_type
    if info.content_encoding:
        md["content-encoding"] = info.content_encoding
    parts = [(p.number, p.size,
              p.actual_size if p.actual_size >= 0 else p.size, p.etag)
             for p in (info.parts or [])]
    return VersionSpec(version_id=info.version_id or "",
                       mod_time=info.mod_time, etag=info.etag,
                       size=info.size,
                       delete_marker=bool(info.delete_marker),
                       metadata=md, parts=parts)


class _SegmentReader:
    """Expose exactly `limit` bytes of an underlying reader as one
    part's stream (the multipart replay carves the concatenated source
    stream along the recorded part boundaries)."""

    def __init__(self, inner, limit: int):
        self.inner = inner
        self.remaining = limit

    def read(self, n: int = -1) -> bytes:
        if self.remaining <= 0:
            return b""
        want = self.remaining if n < 0 else min(n, self.remaining)
        chunk = self.inner.read(want)
        self.remaining -= len(chunk)
        return chunk


def stub_object_info(bucket: str, name: str, spec: VersionSpec
                     ) -> ObjectInfo:
    """ObjectInfo form of a transitioned stub spec — the
    put_stub_version input (geometry is re-minted by the target set)."""
    md = dict(spec.metadata)
    return ObjectInfo(
        bucket=bucket, name=name, mod_time=spec.mod_time,
        size=spec.size, etag=spec.etag, version_id=spec.version_id,
        content_type=md.pop("content-type", ""),
        content_encoding=md.pop("content-encoding", ""),
        user_defined=md,
        parts=[ObjectPartInfo(number=n, size=s, actual_size=a, etag=e)
               for n, s, a, e in spec.parts])


def replay_version(layer, bucket: str, name: str, spec: VersionSpec,
                   reader=None,
                   reader_factory: Optional[Callable] = None,
                   conflict_gate: Optional[bool] = None) -> ObjectInfo:
    """Write one version at `layer` with full fidelity. `reader` (or
    the lazily-invoked `reader_factory`) supplies the version's stored
    bytes for data versions; markers and transitioned stubs need none.

    `conflict_gate` controls the atomic unversioned last-writer-wins
    commit gate (PutOptions.if_none_newer): default None applies it to
    every unversioned data replay (the replication-apply contract); a
    caller legitimately REWRITING the same identity in place — the tier
    restore over its own stub — passes False. Raises
    ReplayEtagMismatch when a replay's recomputed etag disagrees with
    the spec (bytes corrupted in transit)."""
    gate = (not spec.version_id) if conflict_gate is None \
        else conflict_gate
    md = dict(spec.metadata)
    if spec.delete_marker:
        return layer.put_delete_marker(bucket, name, spec.version_id,
                                       spec.mod_time, md)
    if spec.transitioned_stub:
        # metadata-only: the remote tier copy stays where it is; the
        # target must never store (or serve) a 0-byte data object
        return layer.put_stub_version(bucket, name,
                                      stub_object_info(bucket, name, spec),
                                      if_none_newer=gate)
    if reader is None:
        if reader_factory is None:
            raise ValueError("data version replay needs a reader")
        reader = reader_factory()
    if len(spec.parts) > 1:
        return _replay_multipart(layer, bucket, name, spec, reader, md,
                                 gate)
    opts = PutOptions(metadata={**md, "etag": spec.etag},
                      version_id=spec.version_id,
                      versioned=bool(spec.version_id),
                      mod_time=spec.mod_time,
                      # unversioned slot: atomic last-writer-wins under
                      # the engine's write lock (a concurrent client
                      # write must never be clobbered by an older
                      # replica — PreConditionFailed instead)
                      if_none_newer=gate)
    return layer.put_object(bucket, name, reader, spec.size, opts)


class ReplayEtagMismatch(api_errors.ObjectApiError):
    """Replayed bytes don't hash to the source version's etag."""


def _replay_multipart(layer, bucket: str, name: str, spec: VersionSpec,
                      reader, md: dict, gate: bool = False) -> ObjectInfo:
    opts = PutOptions(metadata=md, versioned=bool(spec.version_id))
    upload_id = layer.new_multipart_upload(bucket, name, opts)
    try:
        completes = []
        for number, size, _actual, part_etag in sorted(spec.parts):
            pi = layer.put_object_part(bucket, name, upload_id, number,
                                       _SegmentReader(reader, size), size)
            if part_etag and pi.etag != part_etag:
                raise ReplayEtagMismatch(
                    f"{bucket}/{name} part {number}: got {pi.etag}, "
                    f"want {part_etag}")
            completes.append(CompletePart(number, pi.etag))
        info = layer.complete_multipart_upload(
            bucket, name, upload_id, completes,
            version_id=spec.version_id, mod_time=spec.mod_time,
            # the unversioned slot takes the same atomic conflict gate
            # the single-part replay uses
            if_none_newer=gate)
    except Exception:
        try:
            layer.abort_multipart_upload(bucket, name, upload_id)
        except api_errors.ObjectApiError:
            pass
        raise
    if spec.etag and info.etag != spec.etag:
        raise ReplayEtagMismatch(
            f"{bucket}/{name}: multipart etag {info.etag} != source "
            f"{spec.etag}")
    return info
