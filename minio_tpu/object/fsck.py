"""fsck — the boot-time / on-demand consistency auditor.

A crash at any registered crashpoint (utils/crashpoint.py) leaves one
of a small set of on-disk inconsistency classes behind: staged tmp
garbage, a data dir no xl.meta references, an object whose xl.meta
landed on fewer drives than it should, metacache segments without a
manifest, a torn registry/checkpoint JSON on one pool, a multipart
session that was consumed by a migration, a tier stub whose remote
copy is gone. This module walks EVERY pool and classifies what it
finds:

  * ``repairable`` — fed straight to the existing repair machinery
    (per-object heal, orphan/tmp deletion, manifest drop → walk
    rebuild, registry rewrite-from-best-copy) when ``repair=True``;
  * ``lost`` — data no machinery can recover (shards below the data
    quorum, a stub whose remote tier object is gone when even the
    stub metadata was asked to be kept) — reported, never silently
    dropped.

Surfaces: ``GET/POST /minio/admin/v3/fsck`` (POST repairs),
``madmin.fsck()``, the ``fsck`` CLI verb, and cluster boot under
``MINIO_TPU_FSCK_BOOT=on``. Every finding and repair counts in
``minio_tpu_fsck_findings_total{class}`` /
``minio_tpu_fsck_repaired_total{class}`` — the per-class proof the
repair path ran that the crash harness asserts on.

The audit holds no long-lived locks: repairs go through the same
locked verbs (heal_object, delete_object) the foreground uses.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Iterable, List, Optional

from ..storage import errors as serr
from ..storage.datatypes import (TRANSITION_TIER_KEY,
                                 TRANSITIONED_OBJECT_KEY,
                                 is_restored, is_transitioned)
from ..storage.xl_storage import (MINIO_META_BUCKET,
                                  MINIO_META_MULTIPART_BUCKET,
                                  MINIO_META_TMP_BUCKET,
                                  XL_STORAGE_FORMAT_FILE, XLStorage)
from ..utils import atomicfile, eventlog, knobs, regfence, telemetry
from . import api_errors
from .metacache import manifest_key, mc_prefix

__all__ = ["Finding", "FsckReport", "run_fsck", "CLASSES"]

# every class fsck can report; the metrics/table vocabulary
CLASSES = (
    "meta_missing",            # xl.meta absent on some drives (quorum ok)
    "meta_below_quorum",       # too few xl.meta copies to read (dangling)
    "missing_shards",          # data dir absent on some meta-bearing drives
    "lost_data",               # data dirs below the decode quorum
    "orphan_data",             # data dir no version on any drive references
    "stale_tmp",               # staged 2-phase-commit leftovers
    "stale_multipart",         # consumed/torn multipart session dirs
    "orphan_metacache_segment",  # index segment no manifest references
    "broken_metacache_manifest",  # torn manifest / dangling segment refs
    "dangling_stub",           # transitioned stub whose remote is gone
    "torn_registry",           # unparseable registry/checkpoint JSON copy
    "origin_divergence",       # replication origin markers disagree
    "registry_epoch_fork",     # same epoch, divergent lineage (split brain)
)

# registry / checkpoint document prefixes audited per pool (the docs
# deliberately written to every pool — topology epochs, tier config,
# replication targets, rebalance/resync checkpoints)
REGISTRY_PREFIXES = ("topology/", "tier/", "replicate/", "qos/",
                     "notify/")

_REPL_ORIGIN_KEY = "X-Minio-Internal-replication-origin"


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_fsck_findings_total",
                    "fsck consistency findings by class"),
        reg.counter("minio_tpu_fsck_repaired_total",
                    "fsck findings repaired by class"),
    )


@dataclasses.dataclass
class Finding:
    cls: str
    pool: int
    bucket: str = ""
    object: str = ""
    detail: str = ""
    repairable: bool = True
    repaired: bool = False
    repair_error: str = ""
    # bound repair action (set by the auditor, run by repair_all)
    _repair: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False)

    def to_dict(self) -> dict:
        return {"class": self.cls, "pool": self.pool,
                "bucket": self.bucket, "object": self.object,
                "detail": self.detail, "repairable": self.repairable,
                "repaired": self.repaired,
                "repair_error": self.repair_error}


class FsckReport:
    def __init__(self) -> None:
        self.findings: List[Finding] = []
        self.started = time.time()
        self.duration_s = 0.0
        self.pools = 0
        self.objects_scanned = 0
        self.supported = True
        self.repair_ran = False

    def add(self, f: Finding) -> Finding:
        self.findings.append(f)
        _metrics()[0].inc(1, **{"class": f.cls})
        return f

    @property
    def unrepaired(self) -> List[Finding]:
        """Repairable findings whose repair has not (successfully)
        run, plus every lost finding — what the crash harness asserts
        is EMPTY after a repair pass."""
        return [f for f in self.findings if not f.repaired]

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.cls] = out.get(f.cls, 0) + 1
        return out

    def repaired_counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            if f.repaired:
                out[f.cls] = out.get(f.cls, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "supported": self.supported,
            "clean": self.clean,
            "repair": self.repair_ran,
            "pools": self.pools,
            "objects_scanned": self.objects_scanned,
            "duration_s": round(self.duration_s, 3),
            "counts": self.counts(),
            "repaired": self.repaired_counts(),
            "unrepaired": len(self.unrepaired),
            "findings": [f.to_dict() for f in self.findings],
        }


def _server_sets(object_layer):
    """Unwrap to the ErasureServerSets (through the read-cache
    wrapper); None for FS/gateway backends — fsck audits erasure
    layouts only."""
    for layer in (object_layer, getattr(object_layer, "inner", None)):
        if layer is not None and hasattr(layer, "server_sets") \
                and hasattr(layer, "topology"):
            return layer
    return None


def run_fsck(object_layer, repair: bool = False, tiers=None,
             buckets: Optional[Iterable[str]] = None,
             tmp_age_s: Optional[float] = None) -> FsckReport:
    """Audit every pool; with ``repair=True`` run each finding's
    repair action immediately (counters prove the path ran)."""
    report = FsckReport()
    ss = _server_sets(object_layer)
    if ss is None:
        report.supported = False
        return report
    if tmp_age_s is None:
        tmp_age_s = knobs.get_float("MINIO_TPU_FSCK_TMP_AGE_S")
    with telemetry.span("fsck.run", repair=repair):
        report.pools = len(ss.server_sets)
        want = set(buckets) if buckets else None
        try:
            all_buckets = [v.name for v in ss.list_buckets()]
        except api_errors.ObjectApiError:
            all_buckets = []
        _audit_registry_forks(report, ss)
        for p, pool in enumerate(ss.server_sets):
            _audit_registry_docs(report, ss, p, pool)
            _audit_tmp(report, p, pool, tmp_age_s)
            _audit_multipart(report, p, pool)
            for bucket in all_buckets:
                if want is not None and bucket not in want:
                    continue
                _audit_metacache(report, p, pool, bucket)
                for eng in pool.sets:
                    _audit_namespace(report, p, eng, bucket, tiers,
                                     tmp_age_s)
        if repair:
            report.repair_ran = True
            for f in report.findings:
                _run_repair(f)
        report.duration_s = time.time() - report.started
    eventlog.emit("fsck.complete", findings=len(report.findings),
                  repaired=sum(1 for f in report.findings if f.repaired),
                  unrepaired=len(report.unrepaired))
    if report.unrepaired:
        eventlog.emit("fsck.unrepaired",
                      findings=len(report.unrepaired))
    return report


def _run_repair(f: Finding) -> None:
    if not f.repairable or f._repair is None:
        return
    try:
        f._repair()
        f.repaired = True
        _metrics()[1].inc(1, **{"class": f.cls})
    except Exception as e:  # noqa: BLE001 — report, never abort the pass
        f.repair_error = repr(e)


# ---------------------------------------------------------------------------
# namespace walk: per-set object audit
# ---------------------------------------------------------------------------

def _live_disks(eng) -> list:
    return [d for d in eng.disks if d is not None and d.is_online()]


def _walk_object_paths(disks, bucket: str):
    """Union of object paths (dirs holding xl.meta) and bare data dirs
    across the set's drives, by recursive listing."""
    paths: set[str] = set()

    def walk(d, rel: str) -> None:
        try:
            entries = d.list_dir(bucket, rel)
        except serr.StorageError:
            return
        if XL_STORAGE_FORMAT_FILE in entries:
            paths.add(rel)
            return
        has_files = any(not e.endswith("/") for e in entries)
        subdirs = [e for e in entries if e.endswith("/")]
        if has_files and rel:
            # part files without xl.meta anywhere: an orphaned object
            # dir (meta deleted mid-crash) — surface it
            paths.add(rel)
            return
        for e in subdirs:
            sub = os.path.join(rel, e.rstrip("/")) if rel \
                else e.rstrip("/")
            walk(d, sub)

    for d in disks:
        walk(d, "")
    # a drive that lost its xl.meta walks INTO the object dir and
    # surfaces the data dir itself ("a/b/<uuid>") while a healthy
    # drive surfaces "a/b": keep only the ancestor — auditing the
    # descendant as its own object would misread committed data as an
    # orphan and reclaim it
    out: list[str] = []
    for rel in sorted(paths):
        if out and (rel + "/").startswith(out[-1] + "/"):
            continue
        out.append(rel)
    return out


def _audit_namespace(report: FsckReport, p: int, eng, bucket: str,
                     tiers, tmp_age: float) -> None:
    disks = _live_disks(eng)
    if not disks:
        return
    for path in _walk_object_paths(disks, bucket):
        report.objects_scanned += 1
        _audit_object(report, p, eng, disks, bucket, path, tiers,
                      tmp_age)


def _audit_object(report: FsckReport, p: int, eng, disks, bucket: str,
                  path: str, tiers, tmp_age: float) -> None:
    n = len(disks)
    per_disk_versions: list = []
    for d in disks:
        try:
            per_disk_versions.append(d.read_versions(bucket, path))
        except serr.StorageError:
            per_disk_versions.append(None)
    with_meta = sum(1 for v in per_disk_versions if v is not None)

    # union of versions (by version id) and referenced data dirs
    by_vid: dict = {}
    referenced: set[str] = set()
    for vers in per_disk_versions:
        for fi in vers or []:
            by_vid.setdefault(fi.version_id or "", []).append(fi)
            if fi.data_dir:
                referenced.add(fi.data_dir)

    if with_meta == 0:
        # a dir with files/data dirs but no readable xl.meta anywhere:
        # nothing references this data — reclaim it whole
        report.add(Finding(
            "orphan_data", p, bucket, path,
            detail="object dir with no readable xl.meta on any drive",
            _repair=_delete_on_all(disks, bucket, path)))
        return

    if with_meta < n:
        dangling = with_meta < n - eng.parity_shards
        report.add(Finding(
            "meta_below_quorum" if dangling else "meta_missing",
            p, bucket, path,
            detail=f"xl.meta on {with_meta}/{n} drives",
            _repair=_heal_versions(eng, bucket, path, by_vid)))

    # replication origin markers must agree per version across drives
    for vid, fis in by_vid.items():
        origins = {fi.metadata.get(_REPL_ORIGIN_KEY, "")
                   for fi in fis if fi.metadata}
        origins.discard("")
        if len(origins) > 1:
            report.add(Finding(
                "origin_divergence", p, bucket, path,
                detail=f"version {vid or 'null'}: origin markers "
                       f"{sorted(origins)}",
                _repair=_heal_versions(eng, bucket, path,
                                       {vid: fis})))

    # per-version data-dir presence
    dir_entries: list = []
    for d in disks:
        try:
            dir_entries.append(set(d.list_dir(bucket, path)))
        except serr.StorageError:
            dir_entries.append(set())
    for vid, fis in by_vid.items():
        fi = fis[0]
        if fi.deleted:
            continue
        if is_transitioned(fi.metadata or {}) \
                and not is_restored(fi.metadata or {}):
            # stubs hold no local shards (data_dir cleared): their
            # consistency question is whether the remote still exists
            _audit_stub(report, p, eng, bucket, path, fi, tiers)
            continue
        if not fi.data_dir:
            continue
        have = sum(1 for i, vers in enumerate(per_disk_versions)
                   if vers is not None
                   and fi.data_dir + "/" in dir_entries[i])
        if have >= with_meta:
            continue
        k = fi.erasure.data_blocks if fi.erasure else 1
        if have < k:
            report.add(Finding(
                "lost_data", p, bucket, path, repairable=False,
                detail=f"version {vid or 'null'}: data dir on "
                       f"{have}/{n} drives < decode quorum {k}"))
        else:
            report.add(Finding(
                "missing_shards", p, bucket, path,
                detail=f"version {vid or 'null'}: data dir on "
                       f"{have}/{n} drives",
                _repair=_heal_versions(eng, bucket, path, {vid: fis})))

    # orphan data dirs: present on a drive, referenced by no version
    # on ANY drive (the storage.rename_data.before_meta window); plus
    # write_atomic temp siblings (xl.meta.<hex>.tmp — a crash between
    # the temp write and the rename), age-gated like the tmp bucket
    for i, d in enumerate(disks):
        for e in dir_entries[i]:
            if e.endswith(".tmp"):
                if _older_than(d, bucket, f"{path}/{e}", tmp_age):
                    report.add(Finding(
                        "stale_tmp", p, bucket, f"{path}/{e}",
                        detail=f"atomic-commit temp sibling on {d}",
                        _repair=_delete_dir(d, bucket,
                                            f"{path}/{e}")))
                continue
            if not e.endswith("/"):
                continue
            dd = e.rstrip("/")
            if dd in referenced:
                continue
            report.add(Finding(
                "orphan_data", p, bucket, path,
                detail=f"data dir {dd} on {d} referenced by no "
                       "version",
                _repair=_delete_dir(d, bucket, f"{path}/{dd}")))


def _audit_stub(report: FsckReport, p: int, eng, bucket: str, path: str,
                fi, tiers) -> None:
    """Only a POSITIVE not-found from the tier backend classifies a
    stub as dangling: a transient head failure (tier restarting,
    network not up at boot) or an unmounted tier name is 'cannot
    check', never 'safe to drop' — the repair is an irreversible
    delete of the only reference to the remote data."""
    if tiers is None:
        return
    from ..tier.client import TierObjectNotFound
    tier = (fi.metadata or {}).get(TRANSITION_TIER_KEY, "")
    rkey = (fi.metadata or {}).get(TRANSITIONED_OBJECT_KEY, "")
    try:
        client = tiers.client(tier)
        client.head(rkey)
        return                              # remote intact
    except TierObjectNotFound:
        gone = f"remote object {rkey!r} missing on tier {tier!r}"
    except Exception:  # noqa: BLE001 — unreachable/unknown: skip the
        return         # stub this pass rather than risk dropping it
    vid = fi.version_id or ""

    def drop():
        eng.delete_object(bucket, path, version_id=vid)

    report.add(Finding(
        "dangling_stub", p, bucket, path,
        detail=f"{gone}; repair drops the stub version "
               f"{vid or 'null'} (data is unrecoverable)",
        _repair=drop))


def _heal_versions(eng, bucket: str, path: str, by_vid: dict):
    def heal():
        for vid in by_vid:
            eng.heal_object(bucket, path, version_id=vid or "")
    return heal


def _delete_dir(d, bucket: str, rel: str):
    def rm():
        try:
            d.delete_file(bucket, rel, recursive=True)
        except serr.FileNotFound:
            pass
    return rm


def _delete_on_all(disks, bucket: str, rel: str):
    def rm():
        for d in disks:
            try:
                d.delete_file(bucket, rel, recursive=True)
            except serr.StorageError:
                pass
    return rm


# ---------------------------------------------------------------------------
# tmp staging + multipart sessions (per pool)
# ---------------------------------------------------------------------------

def _audit_tmp(report: FsckReport, p: int, pool, tmp_age_s: float
               ) -> None:
    for eng in pool.sets:
        for d in _live_disks(eng):
            try:
                entries = d.list_dir(MINIO_META_TMP_BUCKET, "")
            except serr.StorageError:
                continue
            for e in entries:
                rel = e.rstrip("/")
                if not _older_than(d, MINIO_META_TMP_BUCKET, rel,
                                   tmp_age_s):
                    continue
                report.add(Finding(
                    "stale_tmp", p, MINIO_META_TMP_BUCKET, rel,
                    detail=f"staged write leftover on {d}",
                    _repair=_delete_dir(d, MINIO_META_TMP_BUCKET, rel)))


def _older_than(d, volume: str, rel: str, age_s: float) -> bool:
    """Age gate so an in-flight PUT's staging is never reaped: local
    drives stat the dir; remote drives only pass under an explicit
    age_s=0 (boot-time/harness mode — nothing can be in flight)."""
    if age_s <= 0:
        return True
    if not isinstance(d, XLStorage):
        return False
    try:
        st = os.stat(os.path.join(d.root, volume, rel))
    except OSError:
        return False
    return (time.time() - st.st_mtime) >= age_s


def _audit_multipart(report: FsckReport, p: int, pool) -> None:
    for eng in pool.sets:
        disks = _live_disks(eng)
        if not disks:
            continue
        sessions: dict[str, list] = {}
        for d in disks:
            try:
                shas = d.list_dir(MINIO_META_MULTIPART_BUCKET, "")
            except serr.StorageError:
                continue
            for sha in shas:
                try:
                    ids = d.list_dir(MINIO_META_MULTIPART_BUCKET,
                                     sha.rstrip("/"))
                except serr.StorageError:
                    continue
                for uid in ids:
                    path = f"{sha.rstrip('/')}/{uid.rstrip('/')}"
                    sessions.setdefault(path, [])
        for path in sorted(sessions):
            metas = []
            for d in disks:
                try:
                    metas.append(d.read_version(
                        MINIO_META_MULTIPART_BUCKET, path))
                except serr.StorageError:
                    pass
            if not metas:
                report.add(Finding(
                    "stale_multipart", p, MINIO_META_MULTIPART_BUCKET,
                    path,
                    detail="session dir with no readable session meta",
                    _repair=_delete_on_all(
                        disks, MINIO_META_MULTIPART_BUCKET, path)))
            elif any((fi.metadata or {}).get("x-minio-internal-migrated")
                     for fi in metas):
                report.add(Finding(
                    "stale_multipart", p, MINIO_META_MULTIPART_BUCKET,
                    path,
                    detail="consumed (migrated) session leftover",
                    _repair=_delete_on_all(
                        disks, MINIO_META_MULTIPART_BUCKET, path)))


# ---------------------------------------------------------------------------
# metacache segments/manifest (per pool, per bucket)
# ---------------------------------------------------------------------------

def _list_meta_keys(pool, prefix: str) -> list[str]:
    keys: list[str] = []
    marker = ""
    while True:
        objs, _prefixes, truncated = pool.list_objects(
            MINIO_META_BUCKET, prefix=prefix, marker=marker,
            max_keys=1000)
        for o in objs:
            keys.append(o.name)
        if not truncated or not objs:
            return keys
        marker = objs[-1].name


def _get_pool_bytes(pool, key: str) -> bytes:
    _info, stream = pool.get_object(MINIO_META_BUCKET, key)
    try:
        return b"".join(stream)
    finally:
        close = getattr(stream, "close", None)
        if close:
            close()


def _metacache_state(pool, bucket: str):
    """One consistent-ish snapshot: (broken_reason, gen, referenced,
    all_keys). Raises ObjectApiError upward only for the key listing."""
    prefix = mc_prefix(bucket)
    keys = set(_list_meta_keys(pool, prefix))
    mkey = manifest_key(bucket)
    referenced: set[str] = set()
    broken, gen = "", -1
    if mkey in keys:
        try:
            doc = atomicfile.load_json_doc(_get_pool_bytes(pool, mkey))
        except api_errors.ObjectApiError:
            doc = None
        if doc is None:
            broken = "manifest unreadable/torn"
        else:
            gen = int(doc.get("gen", -1) or -1)
            try:
                referenced = {s["key"] for s in doc.get("segments", [])}
            except (TypeError, KeyError):
                broken = "manifest segment list malformed"
            else:
                missing = referenced - keys
                if missing:
                    broken = (f"manifest references {len(missing)} "
                              "missing segment(s)")
    return broken, gen, referenced, keys


def _audit_metacache(report: FsckReport, p: int, pool, bucket: str
                     ) -> None:
    # a LIVE manager may be persisting a new generation while we read
    # (segments land before their manifest; old segments are reclaimed
    # after): require TWO consecutive agreeing snapshots before
    # reporting, so an in-flight persist never reads as damage
    try:
        prev = _metacache_state(pool, bucket)
        settled = not prev[0] and not (prev[3] - prev[2]
                                       - {manifest_key(bucket)})
        for _ in range(3):
            if settled:
                break
            time.sleep(0.15)
            cur = _metacache_state(pool, bucket)
            settled = cur == prev
            prev = cur
    except api_errors.ObjectApiError:
        return
    if not settled:
        # still changing after every retry: a live persist is mid-
        # flight — skip this bucket this pass; reporting (and under
        # repair, deleting) a moving target would damage healthy state
        return
    broken, _gen, referenced, keys = prev
    mkey = manifest_key(bucket)
    if broken:
        drop = sorted((keys | referenced) - {mkey}) + [mkey]

        def rm(drop=drop):
            # drop manifest + segments: the next manager start walk-
            # rebuilds (a missing manifest is the SUPPORTED cold path)
            for k in drop:
                try:
                    pool.delete_object(MINIO_META_BUCKET, k)
                except api_errors.ObjectApiError:
                    pass

        report.add(Finding(
            "broken_metacache_manifest", p, bucket, mkey,
            detail=broken + "; repair drops the persisted index "
                   "(walk rebuild)",
            _repair=rm))
        return
    for k in sorted(keys - referenced - {mkey}):
        def rm_one(k=k):
            try:
                pool.delete_object(MINIO_META_BUCKET, k)
            except api_errors.ObjectApiError:
                pass
        report.add(Finding(
            "orphan_metacache_segment", p, bucket, k,
            detail="segment object referenced by no manifest",
            _repair=rm_one))


# ---------------------------------------------------------------------------
# registry / checkpoint documents (per pool)
# ---------------------------------------------------------------------------

def _audit_registry_docs(report: FsckReport, ss, p: int, pool) -> None:
    for prefix in REGISTRY_PREFIXES:
        try:
            keys = _list_meta_keys(pool, prefix)
        except api_errors.ObjectApiError:
            continue
        for key in keys:
            try:
                raw = _get_pool_bytes(pool, key)
            except api_errors.ObjectApiError:
                continue
            if atomicfile.load_json_doc(raw) is not None:
                continue
            repair = _registry_repair(ss, pool, p, key)
            report.add(Finding(
                "torn_registry", p, MINIO_META_BUCKET, key,
                detail="unparseable registry/checkpoint JSON"
                       + ("; repair rewrites from the best pool copy"
                          if repair else "; no healthy copy — repair "
                          "deletes the torn doc (loaders fall back)"),
                _repair=repair or _registry_drop(pool, key)))


def _registry_repair(ss, pool, p: int, key: str):
    """A parseable copy from ANY other pool wins (the epoch loaders
    already pick highest-epoch across pools — convergence, not
    authority, is the goal here)."""
    for q, other in enumerate(ss.server_sets):
        if q == p:
            continue
        try:
            raw = _get_pool_bytes(other, key)
        except api_errors.ObjectApiError:
            continue
        if atomicfile.load_json_doc(raw) is None:
            continue

        def rewrite(raw=raw):
            pool.put_object(MINIO_META_BUCKET, key, raw)
        return rewrite
    return None


def _registry_drop(pool, key: str):
    def rm():
        try:
            pool.delete_object(MINIO_META_BUCKET, key)
        except api_errors.ObjectApiError:
            pass
    return rm


def _audit_registry_forks(report: FsckReport, ss) -> None:
    """Split-brain detection across POOL copies of each lineage-fenced
    registry doc: two copies claiming the same epoch with different
    lineage hashes can only come from divergent histories (both sides
    of a partition committed "the next epoch"). The epoch loaders pick
    a deterministic winner but never merge — this audit is where the
    fork becomes VISIBLE, and the repair is the explicit convergence:
    the highest (epoch, writer, lineage) doc wins everywhere, each
    losing copy is archived to ``<key>.fork-<lineage>`` in its pool
    (never deleted — an operator can diff what the losing side
    committed), then every pool is rewritten with the winner."""
    pools = ss.server_sets
    if len(pools) < 2:
        return
    keys: set[str] = set()
    for pool in pools:
        for prefix in REGISTRY_PREFIXES:
            try:
                keys.update(_list_meta_keys(pool, prefix))
            except api_errors.ObjectApiError:
                continue
    for key in sorted(keys):
        if ".fork-" in key:
            continue                # archived losers are not re-audited
        copies: list = []           # (pool_idx, doc, raw)
        for q, pool in enumerate(pools):
            try:
                raw = _get_pool_bytes(pool, key)
            except api_errors.ObjectApiError:
                continue
            doc = atomicfile.load_json_doc(raw)
            if doc is None:         # torn copies: the torn_registry class
                continue
            copies.append((q, doc, raw))
        docs = [doc for _q, doc, _raw in copies]
        forks = regfence.find_forks(docs)
        if not forks:
            continue
        winner = regfence.pick_best(docs)
        win_lineage = str(winner.get("lineage", ""))
        win_raw = next(raw for _q, doc, raw in copies if doc is winner)
        forked = {str(d.get("lineage", ""))
                  for pair in forks for d in pair}
        losers = []                 # (pool_idx, lineage, raw)
        seen: set = set()
        for q, doc, raw in copies:
            lin = str(doc.get("lineage", ""))
            if lin == win_lineage or lin not in forked \
                    or (q, lin) in seen:
                continue
            seen.add((q, lin))
            losers.append((q, lin, raw))
        if not losers:
            continue

        def converge(key=key, losers=losers, win_raw=win_raw):
            for q, lin, raw in losers:
                pools[q].put_object(MINIO_META_BUCKET,
                                    f"{key}.fork-{lin}", raw)
            for pool in pools:
                pool.put_object(MINIO_META_BUCKET, key, win_raw)

        report.add(Finding(
            "registry_epoch_fork", losers[0][0], MINIO_META_BUCKET, key,
            detail=f"epoch {int(winner.get('epoch', 0))} fork: winner "
                   f"lineage {win_lineage} (writer "
                   f"{winner.get('writer', '')!r}), "
                   f"{len(losers)} losing cop"
                   f"{'y' if len(losers) == 1 else 'ies'} on pool(s) "
                   f"{sorted({q for q, _l, _r in losers})}; repair "
                   "archives losers and converges every pool on the "
                   "winner",
            _repair=converge))
