"""Disk cache ObjectLayer wrapper (cmd/disk-cache.go cacheObjects).

GETs are served from a local cache directory when the cached copy's
ETag still matches the backend; misses read through and populate.
Parity with the reference's cache depth (VERDICT r4 #4):

  * **Block-framed entries** — cache files store ``[digest || block]``
    frames (the cache-side bitrot framing of
    cmd/disk-cache-backend.go:573), so hits verify INCREMENTALLY,
    block by block, as bytes stream out — no full-object hash pass
    before the first byte, and a corrupt block is detected exactly
    where it sits.
  * **Range entries** — a ranged miss caches just the block-aligned
    span it read (cmd/disk-cache.go range caching); later ranged hits
    serve from any cached span that covers them. Whole-object entries
    are the special case covering [0, size).
  * **Streamed fills** — population tees the backend stream into the
    entry file while yielding to the client: constant memory for any
    object size, and a partial fill (client hangup, backend error) is
    discarded, never served.
  * **Watermark LRU** — usage above HIGH_WATERMARK purges
    least-recently-USED entries down to LOW_WATERMARK
    (cmd/disk-cache.go:271 purge semantics); every hit refreshes the
    entry's clock.

Mutations through the wrapper invalidate the whole entry.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import shutil
import threading
from typing import Iterator, Optional

from .. import bitrot as bitrot_mod
from . import api_errors
from .engine import GetOptions, PutOptions

DEFAULT_BUDGET = 1 << 30
HIGH_WATERMARK = 0.9
LOW_WATERMARK = 0.7
MAX_ENTRY_FRACTION = 0.1
CACHE_BLOCK = 1 << 20                 # frame payload size
_ALGO = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256
_DIG = 32                             # digest bytes per frame
_FILL_SEQ = itertools.count()         # unique in-flight fill suffixes


class CacheObjects:
    """ObjectLayer wrapper with a block-framed read cache on a local
    path."""

    def __init__(self, inner, cache_dir: str,
                 budget_bytes: int = DEFAULT_BUDGET,
                 block_size: int = CACHE_BLOCK):
        self.inner = inner
        self.dir = os.path.abspath(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.budget = budget_bytes
        self.block = block_size
        self.hits = 0
        self.misses = 0
        self._mu = threading.Lock()

    # everything not overridden passes straight through
    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- entry layout ------------------------------------------------------

    def _entry_dir(self, bucket: str, key: str) -> str:
        h = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.dir, h[:2], h)

    def _load_entry(self, bucket: str, key: str) -> Optional[dict]:
        d = self._entry_dir(bucket, key)
        try:
            with open(os.path.join(d, "meta.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_meta(self, d: str, meta: dict) -> None:
        tmp = os.path.join(d, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, "meta.json"))

    def _touch(self, bucket: str, key: str) -> None:
        """Refresh the entry's LRU clock (meta mtime is the clock)."""
        try:
            os.utime(os.path.join(self._entry_dir(bucket, key),
                                  "meta.json"))
        except OSError:
            pass

    def _drop(self, bucket: str, key: str) -> None:
        shutil.rmtree(self._entry_dir(bucket, key), ignore_errors=True)

    def _drop_range(self, bucket: str, key: str, fname: str) -> None:
        """Remove one corrupt cache file and its meta reference."""
        d = self._entry_dir(bucket, key)
        with self._mu:
            meta = self._load_entry(bucket, key)
            try:
                os.remove(os.path.join(d, fname))
            except OSError:
                pass
            if meta is not None:
                meta["ranges"] = [r for r in meta.get("ranges", [])
                                  if r["file"] != fname]
                self._write_meta(d, meta)

    # -- framed file I/O ---------------------------------------------------

    def _read_frames(self, path: str, file_start: int, offset: int,
                     length: int) -> Iterator[bytes]:
        """Yield verified payload for [offset, offset+length) out of a
        framed cache file whose payload begins at absolute object
        offset file_start. Raises bitrot mismatch BEFORE yielding the
        affected block."""
        rel = offset - file_start
        first = rel // self.block
        skip = rel - first * self.block
        remaining = length
        with open(path, "rb") as f:
            f.seek(first * (_DIG + self.block))
            while remaining > 0:
                digest = f.read(_DIG)
                block = f.read(self.block)
                if len(digest) < _DIG or not block:
                    raise api_errors.ObjectApiError(
                        "truncated cache frame")
                if bitrot_mod.hash_shard(block, _ALGO) != digest:
                    raise api_errors.ObjectApiError(
                        "cache bitrot mismatch")
                piece = block[skip:skip + remaining]
                skip = 0
                remaining -= len(piece)
                if piece:
                    yield piece

    # -- covering-span lookup ----------------------------------------------

    def _covering(self, meta: dict, start: int, end: int
                  ) -> Optional[dict]:
        """A cached range record covering [start, end), or None."""
        for r in meta.get("ranges", []):
            if r["start"] <= start and r["end"] >= end:
                return r
        return None

    # -- streamed fill -----------------------------------------------------

    def _fill_stream(self, bucket: str, key: str, info, stream,
                     file_start: int, span_len: int,
                     yield_from: int, yield_len: int
                     ) -> Iterator[bytes]:
        """Tee `stream` (payload of [file_start, file_start+span_len))
        into a framed cache file while yielding the requested
        [yield_from, yield_from+yield_len) sub-span. Constant memory;
        a partial fill is discarded in `finally`."""
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        fname = "data" if (file_start == 0
                           and span_len == info.size) else \
            f"r{file_start}"
        # unique per fill: concurrent threads filling the same range
        # must never share a tmp inode
        tmp = os.path.join(
            d, f"{fname}.tmp{os.getpid()}.{next(_FILL_SEQ)}")
        done = 0
        want_skip = yield_from - file_start
        want_left = yield_len
        completed = False
        try:
            with open(tmp, "wb") as out:
                buf = bytearray()
                for chunk in stream:
                    buf += chunk
                    while len(buf) >= self.block:
                        block = bytes(buf[:self.block])
                        del buf[:self.block]
                        out.write(bitrot_mod.hash_shard(block, _ALGO))
                        out.write(block)
                        done += len(block)
                        piece = block[max(want_skip, 0):]
                        want_skip -= len(block)
                        if piece and want_left > 0:
                            piece = piece[:want_left]
                            want_left -= len(piece)
                            yield piece
                if buf:
                    block = bytes(buf)
                    out.write(bitrot_mod.hash_shard(block, _ALGO))
                    out.write(block)
                    done += len(block)
                    piece = block[max(want_skip, 0):]
                    if piece and want_left > 0:
                        yield piece[:want_left]
            completed = done == span_len
        finally:
            if not completed:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            else:
                self._commit(bucket, key, info, fname, tmp,
                             file_start, file_start + span_len)

    def _commit(self, bucket: str, key: str, info, fname: str,
                tmp: str, start: int, end: int) -> None:
        """Publish a completed fill. The entry dir (or the tmp file)
        may have been rmtree'd by a concurrent purge/invalidation —
        losing the cache entry is fine; failing a client whose bytes
        all arrived is not."""
        d = self._entry_dir(bucket, key)
        try:
            self._commit_locked(bucket, key, info, fname, tmp, d,
                                start, end)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return
        self._purge_if_needed()

    def _commit_locked(self, bucket, key, info, fname, tmp, d,
                       start, end) -> None:
        with self._mu:
            meta = self._load_entry(bucket, key)
            if meta is None or meta.get("etag") != info.etag:
                # fresh entry (or a stale generation): ranges reset
                meta = {"etag": info.etag, "size": info.size,
                        "content_type": info.content_type,
                        "user_defined": dict(info.user_defined or {}),
                        "mod_time": info.mod_time, "ranges": []}
                for r in list(os.listdir(d)):
                    if r != "meta.json" and ".tmp" not in r:
                        try:
                            os.remove(os.path.join(d, r))
                        except OSError:
                            pass
            os.replace(tmp, os.path.join(d, fname))
            ranges = [r for r in meta.get("ranges", [])
                      if r["file"] != fname]
            ranges.append({"start": start, "end": end, "file": fname})
            meta["ranges"] = sorted(ranges, key=lambda r: r["start"])
            self._write_meta(d, meta)

    # -- LRU purge ---------------------------------------------------------

    def _usage(self) -> int:
        total = 0
        for root, _, files in os.walk(self.dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    def _purge_if_needed(self) -> None:
        with self._mu:
            usage = self._usage()
            if usage < self.budget * HIGH_WATERMARK:
                return
            entries = []               # (last_access, dir, bytes)
            for sub in os.listdir(self.dir):
                subdir = os.path.join(self.dir, sub)
                if not os.path.isdir(subdir):
                    continue
                for h in os.listdir(subdir):
                    d = os.path.join(subdir, h)
                    try:
                        atime = os.path.getmtime(
                            os.path.join(d, "meta.json"))
                    except OSError:
                        shutil.rmtree(d, ignore_errors=True)
                        continue
                    size = 0
                    for f in os.listdir(d):
                        try:
                            size += os.path.getsize(os.path.join(d, f))
                        except OSError:
                            pass
                    entries.append((atime, d, size))
            entries.sort()              # least recently used first
            target = self.budget * LOW_WATERMARK
            for _, d, size in entries:
                if usage <= target:
                    break
                shutil.rmtree(d, ignore_errors=True)
                usage -= size

    # -- ObjectLayer overrides ---------------------------------------------

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None):
        if opts is not None and getattr(opts, "version_id", ""):
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        info = self.inner.get_object_info(bucket, key, opts)
        want_len = info.size - offset if length < 0 else length
        want_len = max(0, min(want_len, info.size - offset))
        end = offset + want_len

        meta = self._load_entry(bucket, key)
        if meta is not None and meta.get("etag") != info.etag:
            self._drop(bucket, key)     # stale generation
            meta = None
        if meta is not None:
            r = self._covering(meta, offset, end)
            if r is not None:
                d = self._entry_dir(bucket, key)
                path = os.path.join(d, r["file"])
                stream = self._serve_hit(bucket, key, info, path,
                                         r["file"], r["start"], offset,
                                         want_len)
                self.hits += 1
                self._touch(bucket, key)
                return info, stream
        self.misses += 1
        return self._fill_or_passthrough(bucket, key, info, opts,
                                         offset, want_len)

    def _serve_hit(self, bucket, key, info, path, fname, file_start,
                   offset, length) -> Iterator[bytes]:
        """Stream verified frames; on a corrupt/truncated frame, drop
        the bad cache file and continue the REST of the response from
        the backend (bytes already sent were verified)."""
        sent = 0
        try:
            for piece in self._read_frames(path, file_start, offset,
                                           length):
                yield piece
                sent += len(piece)
        except (api_errors.ObjectApiError, OSError):
            # OSError: the entry was purged/invalidated under us — the
            # backend still has the object
            self._drop_range(bucket, key, fname)
            if sent < length:
                _, rest = self.inner.get_object(
                    bucket, key, offset + sent, length - sent)
                yield from rest

    def _fill_or_passthrough(self, bucket, key, info, opts,
                             offset: int, length: int):
        """(info, stream) for a miss. The info returned is the one the
        actual backend READ produced — a concurrent overwrite between
        the stat and the read must not label new bytes with old
        etag/size. A changed generation skips the fill (the span
        arithmetic came from the stale stat; _fill_stream's
        completion check would refuse the commit anyway)."""
        max_entry = self.budget * MAX_ENTRY_FRACTION
        if length <= 0:
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        # whole-object fill
        if offset == 0 and length == info.size and \
                info.size <= max_entry:
            info2, stream = self.inner.get_object(bucket, key, 0,
                                                  info.size, opts)
            if info2.etag != info.etag:
                return info2, stream
            self._ensure_meta(bucket, key, info2)
            return info2, self._fill_stream(bucket, key, info2, stream,
                                            0, info2.size, 0,
                                            info2.size)
        # ranged fill: cache the block-aligned covering span
        astart = offset - offset % self.block
        aend = min(info.size,
                   -(-(offset + length) // self.block) * self.block)
        if aend - astart <= max_entry:
            info2, stream = self.inner.get_object(bucket, key, astart,
                                                  aend - astart, opts)
            if info2.etag != info.etag:
                # new generation: the aligned span was computed from
                # the stale stat — re-read exactly what was asked
                close = getattr(stream, "close", None)
                if close is not None:
                    close()
                return self.inner.get_object(bucket, key, offset,
                                             length, opts)
            self._ensure_meta(bucket, key, info2)
            return info2, self._fill_stream(bucket, key, info2, stream,
                                            astart, aend - astart,
                                            offset, length)
        # too big to cache: read through
        return self.inner.get_object(bucket, key, offset, length, opts)

    def _ensure_meta(self, bucket: str, key: str, info) -> None:
        """Entry skeleton so concurrent fills of different ranges merge
        under one meta generation."""
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        with self._mu:
            meta = self._load_entry(bucket, key)
            if meta is None or meta.get("etag") != info.etag:
                self._write_meta(d, {
                    "etag": info.etag, "size": info.size,
                    "content_type": info.content_type,
                    "user_defined": dict(info.user_defined or {}),
                    "mod_time": info.mod_time, "ranges": []})

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None):
        self._drop(bucket, key)
        return self.inner.put_object(bucket, key, reader, size, opts)

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False):
        self._drop(bucket, key)
        return self.inner.delete_object(bucket, key, version_id,
                                        versioned)

    def delete_objects(self, bucket: str, objects: list[str]):
        for o in objects:
            self._drop(bucket, o)
        return self.inner.delete_objects(bucket, objects)

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        self._drop(bucket, key)
        return self.inner.update_object_metadata(bucket, key, metadata,
                                                 version_id)

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "usage": self._usage(), "budget": self.budget}
