"""Disk cache ObjectLayer wrapper (cmd/disk-cache.go cacheObjects).

GETs are served from a local cache directory when the cached copy's
ETag still matches the backend; misses read through and populate.
Parity with the reference's cache depth (VERDICT r4 #4):

  * **Block-framed entries** — cache files store ``[digest || block]``
    frames (the cache-side bitrot framing of
    cmd/disk-cache-backend.go:573), so hits verify INCREMENTALLY,
    block by block, as bytes stream out — no full-object hash pass
    before the first byte, and a corrupt block is detected exactly
    where it sits.
  * **Range entries** — a ranged miss caches just the block-aligned
    span it read (cmd/disk-cache.go range caching); later ranged hits
    serve from any cached span that covers them. Whole-object entries
    are the special case covering [0, size).
  * **Streamed fills** — population tees the backend stream into the
    entry file while yielding to the client: constant memory for any
    object size, and a partial fill (client hangup, backend error) is
    discarded, never served.
  * **Watermark LRU** — usage above HIGH_WATERMARK purges
    least-recently-USED entries down to LOW_WATERMARK
    (cmd/disk-cache.go:271 purge semantics); every hit refreshes the
    entry's clock.

Mutations through the wrapper invalidate the whole entry.

Erasure-path wiring (the hot-object read cache of the device scan
plane): attached at cluster boot in FRONT of ErasureServerSets (like
``attach_metacache``), a cache hit serves plain GETs and Select scans
from the local framed entry WITHOUT touching the erasure decode path.

  * **Admission by access frequency** — only objects GET-hit at least
    ``admit_hits`` times inside ``admit_window_s`` are filled
    (cmd/disk-cache.go cacheControl + the reference's online/offline
    gating, driven here by the telemetry access histogram): one-shot
    bulk reads never churn the watermark LRU.
  * **Invalidation off the namespace feed** — the engines'
    ``on_namespace_change`` hook (the metacache's delta feed) fans out
    to :meth:`CacheObjects.on_namespace_change`, so mutations that
    bypass the wrapper (lifecycle transition, rebalance, heal,
    replication, restore) still evict. The per-GET etag check remains
    the backstop: a stale entry can mis-HIT but never serve wrong
    bytes.
  * **Tiering interplay** — a transitioned (stubbed) version evicts on
    the transition's namespace delta, AND the serve path re-checks the
    backend metadata: a cached copy never answers a GET that must
    return ``InvalidObjectState``.

Env (cluster boot; constructor args override):
  MINIO_TPU_CACHE=on|off            master switch (default off)
  MINIO_TPU_CACHE_DIR=<path>        entry directory (default
                                    <first-drive>/.minio.sys/cache)
  MINIO_TPU_CACHE_BUDGET_BYTES      watermark budget (default 1 GiB)
  MINIO_TPU_CACHE_ADMIT=2           GETs within the window to admit
  MINIO_TPU_CACHE_ADMIT_WINDOW_S    frequency window (default 300)
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import shutil
import threading
import time
from typing import Iterator, Optional

from .. import bitrot as bitrot_mod
from ..storage.datatypes import is_restored, is_transitioned
from ..utils import knobs, lockcheck, telemetry
from . import api_errors
from .engine import GetOptions, PutOptions

DEFAULT_BUDGET = 1 << 30
HIGH_WATERMARK = 0.9
LOW_WATERMARK = 0.7
MAX_ENTRY_FRACTION = 0.1
CACHE_BLOCK = 1 << 20                 # frame payload size
_ALGO = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256
_DIG = 32                             # digest bytes per frame
_FILL_SEQ = itertools.count()         # unique in-flight fill suffixes
_TRACKER_MAX = 100_000                # bounded access-frequency table


def enabled() -> bool:
    return knobs.get_bool("MINIO_TPU_CACHE")


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_cache_hits_total",
                    "GET/Select reads served from the hot-object "
                    "cache (no erasure decode)"),
        reg.counter("minio_tpu_cache_misses_total",
                    "Cache lookups that read through to the backend"),
        reg.counter("minio_tpu_cache_fills_total",
                    "Entries admitted and filled"),
        reg.counter("minio_tpu_cache_evictions_total",
                    "Entry evictions by cause (mutation, namespace "
                    "delta, watermark purge, bitrot, transition)"),
        reg.counter("minio_tpu_cache_bitrot_fallbacks_total",
                    "Corrupt cache frames detected; response continued "
                    "from the backend"),
        reg.counter("minio_tpu_cache_admit_rejects_total",
                    "Misses below the access-frequency admission bar"),
        reg.histogram("minio_tpu_cache_object_access",
                      "Access count within the admission window "
                      "observed at each GET (the admission signal)"),
    )


class AccessTracker:
    """Decaying per-object GET frequency — the admission signal. An
    object qualifies once it was read `admit_hits` times within
    `window_s`; the table is bounded (oldest half dropped on overflow)
    so hot-key tracking never grows with the namespace."""

    def __init__(self, admit_hits: int, window_s: float):
        self.admit_hits = max(1, admit_hits)
        self.window_s = window_s
        self._mu = lockcheck.mutex("cache.tracker")
        self._t: dict[tuple[str, str], tuple[int, float]] = {}

    def record(self, bucket: str, key: str) -> int:
        """Count one access; returns the in-window count."""
        now = time.monotonic()
        k = (bucket, key)
        with self._mu:
            count, first = self._t.get(k, (0, now))
            if now - first > self.window_s:
                count, first = 0, now          # window expired: restart
            count += 1
            self._t[k] = (count, first)
            if len(self._t) > _TRACKER_MAX:
                # overflow runs inside the GET hot path: single-pass
                # drops of expired then older-half windows — never a
                # full sort under the lock
                for cutoff in (now - self.window_s,
                               now - self.window_s / 2):
                    self._t = {k: v for k, v in self._t.items()
                               if v[1] >= cutoff}
                    if len(self._t) <= _TRACKER_MAX:
                        break
                else:
                    # uniform churn inside the half-window: drop
                    # arbitrary entries to keep the table bounded
                    it = iter(self._t)
                    for k in [next(it)
                              for _ in range(len(self._t) // 2)]:
                        del self._t[k]
        return count

    def admitted(self, count: int) -> bool:
        return count >= self.admit_hits


class CacheObjects:
    """ObjectLayer wrapper with a block-framed read cache on a local
    path."""

    def __init__(self, inner, cache_dir: str,
                 budget_bytes: int = DEFAULT_BUDGET,
                 block_size: int = CACHE_BLOCK,
                 admit_hits: int = 1,
                 admit_window_s: float = 300.0):
        self.inner = inner
        self.dir = os.path.abspath(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.budget = budget_bytes
        self.block = block_size
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.admit_rejects = 0
        self.tracker = AccessTracker(admit_hits, admit_window_s)
        self._m = _metrics()
        self._mu = lockcheck.mutex("cache.meta")
        self._purge_mu = lockcheck.mutex("cache.purge")

    @classmethod
    def from_env(cls, inner, default_dir: str) -> "CacheObjects":
        """The cluster-boot constructor: every knob from the
        MINIO_TPU_CACHE_* environment."""
        return cls(
            inner,
            knobs.get_str("MINIO_TPU_CACHE_DIR") or default_dir,
            budget_bytes=knobs.get_int("MINIO_TPU_CACHE_BUDGET_BYTES"),
            admit_hits=knobs.get_int("MINIO_TPU_CACHE_ADMIT"),
            admit_window_s=knobs.get_float(
                "MINIO_TPU_CACHE_ADMIT_WINDOW_S"))

    # everything not overridden passes straight through
    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- entry layout ------------------------------------------------------

    def _entry_dir(self, bucket: str, key: str) -> str:
        # first level keyed by BUCKET so delete_bucket purges one
        # subtree instead of json-scanning every entry on the node
        bh = hashlib.sha256(bucket.encode()).hexdigest()[:16]
        h = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.dir, bh, h)

    def _load_entry(self, bucket: str, key: str) -> Optional[dict]:
        d = self._entry_dir(bucket, key)
        try:
            with open(os.path.join(d, "meta.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_meta(self, d: str, meta: dict) -> None:
        tmp = os.path.join(d, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, "meta.json"))

    def _touch(self, bucket: str, key: str) -> None:
        """Refresh the entry's LRU clock (meta mtime is the clock)."""
        try:
            os.utime(os.path.join(self._entry_dir(bucket, key),
                                  "meta.json"))
        except OSError:
            pass

    def _drop(self, bucket: str, key: str, cause: str = "mutation"
              ) -> None:
        d = self._entry_dir(bucket, key)
        if os.path.isdir(d):
            self.evictions += 1
            self._m[3].inc(cause=cause)
        shutil.rmtree(d, ignore_errors=True)

    def on_namespace_change(self, bucket: str, key: str) -> None:
        """The engines' namespace-delta feed (PUT/DELETE/metadata/
        tier transition/stub — whatever mutated, the entry is stale):
        evict. Mutations that bypass this wrapper (lifecycle
        transitions, rebalance moves, heals, replication) reach the
        cache only through this hook."""
        self._drop(bucket, key, cause="namespace")

    def _drop_range(self, bucket: str, key: str, fname: str) -> None:
        """Remove one corrupt cache file and its meta reference."""
        d = self._entry_dir(bucket, key)
        # the meta.json read-modify-write IS the shared state the lock
        # exists for: one small-file rewrite, bounded, no backend I/O
        with self._mu:  # check: allow(lock-blocking) meta.json RMW critical section (one small file)
            meta = self._load_entry(bucket, key)
            try:
                os.remove(os.path.join(d, fname))
            except OSError:
                pass
            if meta is not None:
                meta["ranges"] = [r for r in meta.get("ranges", [])
                                  if r["file"] != fname]
                try:
                    self._write_meta(d, meta)
                except OSError:
                    # entry dir purged under us (watermark/namespace
                    # eviction) — the drop already happened
                    pass

    # -- framed file I/O ---------------------------------------------------

    def _read_frames(self, path: str, file_start: int, offset: int,
                     length: int) -> Iterator[bytes]:
        """Yield verified payload for [offset, offset+length) out of a
        framed cache file whose payload begins at absolute object
        offset file_start. Raises bitrot mismatch BEFORE yielding the
        affected block."""
        rel = offset - file_start
        first = rel // self.block
        skip = rel - first * self.block
        remaining = length
        with open(path, "rb") as f:
            f.seek(first * (_DIG + self.block))
            while remaining > 0:
                digest = f.read(_DIG)
                block = f.read(self.block)
                if len(digest) < _DIG or not block:
                    raise api_errors.ObjectApiError(
                        "truncated cache frame")
                if bitrot_mod.hash_shard(block, _ALGO) != digest:
                    raise api_errors.ObjectApiError(
                        "cache bitrot mismatch")
                piece = block[skip:skip + remaining]
                skip = 0
                remaining -= len(piece)
                if piece:
                    yield piece

    # -- covering-span lookup ----------------------------------------------

    def _covering(self, meta: dict, start: int, end: int
                  ) -> Optional[dict]:
        """A cached range record covering [start, end), or None."""
        for r in meta.get("ranges", []):
            if r["start"] <= start and r["end"] >= end:
                return r
        return None

    # -- streamed fill -----------------------------------------------------

    def _fill_stream(self, bucket: str, key: str, info, stream,
                     file_start: int, span_len: int,
                     yield_from: int, yield_len: int
                     ) -> Iterator[bytes]:
        """Tee `stream` (payload of [file_start, file_start+span_len))
        into a framed cache file while yielding the requested
        [yield_from, yield_from+yield_len) sub-span. Constant memory;
        a partial fill is discarded in `finally`."""
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        fname = "data" if (file_start == 0
                           and span_len == info.size) else \
            f"r{file_start}"
        # unique per fill: concurrent threads filling the same range
        # must never share a tmp inode
        tmp = os.path.join(
            d, f"{fname}.tmp{os.getpid()}.{next(_FILL_SEQ)}")
        done = 0
        want_skip = yield_from - file_start
        want_left = yield_len
        completed = False
        try:
            with open(tmp, "wb") as out:
                buf = bytearray()
                for chunk in stream:
                    buf += chunk
                    while len(buf) >= self.block:
                        block = bytes(buf[:self.block])
                        del buf[:self.block]
                        out.write(bitrot_mod.hash_shard(block, _ALGO))
                        out.write(block)
                        done += len(block)
                        piece = block[max(want_skip, 0):]
                        want_skip -= len(block)
                        if piece and want_left > 0:
                            piece = piece[:want_left]
                            want_left -= len(piece)
                            yield piece
                if buf:
                    block = bytes(buf)
                    out.write(bitrot_mod.hash_shard(block, _ALGO))
                    out.write(block)
                    done += len(block)
                    piece = block[max(want_skip, 0):]
                    if piece and want_left > 0:
                        yield piece[:want_left]
            completed = done == span_len
        finally:
            if not completed:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            else:
                self.fills += 1
                self._m[2].inc()
                self._commit(bucket, key, info, fname, tmp,
                             file_start, file_start + span_len)

    def _commit(self, bucket: str, key: str, info, fname: str,
                tmp: str, start: int, end: int) -> None:
        """Publish a completed fill. The entry dir (or the tmp file)
        may have been rmtree'd by a concurrent purge/invalidation —
        losing the cache entry is fine; failing a client whose bytes
        all arrived is not."""
        d = self._entry_dir(bucket, key)
        try:
            self._commit_locked(bucket, key, info, fname, tmp, d,
                                start, end)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return
        self._purge_if_needed()

    def _commit_locked(self, bucket, key, info, fname, tmp, d,
                       start, end) -> None:
        with self._mu:  # check: allow(lock-blocking) meta.json RMW critical section (one small file); caller catches OSError

            meta = self._load_entry(bucket, key)
            if meta is None or meta.get("etag") != info.etag:
                # fresh entry (or a stale generation): ranges reset
                meta = {"bucket": bucket, "key": key,
                        "etag": info.etag, "size": info.size,
                        "content_type": info.content_type,
                        "user_defined": dict(info.user_defined or {}),
                        "mod_time": info.mod_time, "ranges": []}
                for r in list(os.listdir(d)):
                    if r != "meta.json" and ".tmp" not in r:
                        try:
                            os.remove(os.path.join(d, r))
                        except OSError:
                            pass
            os.replace(tmp, os.path.join(d, fname))
            ranges = [r for r in meta.get("ranges", [])
                      if r["file"] != fname]
            ranges.append({"start": start, "end": end, "file": fname})
            meta["ranges"] = sorted(ranges, key=lambda r: r["start"])
            self._write_meta(d, meta)

    # -- LRU purge ---------------------------------------------------------

    def _usage(self) -> int:
        total = 0
        for root, _, files in os.walk(self.dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    def _purge_if_needed(self) -> None:
        """Watermark purge on its OWN serialization lock: the usage
        walk + rmtrees cover the whole cache tree and must not park
        fill commits (`_mu`, the meta.json critical section) behind a
        directory scan. A purge racing a commit is safe — `_commit`
        tolerates its entry dir vanishing — and a second caller
        arriving mid-purge simply skips (that purge is already doing
        the work)."""
        # check: allow(lock-blocking) non-blocking try-acquire: purge-only serialization, deliberately NOT a with-block (a second purger skips instead of queueing)
        if not self._purge_mu.acquire(False):
            return
        try:
            usage = self._usage()
            if usage < self.budget * HIGH_WATERMARK:
                return
            entries = []               # (last_access, dir, bytes)
            for sub in os.listdir(self.dir):
                subdir = os.path.join(self.dir, sub)
                if not os.path.isdir(subdir):
                    continue
                for h in os.listdir(subdir):
                    d = os.path.join(subdir, h)
                    try:
                        atime = os.path.getmtime(
                            os.path.join(d, "meta.json"))
                    except OSError:
                        shutil.rmtree(d, ignore_errors=True)
                        continue
                    size = 0
                    for f in os.listdir(d):
                        try:
                            size += os.path.getsize(os.path.join(d, f))
                        except OSError:
                            pass
                    entries.append((atime, d, size))
            entries.sort()              # least recently used first
            target = self.budget * LOW_WATERMARK
            for _, d, size in entries:
                if usage <= target:
                    break
                shutil.rmtree(d, ignore_errors=True)
                self.evictions += 1
                self._m[3].inc(cause="watermark")
                usage -= size
        finally:
            self._purge_mu.release()

    # -- ObjectLayer overrides ---------------------------------------------

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None):
        if bucket.startswith("."):
            # .minio.sys carries config/index objects mutated by inner
            # layers the wrapper never sees — never cache them
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        if opts is not None and getattr(opts, "version_id", ""):
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        count = self.tracker.record(bucket, key)
        self._m[6].observe(count)
        meta = self._load_entry(bucket, key)
        if meta is None and not self.tracker.admitted(count):
            # common first-touch path: no entry and below the
            # admission bar — pass straight through WITHOUT the extra
            # quorum stat (the inner GET stats and gates itself)
            self.misses += 1
            self._m[1].inc()
            self.admit_rejects += 1
            self._m[5].inc()
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        info = self.inner.get_object_info(bucket, key, opts)
        md = info.user_defined or {}
        if is_transitioned(md) and not is_restored(md):
            # the data lives in a remote tier with no restored copy: a
            # cached generation must NOT answer — the backend GET is
            # the single home of the InvalidObjectState gate
            self._drop(bucket, key, cause="transition")
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        want_len = info.size - offset if length < 0 else length
        want_len = max(0, min(want_len, info.size - offset))
        end = offset + want_len

        if meta is not None and meta.get("etag") != info.etag:
            self._drop(bucket, key)     # stale generation
            meta = None
        if meta is not None:
            r = self._covering(meta, offset, end)
            if r is not None:
                d = self._entry_dir(bucket, key)
                path = os.path.join(d, r["file"])
                stream = self._serve_hit(bucket, key, info, path,
                                         r["file"], r["start"], offset,
                                         want_len)
                self.hits += 1
                self._m[0].inc()
                self._touch(bucket, key)
                return info, stream
        self.misses += 1
        self._m[1].inc()
        if not self.tracker.admitted(count):
            # below the access-frequency bar: read through without
            # filling — one-shot scans must not churn the LRU
            self.admit_rejects += 1
            self._m[5].inc()
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        return self._fill_or_passthrough(bucket, key, info, opts,
                                         offset, want_len)

    def _serve_hit(self, bucket, key, info, path, fname, file_start,
                   offset, length) -> Iterator[bytes]:
        """Stream verified frames; on a corrupt/truncated frame, drop
        the bad cache file and continue the REST of the response from
        the backend (bytes already sent were verified)."""
        sent = 0
        try:
            for piece in self._read_frames(path, file_start, offset,
                                           length):
                yield piece
                sent += len(piece)
        except (api_errors.ObjectApiError, OSError) as e:
            # ObjectApiError = a frame failed its bitrot check — real
            # corruption; OSError = purged under us (already counted
            # by its watermark/namespace eviction) OR a persistent
            # I/O error. Only corruption counts as bitrot, but the
            # range drops either way: on a purge race it is a no-op,
            # on a bad sector it stops re-opening the bad file on
            # every later GET. The backend still has the object.
            if isinstance(e, api_errors.ObjectApiError):
                self._m[4].inc()
                self._m[3].inc(cause="bitrot")
                self.evictions += 1
            self._drop_range(bucket, key, fname)
            if sent < length:
                _, rest = self.inner.get_object(
                    bucket, key, offset + sent, length - sent)
                yield from rest

    def _fill_or_passthrough(self, bucket, key, info, opts,
                             offset: int, length: int):
        """(info, stream) for a miss. The info returned is the one the
        actual backend READ produced — a concurrent overwrite between
        the stat and the read must not label new bytes with old
        etag/size. A changed generation skips the fill (the span
        arithmetic came from the stale stat; _fill_stream's
        completion check would refuse the commit anyway)."""
        max_entry = self.budget * MAX_ENTRY_FRACTION
        if length <= 0:
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        # whole-object fill
        if offset == 0 and length == info.size and \
                info.size <= max_entry:
            info2, stream = self.inner.get_object(bucket, key, 0,
                                                  info.size, opts)
            if info2.etag != info.etag:
                return info2, stream
            self._ensure_meta(bucket, key, info2)
            return info2, self._fill_stream(bucket, key, info2, stream,
                                            0, info2.size, 0,
                                            info2.size)
        # ranged fill: cache the block-aligned covering span
        astart = offset - offset % self.block
        aend = min(info.size,
                   -(-(offset + length) // self.block) * self.block)
        if aend - astart <= max_entry:
            info2, stream = self.inner.get_object(bucket, key, astart,
                                                  aend - astart, opts)
            if info2.etag != info.etag:
                # new generation: the aligned span was computed from
                # the stale stat — re-read exactly what was asked
                close = getattr(stream, "close", None)
                if close is not None:
                    close()
                return self.inner.get_object(bucket, key, offset,
                                             length, opts)
            self._ensure_meta(bucket, key, info2)
            return info2, self._fill_stream(bucket, key, info2, stream,
                                            astart, aend - astart,
                                            offset, length)
        # too big to cache: read through
        return self.inner.get_object(bucket, key, offset, length, opts)

    def _ensure_meta(self, bucket: str, key: str, info) -> None:
        """Entry skeleton so concurrent fills of different ranges merge
        under one meta generation."""
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        with self._mu:  # check: allow(lock-blocking) meta.json RMW critical section (one small file)
            meta = self._load_entry(bucket, key)
            if meta is None or meta.get("etag") != info.etag:
                try:
                    self._write_meta(d, {
                        "bucket": bucket, "key": key,
                        "etag": info.etag, "size": info.size,
                        "content_type": info.content_type,
                        "user_defined": dict(info.user_defined or {}),
                        "mod_time": info.mod_time, "ranges": []})
                except OSError:
                    # entry dir purged between makedirs and the write —
                    # losing the skeleton only skips this fill
                    pass

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None):
        self._drop(bucket, key)
        return self.inner.put_object(bucket, key, reader, size, opts)

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False):
        self._drop(bucket, key)
        return self.inner.delete_object(bucket, key, version_id,
                                        versioned)

    def delete_objects(self, bucket: str, objects: list[str]):
        for o in objects:
            self._drop(bucket, o)
        return self.inner.delete_objects(bucket, objects)

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        self._drop(bucket, key)
        return self.inner.update_object_metadata(bucket, key, metadata,
                                                 version_id)

    def delete_bucket(self, bucket: str, force: bool = False):
        """Purge every entry of the deleted bucket — a recreated
        same-name bucket must start cold. The entry layout's first
        level is the bucket hash, so the purge is one rmtree."""
        out = self.inner.delete_bucket(bucket, force)
        bh = hashlib.sha256(bucket.encode()).hexdigest()[:16]
        shutil.rmtree(os.path.join(self.dir, bh), ignore_errors=True)
        return out

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "fills": self.fills, "evictions": self.evictions,
                "admit_rejects": self.admit_rejects,
                "usage": self._usage(), "budget": self.budget}
