"""Disk cache ObjectLayer wrapper (cmd/disk-cache.go cacheObjects).

GETs are served from a local cache directory when the cached copy's ETag
still matches the backend; misses read through and populate. Mutations
invalidate. An LRU purge keeps the cache under a high-watermark fraction
of its budget (cmd/disk-cache-backend.go purge semantics). Entry
integrity is pinned with a SHA-256 over the cached bytes, verified on
every cache hit (the cache-backend bitrot analog).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import Iterator, Optional

from . import api_errors
from .engine import GetOptions, PutOptions

DEFAULT_BUDGET = 1 << 30
HIGH_WATERMARK = 0.9
LOW_WATERMARK = 0.7
MAX_ENTRY_FRACTION = 0.1


class CacheObjects:
    """ObjectLayer wrapper with a read cache on a local path."""

    def __init__(self, inner, cache_dir: str,
                 budget_bytes: int = DEFAULT_BUDGET):
        self.inner = inner
        self.dir = os.path.abspath(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.budget = budget_bytes
        self.hits = 0
        self.misses = 0
        self._mu = threading.Lock()

    # everything not overridden passes straight through
    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- entry layout ------------------------------------------------------

    def _entry_dir(self, bucket: str, key: str) -> str:
        h = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.dir, h[:2], h)

    def _load_entry(self, bucket: str, key: str) -> Optional[dict]:
        d = self._entry_dir(bucket, key)
        try:
            with open(os.path.join(d, "meta.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _save(self, bucket: str, key: str, info, data: bytes) -> None:
        if len(data) > self.budget * MAX_ENTRY_FRACTION:
            return                     # too big to cache
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "data"), "wb") as f:
            f.write(data)
        meta = {"etag": info.etag, "size": len(data),
                "content_type": info.content_type,
                "user_defined": dict(info.user_defined or {}),
                "mod_time": info.mod_time,
                "sha256": hashlib.sha256(data).hexdigest(),
                "cached_at": time.time()}
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump(meta, f)
        self._purge_if_needed()

    def _drop(self, bucket: str, key: str) -> None:
        shutil.rmtree(self._entry_dir(bucket, key), ignore_errors=True)

    # -- LRU purge ---------------------------------------------------------

    def _usage(self) -> int:
        total = 0
        for root, _, files in os.walk(self.dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    def _purge_if_needed(self) -> None:
        with self._mu:
            if self._usage() < self.budget * HIGH_WATERMARK:
                return
            entries = []
            for sub in os.listdir(self.dir):
                subdir = os.path.join(self.dir, sub)
                if not os.path.isdir(subdir):
                    continue
                for h in os.listdir(subdir):
                    d = os.path.join(subdir, h)
                    try:
                        with open(os.path.join(d, "meta.json")) as f:
                            meta = json.load(f)
                        entries.append((meta.get("cached_at", 0), d,
                                        meta.get("size", 0)))
                    except (OSError, ValueError):
                        shutil.rmtree(d, ignore_errors=True)
            entries.sort()                    # oldest first
            usage = self._usage()
            target = self.budget * LOW_WATERMARK
            for _, d, size in entries:
                if usage <= target:
                    break
                shutil.rmtree(d, ignore_errors=True)
                usage -= size

    # -- ObjectLayer overrides ---------------------------------------------

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None):
        if opts is not None and getattr(opts, "version_id", ""):
            return self.inner.get_object(bucket, key, offset, length,
                                         opts)
        info = self.inner.get_object_info(bucket, key, opts)
        entry = self._load_entry(bucket, key)
        d = self._entry_dir(bucket, key)
        if entry is not None and entry.get("etag") == info.etag:
            try:
                with open(os.path.join(d, "data"), "rb") as f:
                    data = f.read()
            except OSError:
                data = None
            if data is not None and hashlib.sha256(
                    data).hexdigest() == entry.get("sha256"):
                self.hits += 1
                end = len(data) if length < 0 else offset + length
                chunk = data[offset:end]
                return info, iter([chunk])
            self._drop(bucket, key)           # bitrot in the cache
        self.misses += 1
        if offset == 0 and length < 0 or (offset == 0
                                          and length == info.size):
            info2, stream = self.inner.get_object(bucket, key, 0, -1,
                                                  opts)
            data = b"".join(stream)
            self._save(bucket, key, info2, data)
            return info2, iter([data])
        # ranged miss: read through without populating (the reference
        # caches ranges separately; we keep whole-object entries only)
        return self.inner.get_object(bucket, key, offset, length, opts)

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None):
        self._drop(bucket, key)
        return self.inner.put_object(bucket, key, reader, size, opts)

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False):
        self._drop(bucket, key)
        return self.inner.delete_object(bucket, key, version_id,
                                        versioned)

    def delete_objects(self, bucket: str, objects: list[str]):
        for o in objects:
            self._drop(bucket, o)
        return self.inner.delete_objects(bucket, objects)

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""):
        self._drop(bucket, key)
        return self.inner.update_object_metadata(bucket, key, metadata,
                                                 version_id)

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "usage": self._usage(), "budget": self.budget}
