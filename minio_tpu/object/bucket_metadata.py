"""BucketMetadataSys — one durable metadata blob per bucket.

The reference persists a single msgpack blob per bucket at
`.minio.sys/buckets/<bucket>/.metadata.bin` holding policy, lifecycle,
SSE config, tagging, quota, versioning, object-lock, notification and
replication configs, with an in-memory cluster-wide cache
(cmd/bucket-metadata.go, cmd/bucket-metadata-sys.go). Here the blob is
JSON, stored erasure-coded through the object layer itself so it gets
quorum + healing for free.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ..storage.xl_storage import MINIO_META_BUCKET
from . import api_errors

BUCKET_METADATA_FILE = ".metadata.bin"
BUCKET_METADATA_FORMAT = 1


class BucketMetadata:
    """All per-bucket configuration (reference BucketMetadata struct)."""

    FIELDS = ("policy_json", "versioning", "tagging", "quota",
              "lifecycle_xml", "sse_config_xml", "object_lock_xml",
              "notification_xml", "replication_xml",
              "replication_targets")

    def __init__(self, name: str):
        self.name = name
        self.created = time.time()
        self.policy_json: str = ""           # bucket policy (JSON doc)
        self.versioning: str = ""            # "" | "Enabled" | "Suspended"
        self.tagging: dict[str, str] = {}
        self.quota: dict = {}                # {"quota": bytes, "type": ...}
        self.lifecycle_xml: str = ""
        self.sse_config_xml: str = ""
        self.object_lock_xml: str = ""
        self.notification_xml: str = ""
        self.replication_xml: str = ""
        # remote-target registry (cmd/bucket-targets.go): [{arn, host,
        # port, bucket, access_key, secret_key, region, secure}]
        self.replication_targets: list[dict] = []

    def versioning_enabled(self) -> bool:
        return self.versioning == "Enabled"

    def versioning_suspended(self) -> bool:
        return self.versioning == "Suspended"

    def to_bytes(self) -> bytes:
        d = {"format": BUCKET_METADATA_FORMAT, "name": self.name,
             "created": self.created}
        for f in self.FIELDS:
            d[f] = getattr(self, f)
        return json.dumps(d).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "BucketMetadata":
        d = json.loads(raw.decode())
        bm = cls(d.get("name", ""))
        bm.created = d.get("created", 0.0)
        for f in cls.FIELDS:
            if f in d:
                setattr(bm, f, d[f])
        return bm


class BucketMetadataSys:
    """In-memory cache over the persisted per-bucket blobs
    (cmd/bucket-metadata-sys.go)."""

    def __init__(self, object_layer):
        self.obj = object_layer
        self._cache: dict[str, BucketMetadata] = {}
        self._mu = threading.Lock()
        # Cluster hook: called with the bucket name after every persisted
        # change so peers drop their caches (the reference broadcasts
        # LoadBucketMetadata via NotificationSys after each update).
        self.on_change = None

    def _meta_path(self, bucket: str) -> str:
        return f"buckets/{bucket}/{BUCKET_METADATA_FILE}"

    def get(self, bucket: str) -> BucketMetadata:
        with self._mu:
            bm = self._cache.get(bucket)
        if bm is not None:
            return bm
        try:
            _, stream = self.obj.get_object(MINIO_META_BUCKET,
                                            self._meta_path(bucket))
            raw = b"".join(stream)
            bm = BucketMetadata.from_bytes(raw)
        except (api_errors.ObjectNotFound, api_errors.BucketNotFound):
            # never-configured bucket -> defaults; any OTHER failure
            # (quorum loss, IO) must propagate — caching defaults there
            # would silently drop versioning/policy until restart
            bm = BucketMetadata(bucket)
        with self._mu:
            self._cache[bucket] = bm
        return bm

    def set(self, bucket: str, bm: BucketMetadata) -> None:
        self.obj.put_object(MINIO_META_BUCKET, self._meta_path(bucket),
                            bm.to_bytes())
        with self._mu:
            self._cache[bucket] = bm
        self._notify(bucket)

    def _notify(self, bucket: str) -> None:
        if self.on_change is not None:
            try:
                self.on_change(bucket)
            except Exception:  # noqa: BLE001 — peers reload lazily anyway
                pass

    def update(self, bucket: str, **fields) -> BucketMetadata:
        bm = self.get(bucket)
        for k, v in fields.items():
            if k not in BucketMetadata.FIELDS:
                raise ValueError(f"unknown bucket metadata field {k}")
            setattr(bm, k, v)
        self.set(bucket, bm)
        return bm

    def delete(self, bucket: str) -> None:
        try:
            self.obj.delete_object(MINIO_META_BUCKET,
                                   self._meta_path(bucket))
        except api_errors.ObjectApiError:
            pass
        with self._mu:
            self._cache.pop(bucket, None)
        self._notify(bucket)

    def reload(self, bucket: str) -> None:
        """Drop the cache entry (peer-notified metadata change)."""
        with self._mu:
            self._cache.pop(bucket, None)

    # convenience accessors -------------------------------------------------
    def versioning_enabled(self, bucket: str) -> bool:
        return self.get(bucket).versioning_enabled()

    def versioning_suspended(self, bucket: str) -> bool:
        return self.get(bucket).versioning_suspended()

    def get_quota(self, bucket: str) -> Optional[dict]:
        q = self.get(bucket).quota
        return q or None
