"""Bitrot-framed shard I/O over StorageAPI.

Streaming algorithms (the default HighwayHash256S) interleave a digest
before every shard block inside the shard file — ``[h(block) || block]*``
— so reads verify incrementally without a separate checksum file
(reference: cmd/bitrot-streaming.go:46-58 writer, :111-150 reader).
Whole-file algorithms hash the entire shard and store the digest in
xl.meta's checksum list (cmd/bitrot-whole.go).

Writers buffer frames and flush to the drive with append_file; readers
pread frames by computed offset and verify before returning payload.
"""

from __future__ import annotations

import io
from typing import Optional

from .. import bitrot as bitrot_mod
from ..storage import errors
from ..storage.api import StorageAPI

BitrotAlgorithm = bitrot_mod.BitrotAlgorithm


def new_bitrot_writer(disk: StorageAPI, volume: str, path: str,
                      length: int, algo: BitrotAlgorithm,
                      shard_size: int):
    """Factory mirroring reference newBitrotWriter (cmd/bitrot.go:99)."""
    if algo.streaming:
        return StreamingBitrotWriter(disk, volume, path, shard_size, algo)
    return WholeBitrotWriter(disk, volume, path, algo)


def new_bitrot_reader(disk: StorageAPI, volume: str, path: str,
                      till_offset: int, algo: BitrotAlgorithm,
                      expected_digest: bytes, shard_size: int):
    """Factory mirroring reference newBitrotReader (cmd/bitrot.go:105)."""
    if algo.streaming:
        return StreamingBitrotReader(disk, volume, path, till_offset,
                                     algo, shard_size)
    return WholeBitrotReader(disk, volume, path, algo, expected_digest,
                             shard_size)


class StreamingBitrotWriter:
    """Writes [digest || block] frames; every write() must be exactly one
    shard block (the last may be short) — matching the encode loop's
    block cadence."""

    FLUSH_THRESHOLD = 8 << 20  # bound writer memory on huge parts

    def __init__(self, disk: StorageAPI, volume: str, path: str,
                 shard_size: int, algo: BitrotAlgorithm):
        self.disk, self.volume, self.path = disk, volume, path
        self.shard_size, self.algo = shard_size, algo
        self._buf = io.BytesIO()
        self._started = False
        # Local drives expose a persistent append handle: frames stream
        # straight into the OS file (one memcpy pass fewer than
        # buffer-then-append). Remote disks keep the buffered batches —
        # one RPC per flush, not per frame. Opened lazily so writer
        # construction never touches the drive (per-drive faults must
        # surface inside the quorum-tolerant write fan-out).
        self._file = None
        try:
            probe = getattr(disk, "has_appender", None)
            self._use_appender = bool(probe is not None and probe())
        except Exception:  # noqa: BLE001 — capability probe only
            self._use_appender = False

    def write(self, block: bytes) -> None:
        if len(block) == 0:
            return
        digest = bitrot_mod.hash_shard(block, self.algo)
        self.write_with_digest(block, digest)

    def write_with_digest(self, block, digest) -> None:
        """Frame a block whose digest was already computed (by the batched
        device/native hasher) — the accelerator handoff seam."""
        if self._use_appender:
            try:
                if self._file is None:
                    self._file = self.disk.open_appender(self.volume,
                                                         self.path)
                self._file.write(digest)
                self._file.write(block)
            except OSError as e:
                raise errors.FaultyDisk(str(e)) from e
            return
        self._buf.write(digest)
        self._buf.write(block)
        if self._buf.tell() >= self.FLUSH_THRESHOLD:
            self._flush()

    def _flush(self) -> None:
        # getbuffer(): hand the drive a view, not a copy, of the frame
        # buffer (a full extra pass over the payload per shard file)
        data = self._buf.getbuffer()
        if not data.nbytes and self._started:
            return
        self.disk.append_file(self.volume, self.path, data)
        self._started = True
        del data
        self._buf = io.BytesIO()

    def close(self) -> None:
        if self._use_appender:
            try:
                if self._file is None:
                    # 0-byte objects still commit an (empty) shard file
                    self._file = self.disk.open_appender(self.volume,
                                                         self.path)
                self._file.close()
            except OSError as e:
                raise errors.FaultyDisk(str(e)) from e
            finally:
                self._file = None
            return
        self._flush()

    def digest(self) -> bytes:
        return b""  # streaming: digests live in the frames


class WholeBitrotWriter:
    def __init__(self, disk: StorageAPI, volume: str, path: str,
                 algo: BitrotAlgorithm):
        self.disk, self.volume, self.path = disk, volume, path
        self.algo = algo
        self._hasher = bitrot_mod.new_hasher(algo)
        self._buf = io.BytesIO()

    def write(self, block: bytes) -> None:
        self._hasher.update(block)
        self._buf.write(block)

    def write_with_digest(self, block: bytes, digest: bytes) -> None:
        # whole-file algos hash the entire shard; a per-block digest from
        # the batched hasher can't be used — rehash into the running state
        self.write(block)

    def close(self) -> None:
        data = self._buf.getvalue()
        self.disk.create_file(self.volume, self.path, len(data),
                              io.BytesIO(data))

    def digest(self) -> bytes:
        return self._hasher.digest()


class StreamingBitrotReader:
    """Verified positional reads of shard blocks.

    read_at(offset, length): offset/length are in *payload* coordinates;
    the frame location on disk is derived from the shard size
    (cmd/bitrot-streaming.go:111-150)."""

    def __init__(self, disk: StorageAPI, volume: str, path: str,
                 till_offset: int, algo: BitrotAlgorithm, shard_size: int):
        self.disk, self.volume, self.path = disk, volume, path
        self.algo, self.shard_size = algo, shard_size
        # till_offset is in payload coords; on-disk adds digest framing
        self.till_offset = bitrot_mod.bitrot_shard_file_size(
            till_offset, shard_size, algo)
        self._stream: Optional[io.BufferedReader] = None
        self._pos = -1  # next on-disk offset the stream will yield

    def read_at(self, offset: int, length: int) -> bytes:
        """Read payload bytes [offset, offset+length) — must be
        block-aligned (offset % shard_size == 0), like the reference.
        Verifies every frame before returning."""
        out = bytearray()
        for digest, block in self.read_frames(offset, length):
            got = bitrot_mod.hash_shard(block, self.algo)
            if got != digest:
                raise errors.BitrotHashMismatch(digest.hex(), got.hex())
            out += block
        return bytes(out)

    def read_frames(self, offset: int, length: int
                    ) -> list[tuple[bytes, bytes]]:
        """Raw (expected_digest, payload) frames WITHOUT verifying — the
        deferred-verify seam for the fused device path: the engine batches
        many shards' frames into one device program that hashes and
        reconstructs together (models/pipeline.get_step), then compares
        digests host-side. Callers that don't batch must use read_at."""
        if length == 0:
            return []
        if offset % self.shard_size:
            raise errors.UnexpectedError(
                f"unaligned bitrot read at {offset}")
        block_idx = offset // self.shard_size
        disk_off = block_idx * (self.algo.digest_size + self.shard_size)
        if self._stream is None or disk_off != self._pos:
            if self._stream is not None:
                self._stream.close()
            self._stream = self.disk.read_file_stream(
                self.volume, self.path, disk_off,
                self.till_offset - disk_off)
            self._pos = disk_off

        frames: list[tuple[bytes, bytes]] = []
        remaining = length
        while remaining > 0:
            digest = self._read_exact(self.algo.digest_size)
            n = min(self.shard_size, remaining)
            block = self._read_exact(n)
            self._pos += self.algo.digest_size + n
            frames.append((digest, block))
            remaining -= n
        return frames

    def _read_exact(self, n: int) -> bytes:
        assert self._stream is not None
        buf = b""
        while len(buf) < n:
            chunk = self._stream.read(n - len(buf))
            if not chunk:
                raise errors.FileCorrupt(
                    f"{self.path}: truncated bitrot frame")
            buf += chunk
        return buf

    def close(self) -> None:
        if self._stream is not None:
            self._stream.close()
            self._stream = None


class WholeBitrotReader:
    """Reads the whole shard once, verifies the single digest, then serves
    positional reads from memory (reference wholeBitrotReader uses a
    ReadFile verifier; shard files are small enough per part)."""

    def __init__(self, disk: StorageAPI, volume: str, path: str,
                 algo: BitrotAlgorithm, expected_digest: bytes,
                 shard_size: int):
        self.disk, self.volume, self.path = disk, volume, path
        self.algo, self.expected = algo, expected_digest
        self.shard_size = shard_size
        self._data: Optional[bytes] = None

    def read_at(self, offset: int, length: int) -> bytes:
        if self._data is None:
            data = self.disk.read_all(self.volume, self.path)
            if self.expected:
                got = bitrot_mod.hash_shard(data, self.algo)
                if got != self.expected:
                    raise errors.BitrotHashMismatch(
                        self.expected.hex(), got.hex())
            self._data = data
        return self._data[offset:offset + length]

    def close(self) -> None:
        self._data = None
