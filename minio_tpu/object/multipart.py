"""Multipart upload sessions (reference cmd/erasure-multipart.go).

Sessions live under ``.minio.sys/multipart/<sha256(bucket/object)>/
<uploadID>/`` — a flat v3-format hierarchy: the session's xl.meta holds
the user metadata + the parts recorded so far; each part is separately
erasure-coded into ``<dataDir>/part.N`` with bitrot framing
(PutObjectPart encodes exactly like PutObject, cmd/erasure-multipart.go:430).
CompleteMultipartUpload validates the client's part list, freezes the
final FileInfo, and commits the whole session dir with the same
rename_data 2-phase commit PUT uses.

Crash safety: a session is resumable by uploadID at any point (the
reference's checkpoint/resume analog, SURVEY §5) — parts already
uploaded survive process restarts because they live on the drives.
"""

from __future__ import annotations

import copy
import hashlib
import uuid as _uuid
from typing import Optional

import numpy as np

from .. import bitrot as bitrot_mod
from ..utils import crashpoint, healthtrack
from ..storage import errors as serr
from ..storage.datatypes import (NULL_VERSION_ID, ChecksumInfo, FileInfo,
                                 ObjectInfo, now)
from ..storage.xl_storage import (MINIO_META_MULTIPART_BUCKET,
                                  MINIO_META_TMP_BUCKET)
from . import api_errors, bitrot_io, metadata as meta
from .engine import ErasureObjects, PutOptions, _read_full
from .hash_reader import HashReader

MIN_PART_SIZE = 5 << 20  # S3: every part but the last >= 5 MiB


class CompletePart:
    def __init__(self, part_number: int, etag: str):
        self.part_number = part_number
        self.etag = etag


class PartInfo:
    def __init__(self, part_number: int, etag: str, size: int,
                 actual_size: int, last_modified: float):
        self.part_number = part_number
        self.etag = etag
        self.size = size
        self.actual_size = actual_size
        self.last_modified = last_modified


class MultipartMixin(ErasureObjects):
    # -- paths -------------------------------------------------------------

    @staticmethod
    def _mp_sha_dir(bucket: str, object_name: str) -> str:
        return hashlib.sha256(
            f"{bucket}/{object_name}".encode()).hexdigest()

    def _upload_dir(self, bucket: str, object_name: str,
                    upload_id: str) -> str:
        return f"{self._mp_sha_dir(bucket, object_name)}/{upload_id}"

    def _check_upload_exists(self, bucket: str, object_name: str,
                             upload_id: str) -> FileInfo:
        path = self._upload_dir(bucket, object_name, upload_id)
        metas, errs = meta.read_all_file_info(
            self.disks, MINIO_META_MULTIPART_BUCKET, path)
        live = [fi for fi in metas if fi is not None]
        if not live:
            raise api_errors.InvalidUploadID(upload_id)
        k = live[0].erasure.data_blocks
        try:
            return meta.pick_valid_file_info(metas, max(1, k))
        except api_errors.InsufficientReadQuorum:
            raise api_errors.InvalidUploadID(upload_id) from None

    def get_multipart_info(self, bucket: str, object_name: str,
                           upload_id: str) -> dict:
        """Session metadata of an in-progress upload (SSE seals etc.)."""
        fi = self._check_upload_exists(bucket, object_name, upload_id)
        return dict(fi.metadata)

    # -- session lifecycle -------------------------------------------------

    def new_multipart_upload(self, bucket: str, object_name: str,
                             opts: Optional[PutOptions] = None,
                             upload_id: Optional[str] = None) -> str:
        """`upload_id` reuses a caller-held id instead of minting one:
        the decommission drain migrates a LIVE session between pools
        and the client's id must keep resolving across the move."""
        opts = opts or PutOptions()
        self.get_bucket_info(bucket)
        k, m, _, write_quorum = self._default_quorums(opts.parity)
        upload_id = upload_id or str(_uuid.uuid4())
        path = self._upload_dir(bucket, object_name, upload_id)

        from ..storage.datatypes import new_file_info
        fi = new_file_info(f"{bucket}/{object_name}", k, m)
        fi.erasure.block_size = self.block_size
        fi.volume = MINIO_META_MULTIPART_BUCKET
        fi.name = path
        fi.data_dir = str(_uuid.uuid4())
        fi.mod_time = now()
        fi.metadata = dict(opts.metadata)
        # the sha-dir layout loses bucket + object name; keep them in the
        # session metadata so bucket-wide upload listings can report real
        # keys and never leak another bucket's uploads (the multipart
        # meta volume is shared by ALL buckets)
        fi.metadata["x-minio-internal-bucket"] = bucket
        fi.metadata["x-minio-internal-object-name"] = object_name
        if opts.versioned:
            fi.metadata["x-minio-internal-versioned"] = "true"

        metas = [fi.light_copy() for _ in self.disks]
        meta.write_unique_file_info(self.disks, MINIO_META_MULTIPART_BUCKET,
                                    path, metas, write_quorum)
        return upload_id

    def put_object_part(self, bucket: str, object_name: str,
                        upload_id: str, part_number: int, reader,
                        size: int = -1) -> PartInfo:
        if not (1 <= part_number <= 10000):
            raise api_errors.InvalidPart(part_number)
        if isinstance(reader, (bytes, bytearray)):
            import io as _io
            size = len(reader)
            reader = HashReader(_io.BytesIO(reader), size)
        elif not isinstance(reader, HashReader):
            reader = HashReader(reader, size)

        with self.ns.new_lock(
                f"{bucket}/{object_name}/{upload_id}").write_locked():
            session_fi = self._check_upload_exists(bucket, object_name,
                                                   upload_id)
            k = session_fi.erasure.data_blocks
            m = session_fi.erasure.parity_blocks
            write_quorum = meta.write_quorum_for(k, m)
            codec = self.codec(k, m)
            path = self._upload_dir(bucket, object_name, upload_id)
            shuffled = meta.shuffle_disks(self.disks,
                                          session_fi.erasure.distribution)

            tmp_id = str(_uuid.uuid4())
            tmp_part = f"{tmp_id}/part.{part_number}"
            writers: list[Optional[object]] = []
            for d in shuffled:
                writers.append(None if d is None else
                               bitrot_io.new_bitrot_writer(
                                   d, MINIO_META_TMP_BUCKET, tmp_part, -1,
                                   self.bitrot_algo, codec.shard_size))
            try:
                total = self._encode_stream(reader, codec, writers,
                                            write_quorum, bucket,
                                            object_name)
                reader.verify()
                etag = reader.md5_current_hex()

                def close_writer(i, d):
                    w = writers[i]
                    if w is None:
                        raise serr.DiskNotFound(f"writer {i}")
                    w.close()

                # quorum-ack: the part upload, like the single-part
                # PUT, must not wait out a gray drive once quorum is
                # durable — the laggard's missing shard surfaces as a
                # rename error at complete and heals through MRF
                stall = healthtrack.write_stall_s()
                _, errs = meta.for_each_disk_quorum(
                    shuffled, close_writer, write_quorum,
                    stall_s=stall, stage="close")
                for i, e in enumerate(errs):
                    if e is not None:
                        writers[i] = None

                # move the staged part into the session's data dir
                dst = f"{path}/{session_fi.data_dir}/part.{part_number}"

                # staged shards exist, the session journal has never
                # seen the part — a crash here loses only tmp garbage
                crashpoint.hit("multipart.part.before_rename")

                def rename(i, d):
                    if writers[i] is None:
                        raise serr.DiskNotFound(f"writer {i}")
                    d.rename_file(MINIO_META_TMP_BUCKET, tmp_part,
                                  MINIO_META_MULTIPART_BUCKET, dst)

                _, errs = meta.for_each_disk_quorum(
                    shuffled, rename, write_quorum, stall_s=stall,
                    stage="rename")
                err = meta.reduce_write_quorum_errs(
                    errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
                if err is not None:
                    raise api_errors.to_object_err(err, bucket, object_name)
            finally:
                reader.close()  # stop the async hasher even on failure
                self._cleanup_tmp(shuffled, tmp_id)

            # record the part in the session journal
            session_fi.add_object_part(part_number, etag, total,
                                       reader.actual_size
                                       if reader.actual_size >= 0 else total)
            session_fi.erasure.checksums = [
                c for c in session_fi.erasure.checksums
                if c.part_number != part_number]
            session_fi.erasure.checksums.append(
                ChecksumInfo(part_number, self.bitrot_algo.value, b""))
            session_fi.mod_time = now()
            metas = [session_fi.light_copy() for _ in self.disks]
            meta.write_unique_file_info(
                self.disks, MINIO_META_MULTIPART_BUCKET, path, metas,
                write_quorum)
            # actual_size = client (plaintext) bytes; total = stored
            # bytes (ciphertext under SSE) — keep the returned PartInfo
            # consistent with the session journal entry above
            return PartInfo(part_number, etag, total,
                            reader.actual_size
                            if reader.actual_size >= 0 else total, now())

    def list_object_parts(self, bucket: str, object_name: str,
                          upload_id: str, part_marker: int = 0,
                          max_parts: int = 1000) -> list[PartInfo]:
        fi = self._check_upload_exists(bucket, object_name, upload_id)
        out = [PartInfo(p.number, p.etag, p.size, p.actual_size, fi.mod_time)
               for p in fi.parts if p.number > part_marker]
        return out[:max_parts]

    def read_multipart_part(self, bucket: str, object_name: str,
                            upload_id: str, part_number: int):
        """Decode ONE uncommitted session part back into plaintext —
        the read half of a live-session migration (decommission drains
        in-flight uploads instead of waiting them out). Returns
        (PartInfo, chunk iterator); the same verified/reconstructing
        group readers the GET path uses, pointed at the session's
        ``part.N`` files under the multipart meta volume."""
        path = self._upload_dir(bucket, object_name, upload_id)
        metas, _errs = meta.read_all_file_info(
            self.disks, MINIO_META_MULTIPART_BUCKET, path)
        live = [fi for fi in metas if fi is not None]
        if not live:
            raise api_errors.InvalidUploadID(upload_id)
        k = live[0].erasure.data_blocks
        try:
            fi = meta.pick_valid_file_info(metas, max(1, k))
        except api_errors.InsufficientReadQuorum:
            raise api_errors.InvalidUploadID(upload_id) from None
        part = next((p for p in fi.parts if p.number == part_number),
                    None)
        if part is None:
            raise api_errors.InvalidPart(part_number)
        disks = meta.shuffle_disks(self.disks, fi.erasure.distribution)
        smeta = meta.shuffle_parts_metadata(metas,
                                            fi.erasure.distribution)
        codec = self.codec(fi.erasure.data_blocks,
                           fi.erasure.parity_blocks)
        info = PartInfo(part.number, part.etag, part.size,
                        part.actual_size, fi.mod_time)
        stream = self._read_part(MINIO_META_MULTIPART_BUCKET, path, fi,
                                 disks, smeta, codec, part, 0, part.size)
        return info, stream

    def _scan_multipart_sessions(self, sha_dirs=None):
        """(owner_bucket, object, upload_id, fi) for every session the
        first healthy disk can list (shared by the per-bucket lister
        and the decommission sweep — ONE scan implementation to keep
        in sync). `sha_dirs` narrows the walk to known sha prefixes."""
        for d in self.disks:
            if d is None:
                continue
            try:
                dirs = sha_dirs if sha_dirs is not None else \
                    d.list_dir(MINIO_META_MULTIPART_BUCKET, "")
                for sha in dirs:
                    try:
                        ids = d.list_dir(MINIO_META_MULTIPART_BUCKET,
                                         sha.rstrip("/"))
                    except serr.StorageError:
                        continue
                    for uid in ids:
                        uid = uid.rstrip("/")
                        path = f"{sha.rstrip('/')}/{uid}"
                        try:
                            fi = d.read_version(
                                MINIO_META_MULTIPART_BUCKET, path)
                        except serr.StorageError:
                            continue
                        yield (fi.metadata.get(
                            "x-minio-internal-bucket", ""),
                            fi.metadata.get(
                                "x-minio-internal-object-name", ""),
                            uid, fi)
                return
            except serr.StorageError:
                continue

    def list_multipart_uploads(self, bucket: str, object_name: str = ""
                               ) -> list[dict]:
        """Uploads in progress (for `object_name` if given): each entry is
        {"object", "upload_id", "initiated"} read from the session
        xl.meta (cmd/erasure-multipart.go ListMultipartUploads)."""
        sha_dirs = [self._mp_sha_dir(bucket, object_name) + "/"] \
            if object_name else None
        out: list[dict] = []
        for owner, obj, uid, fi in \
                self._scan_multipart_sessions(sha_dirs):
            # shared volume holds ALL buckets; ownerless (pre-layout)
            # sessions count toward the requested bucket
            if (owner or bucket) != bucket:
                continue
            out.append({"object": obj or object_name,
                        "upload_id": uid, "initiated": fi.mod_time})
        out.sort(key=lambda u: (u["object"], u["upload_id"]))
        return out

    def list_all_multipart_uploads(self) -> list[dict]:
        """Every live session in the shared multipart meta volume,
        each entry carrying its owning ``bucket`` — ONE volume scan
        for the decommission sweep instead of a full rescan per
        bucket."""
        out = [{"bucket": owner, "object": obj, "upload_id": uid,
                "initiated": fi.mod_time}
               for owner, obj, uid, fi in self._scan_multipart_sessions()
               if owner]               # pre-layout session: no owner
        out.sort(key=lambda u: (u["bucket"], u["object"],
                                u["upload_id"]))
        return out

    def mark_multipart_session(self, bucket: str, object_name: str,
                               upload_id: str,
                               extra: dict[str, str]) -> None:
        """Merge `extra` into the session journal's metadata (the
        migration-progress marker). Caller holds the session write
        lock — this writes the journal raw, exactly like the part
        recorder above."""
        fi = self._check_upload_exists(bucket, object_name, upload_id)
        fi.metadata.update(extra)
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        path = self._upload_dir(bucket, object_name, upload_id)
        metas = [fi.light_copy() for _ in self.disks]
        meta.write_unique_file_info(
            self.disks, MINIO_META_MULTIPART_BUCKET, path, metas,
            meta.write_quorum_for(k, m))

    def abort_multipart_upload(self, bucket: str, object_name: str,
                               upload_id: str) -> None:
        self._check_upload_exists(bucket, object_name, upload_id)
        path = self._upload_dir(bucket, object_name, upload_id)

        def rm(i, d):
            try:
                d.delete_file(MINIO_META_MULTIPART_BUCKET, path,
                              recursive=True)
            except serr.FileNotFound:
                pass

        meta.for_each_disk(self.disks, rm)

    def complete_multipart_upload(self, bucket: str, object_name: str,
                                  upload_id: str,
                                  parts: list[CompletePart],
                                  version_id: str = "",
                                  mod_time: Optional[float] = None,
                                  if_none_newer: bool = False
                                  ) -> ObjectInfo:
        """`version_id`/`mod_time` are the version-faithful replay form
        (replication apply + tier restore): the committed object keeps
        the SOURCE version's identity instead of minting fresh ones, so
        a multipart object crosses sites with its part boundaries AND
        its multipart etag intact. `if_none_newer` applies the same
        atomic unversioned conflict gate the single-part replay uses
        (PutOptions.if_none_newer). S3 handlers never pass any of
        them."""
        with self.ns.new_lock(
                f"{bucket}/{object_name}/{upload_id}").write_locked():
            session_fi = self._check_upload_exists(bucket, object_name,
                                                   upload_id)
            k = session_fi.erasure.data_blocks
            m = session_fi.erasure.parity_blocks
            write_quorum = meta.write_quorum_for(k, m)
            path = self._upload_dir(bucket, object_name, upload_id)

            by_number = {p.number: p for p in session_fi.parts}
            total = 0
            md5_concat = b""
            final_parts = []
            for idx, cp in enumerate(parts):
                have = by_number.get(cp.part_number)
                if have is None or have.etag != cp.etag.strip('"'):
                    raise api_errors.InvalidPart(
                        cp.part_number, cp.etag,
                        have.etag if have else "missing")
                if (idx != len(parts) - 1
                        and have.size < MIN_PART_SIZE):
                    raise api_errors.PartTooSmall(cp.part_number, have.size)
                total += have.size
                md5_concat += bytes.fromhex(have.etag)
                final_parts.append(have)

            etag = (hashlib.md5(md5_concat).hexdigest()
                    + f"-{len(parts)}")

            fi = copy.deepcopy(session_fi)
            fi.volume, fi.name = bucket, object_name
            fi.size = total
            fi.mod_time = mod_time if mod_time else now()
            fi.parts = final_parts
            fi.metadata["etag"] = etag
            versioned_session = fi.metadata.pop(
                "x-minio-internal-versioned", "")
            if version_id:
                fi.version_id = version_id
            elif versioned_session:
                fi.version_id = str(_uuid.uuid4())
            fi.erasure.checksums = [
                ChecksumInfo(p.number, self.bitrot_algo.value, b"")
                for p in final_parts]

            # drop uncommitted parts' shard files
            keep = {p.number for p in final_parts}
            extra = [p for p in session_fi.parts if p.number not in keep]

            def drop_extra(i, d):
                for p in extra:
                    try:
                        d.delete_file(
                            MINIO_META_MULTIPART_BUCKET,
                            f"{path}/{fi.data_dir}/part.{p.number}")
                    except serr.StorageError:
                        pass

            if extra:
                meta.for_each_disk(self.disks, drop_extra)

            metas = [fi.light_copy() for _ in self.disks]
            with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
                if if_none_newer:
                    # the replication apply's atomic last-writer-wins,
                    # inside the same lock the commit holds (the
                    # single-part path's PutOptions.if_none_newer gate)
                    self._check_none_newer(bucket, object_name, fi)
                meta.write_unique_file_info(
                    self.disks, MINIO_META_MULTIPART_BUCKET, path, metas,
                    write_quorum)
                # final session meta written, object not yet renamed
                # into the namespace: the session must survive intact
                crashpoint.hit("multipart.complete.before_rename")

                def rename(i, d):
                    # one hit per drive (arm :<nth>): a torn complete
                    crashpoint.hit("multipart.complete.rename.partial",
                                   disk=i)
                    # name the committed version: the session meta also
                    # holds the placeholder entry, and a version-
                    # faithful replay's preserved mod time can sort
                    # behind it ("latest" would commit the placeholder)
                    d.rename_data(MINIO_META_MULTIPART_BUCKET, path,
                                  fi.data_dir, bucket, object_name,
                                  fi.version_id or NULL_VERSION_ID)

                # quorum-ack commit: a drive stalling mid-rename must
                # not hold the CompleteMultipartUpload response once
                # quorum is durable — the laggard lands in `errs` and
                # feeds MRF below exactly like a failed rename; when an
                # abandoned rename settles LATE it may have laid an
                # older version over a newer commit, so it re-queues
                # the MRF check against then-current quorum state
                _, errs = meta.for_each_disk_quorum(
                    self.disks, rename, write_quorum,
                    stall_s=healthtrack.write_stall_s(), stage="rename",
                    on_settle=lambda _i: self._notify_degraded(
                        bucket, object_name, fi.version_id))
                err = meta.reduce_write_quorum_errs(
                    errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
                if err is not None:
                    raise api_errors.to_object_err(err, bucket, object_name)
            if any(e is not None for e in errs):
                # commit met quorum but some drives missed the rename:
                # the completed object is degraded on those drives —
                # feed the MRF heal queue exactly like a degraded PUT
                # (ROADMAP follow-up: on_degraded_write previously fired
                # only from PUT/delete/metadata)
                self._notify_degraded(bucket, object_name, fi.version_id)
            self._notify_namespace(bucket, object_name)
            return fi.to_object_info(bucket, object_name)
