"""Persisted bucket metacache: indexed listings + ONE namespace feed.

The listing/metadata plane was the hottest un-optimized path left after
the data-path offloads: every ListObjectsV2 page re-ran a lazy heap
merge-walk across all drives plus a per-name quorum metadata read, and
the crawler, heal scanner, lifecycle, tiering and rebalance loops each
re-walked the namespace independently — fine at 10^4 objects, ruinous
at 10^8. This module is the reference MinIO lineage's metacache pattern
(cmd/metacache*.go) adapted to this repo's planes:

  * a per-bucket, incrementally maintained index of every object's
    quorum-merged version list, held sorted in memory and PERSISTED as
    ordinary erasure-coded objects under
    ``.minio.sys/buckets/<bucket>/.metacache/`` (sorted key segments +
    a manifest) — the index itself survives drive loss, reconstructs
    through the regular GET path, and heals like any object;
  * fed by PUT/DELETE/delete-marker/transition deltas from the engine
    write paths (the ``on_degraded_write`` hook pattern:
    ``engine.on_namespace_change``); the hot path only appends to a
    bounded journal — it NEVER blocks on index I/O. A background
    drainer re-reads each touched name's merged versions and applies
    them, so the index always converges to quorum truth;
  * listings (`list_objects` / `list_objects_v2` / `list_object_versions`)
    are served from the index with BOUNDED staleness
    (``MINIO_TPU_METACACHE_STALENESS_S``): a pending delta older than
    the bound forces a synchronous journal drain before the page is
    cut. The merge-walk remains the fallback and the correctness
    oracle — the page shape is produced by the very same
    ``engine.paginate_objects`` loop;
  * a background reconcile walker repairs drift (missed hooks, journal
    overflow, segment corruption) against the merge-walk;
  * the index doubles as the SINGLE namespace feed
    (:meth:`MetacacheManager.namespace_feed`) consumed by
    DataUsageCrawler, HealScanner, lifecycle sweeps, the tier
    TransitionWorker actions and the rebalance drain walker — one walk
    amortized across five subsystems
    (``minio_tpu_namespace_walks_total`` counts who still walks).

Knobs (README "Listing and the bucket metacache"):

  MINIO_TPU_METACACHE=on|off            master switch (off = exactly the
                                        old merge-walk behavior)
  MINIO_TPU_METACACHE_FEED=on|off       scanners consume the index feed
  MINIO_TPU_METACACHE_STALENESS_S=2.0   serve-time staleness bound
  MINIO_TPU_METACACHE_FLUSH_S=0.2       journal drain cadence
  MINIO_TPU_METACACHE_PERSIST_S=30      min seconds between segment writes
  MINIO_TPU_METACACHE_SEGMENT_KEYS=5000 keys per persisted segment
  MINIO_TPU_METACACHE_JOURNAL=100000    max pending deltas (overflow
                                        invalidates the bucket until the
                                        next reconcile — never a silent
                                        wrong listing)
  MINIO_TPU_METACACHE_RECONCILE_S=300   drift-repair walk cadence
"""

from __future__ import annotations

import bisect
import json
import threading
import time
import uuid as _uuid
from typing import Iterator, Optional

from ..storage.datatypes import ObjectInfo, ObjectPartInfo
from ..storage.xl_storage import MINIO_META_BUCKET
from ..utils import crashpoint, knobs, lockcheck, telemetry
from . import api_errors
from .engine import paginate_objects, paginate_versions

_FORMAT = 1


def enabled() -> bool:
    return knobs.get_bool("MINIO_TPU_METACACHE")


def feed_enabled() -> bool:
    return enabled() and knobs.get_bool("MINIO_TPU_METACACHE_FEED")


def mc_prefix(bucket: str) -> str:
    return f"buckets/{bucket}/.metacache/"


def manifest_key(bucket: str) -> str:
    return mc_prefix(bucket) + "manifest.json"


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_metacache_serves_total",
                    "Listing pages served from the bucket index"),
        reg.counter("minio_tpu_metacache_fallbacks_total",
                    "Listing pages that fell back to the merge-walk"),
        reg.counter("minio_tpu_metacache_deltas_total",
                    "Namespace deltas journaled from the write paths"),
        reg.counter("minio_tpu_metacache_delta_drops_total",
                    "Deltas dropped on journal overflow (bucket is "
                    "invalidated until reconciled — never served stale "
                    "beyond the bound)"),
        reg.counter("minio_tpu_metacache_sync_drains_total",
                    "Serve-time synchronous drains forced by the "
                    "staleness bound"),
        reg.counter("minio_tpu_metacache_reconcile_repairs_total",
                    "Index entries repaired by the reconcile walker"),
        reg.gauge("minio_tpu_metacache_entries",
                  "Object names currently indexed across buckets"),
    )


def listing_histogram():
    return telemetry.REGISTRY.histogram(
        "minio_tpu_listing_page_seconds",
        "Listing page latency by verb and serving path "
        "(source=index|walk)")


def walks_counter():
    """Full-namespace walk counter — the A/B's proof that ONE
    reconcile/build walk replaced the per-subsystem walks. Labelled by
    consumer (crawler, heal, lifecycle, transition, rebalance,
    metacache) and source (merge = a real cross-drive walk, index = a
    feed read)."""
    return telemetry.REGISTRY.counter(
        "minio_tpu_namespace_walks_total",
        "Full-namespace walks by consumer and source")


# ---------------------------------------------------------------------------
# (de)serialization — one compact dict per version
# ---------------------------------------------------------------------------

def _oi_to_doc(o: ObjectInfo) -> dict:
    d = {"v": o.version_id, "t": o.mod_time, "s": o.size,
         "as": o.actual_size, "e": o.etag}
    if o.delete_marker:
        d["dm"] = 1
    if not o.is_latest:
        d["nl"] = 1
    if o.content_type:
        d["ct"] = o.content_type
    if o.content_encoding:
        d["ce"] = o.content_encoding
    if o.storage_class and o.storage_class != "STANDARD":
        d["sc"] = o.storage_class
    if o.user_defined:
        d["ud"] = o.user_defined
    if o.parts:
        d["p"] = [[p.number, p.size, p.actual_size, p.etag]
                  for p in o.parts]
    if o.data_blocks:
        d["db"] = o.data_blocks
    if o.parity_blocks:
        d["pb"] = o.parity_blocks
    return d


def _doc_to_oi(bucket: str, name: str, d: dict) -> ObjectInfo:
    return ObjectInfo(
        bucket=bucket, name=name, version_id=d.get("v", ""),
        mod_time=d.get("t", 0.0), size=d.get("s", 0),
        actual_size=d.get("as", 0), etag=d.get("e", ""),
        delete_marker=bool(d.get("dm")), is_latest=not d.get("nl"),
        content_type=d.get("ct", ""), content_encoding=d.get("ce", ""),
        storage_class=d.get("sc", "STANDARD"),
        user_defined=dict(d.get("ud") or {}),
        parts=[ObjectPartInfo(number=p[0], size=p[1], actual_size=p[2],
                              etag=p[3]) for p in d.get("p", [])],
        data_blocks=d.get("db", 0), parity_blocks=d.get("pb", 0))


class _BucketIndex:
    """In-memory sorted index of one bucket (guarded by the manager's
    lock): `names` sorted asc, `entries[name]` = quorum-merged versions
    newest-first (exactly `engine.object_versions` output), plus the
    persisted-segment map and the dirty set driving incremental segment
    rewrites."""

    __slots__ = ("bucket", "names", "entries", "state", "invalid",
                 "dirty", "segments", "gen", "last_persist")

    READY = "ready"
    BUILDING = "building"

    def __init__(self, bucket: str):
        self.bucket = bucket
        self.names: list[str] = []
        self.entries: dict[str, list[ObjectInfo]] = {}
        self.state = self.BUILDING
        # invalid: journal overflowed (a delta was LOST) — listings
        # fall back until the next reconcile walk restores truth
        self.invalid = False
        self.dirty: set[str] = set()
        # persisted layout: [{"key","first","count"}] sorted by first;
        # segment i covers [first_i, first_{i+1}); None = never persisted
        self.segments: Optional[list[dict]] = None
        self.gen = 0
        self.last_persist = 0.0

    def apply(self, name: str, versions: list[ObjectInfo]) -> bool:
        """Install one name's refreshed version list (empty = gone).
        Returns True when the index actually changed."""
        have = self.entries.get(name)
        if versions:
            if have is None:
                bisect.insort(self.names, name)
            elif _same_versions(have, versions):
                return False
            self.entries[name] = versions
        else:
            if have is None:
                return False
            i = bisect.bisect_left(self.names, name)
            if i < len(self.names) and self.names[i] == name:
                del self.names[i]
            del self.entries[name]
        self.dirty.add(name)
        return True


def _same_versions(a: list[ObjectInfo], b: list[ObjectInfo]) -> bool:
    if len(a) != len(b):
        return False
    return all(x.version_id == y.version_id and x.mod_time == y.mod_time
               and x.etag == y.etag
               and x.delete_marker == y.delete_marker
               and x.user_defined == y.user_defined
               for x, y in zip(a, b))


class MetacacheManager:
    """Owns every bucket's index, the bounded delta journal, the
    drain/persist/reconcile daemon, and the serve/feed surface.

    Attach with ``server_sets.attach_metacache(mgr)`` — that points the
    engines' ``on_namespace_change`` hooks at :meth:`record` and makes
    the listing paths consult :meth:`serve_list_objects` /
    :meth:`serve_list_object_versions` (which return None whenever the
    caller must fall back to the merge-walk)."""

    def __init__(self, object_layer,
                 staleness_s: Optional[float] = None,
                 flush_s: Optional[float] = None,
                 persist_s: Optional[float] = None,
                 reconcile_s: Optional[float] = None,
                 segment_keys: Optional[int] = None,
                 journal_max: Optional[int] = None):
        self.obj = object_layer
        self._staleness = staleness_s
        self._flush_s = flush_s
        self._persist_s = persist_s
        self._reconcile_s = reconcile_s
        self._segment_keys = segment_keys
        self._journal_max = journal_max
        self._cond = lockcheck.condition("metacache.cond")
        # metric families resolved ONCE — record() runs per PUT/DELETE
        # and must not pay seven registry-lock lookups each call
        self._m = _metrics()
        self._indexes: dict[str, _BucketIndex] = {}
        # pending deltas: bucket -> {name: oldest-enqueue monotonic ts}
        self._pending: dict[str, dict[str, float]] = {}
        self._pending_count = 0
        self._build_q: list[str] = []
        self._last_reconcile = time.monotonic()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # stats (tests/admin)
        self.serves = 0
        self.fallbacks = 0
        self.deltas = 0
        self.drops = 0
        self.sync_drains = 0
        self.builds = 0
        self.reconciles = 0
        self.repairs = 0
        self.persist_errors = 0

    # -- knobs (env read per call so tests can flip them) ------------------

    def staleness_s(self) -> float:
        return self._staleness if self._staleness is not None else \
            knobs.get_float("MINIO_TPU_METACACHE_STALENESS_S")

    def flush_s(self) -> float:
        return self._flush_s if self._flush_s is not None else \
            knobs.get_float("MINIO_TPU_METACACHE_FLUSH_S")

    def persist_s(self) -> float:
        return self._persist_s if self._persist_s is not None else \
            knobs.get_float("MINIO_TPU_METACACHE_PERSIST_S")

    def reconcile_s(self) -> float:
        return self._reconcile_s if self._reconcile_s is not None else \
            knobs.get_float("MINIO_TPU_METACACHE_RECONCILE_S")

    def segment_keys(self) -> int:
        return self._segment_keys if self._segment_keys is not None else \
            knobs.get_int("MINIO_TPU_METACACHE_SEGMENT_KEYS")

    def journal_max(self) -> int:
        return self._journal_max if self._journal_max is not None else \
            knobs.get_int("MINIO_TPU_METACACHE_JOURNAL")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MetacacheManager":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metacache")
        self._thread.start()
        return self

    def close(self, flush: bool = True) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        # join the flusher: an in-flight _persist keeps writing segment
        # objects (staging tmps and all) after the flag flips — callers
        # (shutdown, fsck-after-close tests) need the drives quiescent
        # once close() returns
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=10.0)
        if flush:
            for b, idx in list(self._indexes.items()):
                if idx.state == _BucketIndex.READY and idx.dirty:
                    try:
                        self._persist(b)
                    except Exception:  # noqa: BLE001 — shutdown path
                        pass

    # -- hot-path producer -------------------------------------------------

    def record(self, bucket: str, name: str) -> None:
        """Journal one namespace delta. O(1), never blocks on I/O —
        this runs inside the PUT/DELETE hot path."""
        now = time.monotonic()
        with self._cond:
            if self._closed:
                return
            if self._pending_count >= self.journal_max():
                # a LOST delta means unbounded staleness: invalidate
                # the bucket (serves fall back) until reconcile repairs
                self.drops += 1
                idx = self._indexes.get(bucket)
                if idx is not None:
                    idx.invalid = True
                self._m[3].inc()
                return
            pend = self._pending.setdefault(bucket, {})
            if name not in pend:
                pend[name] = now
                self._pending_count += 1
            self.deltas += 1
            self._m[2].inc()
            self._cond.notify_all()

    # -- the daemon --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                self._cond.wait(self.flush_s())
                if self._closed:
                    return
                build = self._build_q.pop(0) if self._build_q else None
            try:
                if build is not None:
                    self.build(build)
                self._drain_once()
                self._persist_due()
                if time.monotonic() - self._last_reconcile \
                        >= self.reconcile_s():
                    self._last_reconcile = time.monotonic()
                    for b in list(self._indexes):
                        self.reconcile(b)
            except Exception:  # noqa: BLE001 — the daemon must survive
                pass

    def _drain_once(self) -> int:
        """Apply every pending delta (background cadence)."""
        with self._cond:
            work: dict[str, list[str]] = {}
            for b in list(self._pending):
                idx = self._indexes.get(b)
                if idx is not None and idx.state != _BucketIndex.READY:
                    # a build is in flight: its walk may already have
                    # passed these names — keep them journaled so the
                    # post-build drain re-reads them (claiming them now
                    # would lose the delta and go stale unboundedly)
                    continue
                work[b] = list(self._pending.pop(b))
            self._pending_count = sum(len(v)
                                      for v in self._pending.values())
        applied = 0
        if work:
            # claimed deltas die with the process here: acked writes
            # must still surface via rebuild/reconcile after restart
            crashpoint.hit("metacache.journal.drain")
        for bucket, names in work.items():
            with self._cond:
                idx = self._indexes.get(bucket)
            if idx is None:
                continue        # never built: a future build reads truth
            for name in names:
                self._refresh(bucket, name)
                applied += 1
        return applied

    def _refresh(self, bucket: str, name: str) -> None:
        """Re-read one name's quorum-merged cross-pool versions and
        install them (runs OUTSIDE the lock — this is the delta's
        deferred metadata read, off the PUT hot path)."""
        versions = self._read_versions(bucket, name)
        with self._cond:
            idx = self._indexes.get(bucket)
            if idx is not None:
                idx.apply(name, versions)
                self._m[6].set(sum(len(i.names)
                                      for i in self._indexes.values()))

    def _read_versions(self, bucket: str, name: str) -> list[ObjectInfo]:
        """One name's cross-pool quorum-merged versions — the layer's
        own object_versions does the pool dedup + newest-first sort."""
        try:
            return self.obj.object_versions(bucket, name)
        except api_errors.ObjectApiError:
            return []

    # -- staleness ---------------------------------------------------------

    def _ensure_fresh(self, bucket: str) -> None:
        """Enforce the staleness bound at serve time: any pending delta
        older than the bound is drained SYNCHRONOUSLY before a page is
        cut from the index."""
        bound = self.staleness_s()
        with self._cond:
            pend = self._pending.get(bucket)
            if not pend:
                return
            oldest = min(pend.values())
            if bound > 0 and time.monotonic() - oldest <= bound:
                return
            names = list(pend)
            del self._pending[bucket]
            self._pending_count -= len(names)
            self.sync_drains += 1
            self._m[4].inc()
        for name in names:
            self._refresh(bucket, name)

    # -- build / load / persist / reconcile --------------------------------

    def _walk_names(self, bucket: str) -> set[str]:
        """One full merge-walk of the bucket's names across every pool
        and set — THE amortized walk."""
        walks_counter().inc(consumer="metacache", source="merge")
        names: set[str] = set()
        layers = getattr(self.obj, "server_sets", None) or [self.obj]
        for z in layers:
            for eng in getattr(z, "sets", [z]):
                try:
                    names.update(eng._merged_names(bucket, ""))
                except api_errors.ObjectApiError:
                    continue
        return names

    def build(self, bucket: str) -> bool:
        """Build (or rebuild) one bucket's index: try the persisted
        segments first, else a full merge-walk + per-name refresh.
        Returns True when the bucket is ready afterwards."""
        try:
            self.obj.get_bucket_info(bucket)
        except api_errors.BucketNotFound:
            self.drop_bucket(bucket, purge=True)
            return False
        except api_errors.ObjectApiError:
            # transient (quorum) failure: keep persisted artifacts
            self.drop_bucket(bucket)
            return False
        with self._cond:
            idx = self._indexes.get(bucket)
            if idx is not None and idx.state == _BucketIndex.READY \
                    and not idx.invalid:
                return True
            idx = _BucketIndex(bucket)
            self._indexes[bucket] = idx
            drops0 = self.drops
        self.builds += 1
        with telemetry.trace("metacache.build", bucket=bucket):
            if self._load_persisted(bucket, idx):
                # the persisted snapshot may predate downtime mutations
                # (and, when the old index overflowed, the lost delta):
                # presence drift alone cannot prove version freshness —
                # an overwrite changes versions without changing the
                # name set — so stay invalid (serves fall back) until
                # the immediate reconcile has refreshed EVERY name
                with self._cond:
                    idx.state = _BucketIndex.READY
                    idx.invalid = True
                self.reconcile(bucket)
                return True
            names = sorted(self._walk_names(bucket))
            entries: dict[str, list[ObjectInfo]] = {}
            for n in names:
                vers = self._read_versions(bucket, n)
                if vers:
                    entries[n] = vers
            with self._cond:
                idx.names = sorted(entries)
                idx.entries = entries
                idx.state = _BucketIndex.READY
                # an overflow DURING this walk lost a delta the walk
                # may already have passed — stay invalid for reconcile
                idx.invalid = self.drops != drops0
                idx.dirty = set(idx.names)
                self._m[6].set(sum(len(i.names)
                                      for i in self._indexes.values()))
        return True

    def _load_persisted(self, bucket: str, idx: _BucketIndex) -> bool:
        """Load manifest + segments written by a previous process. Any
        read/parse failure (drive loss beyond parity, bitrot the GET
        path could not reconstruct) abandons the load — the caller
        rebuilds from the walk, never serves a wrong listing."""
        try:
            doc = json.loads(self._get_bytes(manifest_key(bucket)))
            if doc.get("format") != _FORMAT or doc.get("bucket") != bucket:
                return False
            names: list[str] = []
            entries: dict[str, list[ObjectInfo]] = {}
            for seg in doc.get("segments", []):
                payload = json.loads(self._get_bytes(seg["key"]))
                for name, vdocs in payload:
                    entries[name] = [_doc_to_oi(bucket, name, d)
                                     for d in vdocs]
            names = sorted(entries)
            with self._cond:
                idx.names = names
                idx.entries = entries
                idx.segments = sorted(doc.get("segments", []),
                                      key=lambda s: s["first"])
                idx.gen = int(doc.get("gen", 0))
                idx.dirty = set()
        except (api_errors.ObjectApiError, ValueError, KeyError,
                TypeError, IndexError, AttributeError):
            # AttributeError covers a torn manifest whose truncated
            # prefix still parses as valid non-dict JSON
            return False
        return True

    def _get_bytes(self, key: str) -> bytes:
        _info, stream = self.obj.get_object(MINIO_META_BUCKET, key)
        try:
            return b"".join(stream)
        finally:
            close = getattr(stream, "close", None)
            if close:
                close()

    def _persist_due(self) -> None:
        now = time.monotonic()
        for bucket, idx in list(self._indexes.items()):
            if idx.state != _BucketIndex.READY or not idx.dirty:
                continue
            if now - idx.last_persist < self.persist_s():
                continue
            try:
                self._persist(bucket)
            except Exception:  # noqa: BLE001 — retried next interval
                self.persist_errors += 1

    def _persist(self, bucket: str) -> None:
        """Write dirty segments + a fresh manifest. Incremental: only
        segments whose key range contains a dirty name are rewritten;
        oversized segments split, emptied ones drop. The lock covers
        only the range math + entry-ref snapshot (version lists are
        replaced wholesale, never mutated in place) — serialization and
        the erasure-coded object writes run outside it so record()
        never stalls behind a persist."""
        seg_max = self.segment_keys()
        with self._cond:
            idx = self._indexes.get(bucket)
            if idx is None or idx.state != _BucketIndex.READY:
                return
            dirty = set(idx.dirty)
            idx.dirty.clear()
            names = idx.names
            old = idx.segments
            if old is None or not old:
                keep: list[dict] = []
                rewrite_ranges = [(0, len(names))]
                replaced_keys: list[str] = []
            else:
                firsts = [s["first"] for s in old]
                affected: set[int] = set()
                for dn in dirty:
                    j = bisect.bisect_right(firsts, dn) - 1
                    affected.add(max(j, 0))
                keep = [s for j, s in enumerate(old) if j not in affected]
                replaced_keys = [old[j]["key"] for j in sorted(affected)]
                rewrite_ranges = []
                for j in sorted(affected):
                    lo = 0 if j == 0 else bisect.bisect_left(
                        names, firsts[j])
                    hi = len(names) if j + 1 >= len(old) else \
                        bisect.bisect_left(names, firsts[j + 1])
                    rewrite_ranges.append((lo, hi))
            # copy only the name slices under the lock; the version
            # lists are resolved lock-free below (apply() replaces
            # them wholesale, and a name deleted mid-persist simply
            # drops out of the chunk — reconcile/journal converge it)
            name_chunks: list[list[str]] = []
            for lo, hi in rewrite_ranges:
                chunk_names = names[lo:hi]
                if not chunk_names and old:
                    continue            # emptied segment: drop it
                for c0 in range(0, max(len(chunk_names), 1), seg_max):
                    name_chunks.append(chunk_names[c0:c0 + seg_max])
                    if not chunk_names:
                        break
            entries = idx.entries
            gen = idx.gen + 1
            count = len(names)
        # (key, [(name, version-list ref)], first, count)
        chunks: list[tuple[str, list, str, int]] = []
        for chunk in name_chunks:
            pairs = [(n, vers) for n in chunk
                     for vers in [entries.get(n)] if vers]
            key = (mc_prefix(bucket)
                   + f"seg-{_uuid.uuid4().hex[:12]}.json")
            chunks.append((key, pairs,
                           chunk[0] if chunk else "", len(pairs)))
        if old is None:
            # this index never knew its persisted layout (walk rebuild
            # after a failed load): the stored manifest's segments are
            # about to become unreferenced — collect them for reclaim
            try:
                prior = json.loads(self._get_bytes(manifest_key(bucket)))
                replaced_keys = [s["key"]
                                 for s in prior.get("segments", [])]
            except Exception:  # noqa: BLE001 — no readable prior manifest
                pass
        written: list[str] = []
        try:
            for key, pairs, _first, _count in chunks:
                body = json.dumps(
                    [[n, [_oi_to_doc(o) for o in vers]]
                     for n, vers in pairs]).encode()
                self.obj.put_object(MINIO_META_BUCKET, key, body)
                written.append(key)
                # one hit per segment (arm :<nth>): segments without a
                # manifest are the orphan class fsck reclaims
                crashpoint.hit("metacache.persist.segment")
            segments = sorted(
                keep + [{"key": k, "first": f, "count": c}
                        for k, _p, f, c in chunks],
                key=lambda s: s["first"])
            # every segment landed, the manifest has not: restart
            # loads the PRIOR manifest (or walk-rebuilds) and this
            # attempt's segments are orphans
            crashpoint.hit("metacache.persist.before_manifest")
            manifest = json.dumps({
                "format": _FORMAT, "bucket": bucket, "gen": gen,
                "updated": time.time(), "count": count,
                "segments": segments}).encode()
            self.obj.put_object(MINIO_META_BUCKET, manifest_key(bucket),
                                manifest)
        except Exception:
            with self._cond:
                idx.dirty |= dirty      # retry next interval
            # the retry mints fresh uuid keys: reclaim this attempt's
            # segment objects or they leak unreferenced forever
            for key in written:
                try:
                    self.obj.delete_object(MINIO_META_BUCKET, key)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            raise
        with self._cond:
            idx.segments = segments
            idx.gen = gen
            idx.last_persist = time.monotonic()
        # old segment objects are garbage now (manifest no longer
        # references them) — reclaim best-effort
        for key in replaced_keys:
            try:
                self.obj.delete_object(MINIO_META_BUCKET, key)
            except Exception:  # noqa: BLE001 — orphans are harmless
                pass

    def reconcile(self, bucket: str) -> int:
        """Repair index drift against the merge-walk: names the walk
        has but the index misses (lost deltas) and names the index has
        but the walk lost (stale entries) are re-read and fixed. THE
        periodic amortized walk; also the recovery path after journal
        overflow or a failed segment load. Returns entries repaired."""
        with self._cond:
            idx = self._indexes.get(bucket)
            if idx is None or idx.state != _BucketIndex.READY:
                return 0
            have = set(idx.names)
            invalid = idx.invalid
            drops0 = self.drops
        self.reconciles += 1
        with telemetry.trace("metacache.reconcile", bucket=bucket):
            try:
                walked = self._walk_names(bucket)
            except Exception:  # noqa: BLE001 — try again next interval
                return 0
            if invalid:
                # a delta was LOST (journal overflow): name-set drift
                # alone cannot prove freshness — an overwrite changes
                # versions without changing presence. Refresh EVERY
                # name before trusting the index again.
                drift = sorted(walked | have)
            else:
                drift = sorted(walked.symmetric_difference(have))
            for name in drift:
                self._refresh(bucket, name)
            with self._cond:
                # an overflow DURING this walk lost a delta the walk may
                # have already passed — leave invalid for the next round
                if self.drops == drops0:
                    idx.invalid = False
            if drift:
                self.repairs += len(drift)
                self._m[5].inc(len(drift))
        return len(drift)

    def drop_bucket(self, bucket: str, purge: bool = False) -> None:
        """Forget a bucket's in-memory state; with ``purge`` also delete
        the persisted manifest + segments — a DELETEd bucket's index
        must not be reloadable by a later same-name incarnation."""
        with self._cond:
            self._indexes.pop(bucket, None)
            pend = self._pending.pop(bucket, None)
            if pend:
                self._pending_count -= len(pend)
        if purge:
            self._purge_persisted(bucket)

    def _purge_persisted(self, bucket: str) -> None:
        keys: list[str] = []
        try:
            doc = json.loads(self._get_bytes(manifest_key(bucket)))
            keys = [s["key"] for s in doc.get("segments", [])]
        except Exception:  # noqa: BLE001 — no manifest, nothing to purge
            pass
        for key in keys + [manifest_key(bucket)]:
            try:
                self.obj.delete_object(MINIO_META_BUCKET, key)
            except Exception:  # noqa: BLE001 — best-effort reclaim
                pass

    # -- serving -----------------------------------------------------------

    def _ready_index(self, bucket: str,
                     build_sync: bool = False) -> Optional[_BucketIndex]:
        if not enabled():
            return None
        with self._cond:
            idx = self._indexes.get(bucket)
            ok = idx is not None and idx.state == _BucketIndex.READY \
                and not idx.invalid
            if not ok and not build_sync:
                if bucket not in self._build_q:
                    self._build_q.append(bucket)
                    self._cond.notify_all()
                return None
        if not ok:
            if not self.build(bucket):
                return None
            with self._cond:
                idx = self._indexes.get(bucket)
                if idx is None or idx.state != _BucketIndex.READY \
                        or idx.invalid:
                    return None
        self._ensure_fresh(bucket)
        return idx

    def _iter_names_chunked(self, idx: _BucketIndex, prefix: str,
                            marker: str, inclusive: bool = False,
                            chunk: int = 1024) -> Iterator[str]:
        """Scan the live index WITHOUT holding the manager lock across
        the whole page (record() — the PUT hot path — takes the same
        lock): grab a bounded chunk under the lock, yield it lock-free,
        re-anchor by bisect on the last yielded name. A concurrent
        insert/delete lands before or after the anchor exactly like a
        write racing a merge-walk page."""
        last, inc = marker, inclusive
        while True:
            with self._cond:
                batch = _slice_names(idx.names, prefix, last, inc, chunk)
            yield from batch
            if len(batch) < chunk:
                return
            last, inc = batch[-1], False

    def serve_list_objects(self, bucket: str, prefix: str, marker: str,
                           delimiter: str, max_keys: int):
        """One list_objects page from the index, or None (caller falls
        back to the merge-walk). Page shape comes from the SAME
        paginate_objects loop the engine runs."""
        idx = self._ready_index(bucket)
        if idx is None:
            self.fallbacks += 1
            self._m[1].inc()
            return None
        # existence parity with the merge path: a deleted bucket must
        # raise BucketNotFound, not serve a stale page
        self.obj.get_bucket_info(bucket)
        with telemetry.span("metacache.serve", bucket=bucket,
                            verb="list"):
            # lock-free entry reads: dict get is GIL-atomic and apply()
            # replaces version lists wholesale, never mutates in place
            entries = idx.entries

            def read_latest(name: str):
                vers = entries.get(name)
                if not vers or vers[0].delete_marker:
                    return None
                return vers[0]

            page = paginate_objects(
                self._iter_names_chunked(idx, prefix, marker),
                read_latest, prefix, marker, delimiter, max_keys)
        self.serves += 1
        self._m[0].inc()
        return page

    def serve_list_object_versions(self, bucket: str, prefix: str,
                                   marker: str, max_keys: int,
                                   version_marker: str = "",
                                   delimiter: str = ""):
        """One list_object_versions page (the engine's 5-tuple,
        CommonPrefixes included) from the index, or None to fall back.
        Page shape comes from the SAME paginate_versions loop the
        merge-walk runs."""
        idx = self._ready_index(bucket)
        if idx is None:
            self.fallbacks += 1
            self._m[1].inc()
            return None
        self.obj.get_bucket_info(bucket)
        with telemetry.span("metacache.serve", bucket=bucket,
                            verb="versions"):
            entries = idx.entries
            page = paginate_versions(
                self._iter_names_chunked(idx, prefix, marker,
                                         inclusive=bool(version_marker)),
                lambda n: entries.get(n) or [],
                prefix, marker, version_marker, delimiter, max_keys)
        self.serves += 1
        self._m[0].inc()
        return page

    # -- the namespace feed ------------------------------------------------

    def namespace_feed(self, bucket: str, versions: bool = False,
                       consumer: str = "feed") -> Optional[Iterator]:
        """THE shared scanner walk: an iterator over the bucket's
        indexed namespace — latest listable ObjectInfos, or
        ``(name, versions)`` pairs with ``versions=True``. Returns None
        when the feed is unavailable (disabled, or the bucket cannot be
        built) so consumers fall back to their own merge-walk.

        The first consumer to ask builds the index synchronously —
        that build IS the one amortized walk; every later consumer
        reads memory."""
        if not feed_enabled():
            return None
        idx = self._ready_index(bucket, build_sync=True)
        if idx is None:
            return None
        with self._cond:
            names = list(idx.names)
            entries = idx.entries
        walks_counter().inc(consumer=consumer, source="index")

        def it():
            for n in names:
                with self._cond:
                    vers = list(entries.get(n) or ())
                if not vers:
                    continue
                if versions:
                    yield n, vers
                else:
                    if vers[0].delete_marker:
                        continue
                    yield vers[0]
        return it()

    # -- heal surface ------------------------------------------------------

    def segment_objects(self) -> list[str]:
        """Meta-bucket keys of every live manifest + segment — the heal
        scanner sweeps these like ordinary objects so the index
        survives drive replacement."""
        out: list[str] = []
        with self._cond:
            for bucket, idx in self._indexes.items():
                if idx.segments is None:
                    continue
                out.append(manifest_key(bucket))
                out.extend(s["key"] for s in idx.segments)
        return out

    # -- tests / admin -----------------------------------------------------

    def drain(self, timeout: float = 10.0) -> bool:
        """Apply every pending delta NOW (tests; also the bench's
        settle step). Returns False when new deltas kept arriving past
        the deadline."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._drain_once()
            with self._cond:
                if not self._pending_count:
                    return True
        return False

    def stats(self) -> dict:
        with self._cond:
            return {
                "buckets": {b: {"state": i.state, "invalid": i.invalid,
                                "names": len(i.names), "gen": i.gen,
                                "dirty": len(i.dirty)}
                            for b, i in self._indexes.items()},
                "pending": self._pending_count,
                "serves": self.serves, "fallbacks": self.fallbacks,
                "deltas": self.deltas, "drops": self.drops,
                "sync_drains": self.sync_drains, "builds": self.builds,
                "reconciles": self.reconciles, "repairs": self.repairs,
                "persist_errors": self.persist_errors,
            }


def _slice_names(names: list[str], prefix: str, marker: str,
                 inclusive: bool, k: int) -> list[str]:
    """Up to ``k`` sorted prefix-matching names starting after (or at,
    with ``inclusive``) the marker — the index-side analog of the
    engine's `_merged_names` contract, bounded so the caller never
    holds the manager lock across a whole-bucket scan."""
    start = 0
    if marker and marker >= prefix:
        start = bisect.bisect_left(names, marker) if inclusive \
            else bisect.bisect_right(names, marker)
    elif prefix:
        start = bisect.bisect_left(names, prefix)
    out: list[str] = []
    for i in range(start, len(names)):
        n = names[i]
        if prefix and not n.startswith(prefix):
            break               # sorted: past the prefix range
        out.append(n)
        if len(out) >= k:
            break
    return out
