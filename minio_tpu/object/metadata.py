"""Erasure metadata quorum algebra.

The distributed-correctness core of the object engine: reading xl.meta
from every drive, agreeing on the valid copy, and deciding whether enough
drives succeeded (reference: cmd/erasure-metadata.go,
cmd/erasure-metadata-utils.go).

Errors are classified by type (the reference compares sentinel error
values); None means success. Quorums: readQuorum = dataBlocks,
writeQuorum = dataBlocks (+1 when data == parity)
(objectQuorumFromMeta, cmd/erasure-metadata.go:320-340).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

from ..storage import errors as serr
from ..storage.api import StorageAPI
from ..storage.datatypes import FileInfo
from . import api_errors

# Per-drive errors ignored during object ops (reference objectOpIgnoredErrs:
# a gone disk shouldn't mask the real outcome).
OBJECT_OP_IGNORED_ERRS = (serr.DiskNotFound, serr.FaultyDisk,
                          serr.DiskAccessDenied)


def _err_key(err: Optional[Exception]):
    return None if err is None else type(err)


def reduce_errs(errs: Sequence[Optional[Exception]],
                ignored: tuple = ()) -> tuple[int, Optional[Exception]]:
    """(count, representative) of the most frequent error class, preferring
    success (None) on ties (reference reduceErrs,
    cmd/erasure-metadata-utils.go:34-57)."""
    counts: dict = {}
    rep: dict = {}
    for e in errs:
        if e is not None and ignored and isinstance(e, ignored):
            continue
        k = _err_key(e)
        counts[k] = counts.get(k, 0) + 1
        rep.setdefault(k, e)
    best_k, best_n = None, 0
    for k, n in counts.items():
        if n > best_n or (n == best_n and k is None):
            best_k, best_n = k, n
    return best_n, rep.get(best_k)


def reduce_read_quorum_errs(errs, ignored, read_quorum: int
                            ) -> Optional[Exception]:
    n, err = reduce_errs(errs, ignored)
    if n >= read_quorum:
        return err
    return api_errors.InsufficientReadQuorum(
        f"{n} agreeing drives < read quorum {read_quorum}")


def reduce_write_quorum_errs(errs, ignored, write_quorum: int
                             ) -> Optional[Exception]:
    n, err = reduce_errs(errs, ignored)
    if n >= write_quorum:
        return err
    return api_errors.InsufficientWriteQuorum(
        f"{n} agreeing drives < write quorum {write_quorum}")


# ---------------------------------------------------------------------------
# Parallel per-drive fan-out (the reference's errgroup-per-disk pattern)
# ---------------------------------------------------------------------------

_POOL = ThreadPoolExecutor(max_workers=64, thread_name_prefix="drive-io")


def for_each_disk(disks: Sequence[Optional[StorageAPI]],
                  fn: Callable[[int, StorageAPI], object]
                  ) -> tuple[list, list[Optional[Exception]]]:
    """Run fn(index, disk) on every non-None drive concurrently.

    Returns (results, errors) — per index; a None disk yields
    DiskNotFound (same shape as the reference's errgroup pattern)."""
    results: list = [None] * len(disks)
    errs: list[Optional[Exception]] = [None] * len(disks)

    def run(i: int):
        d = disks[i]
        if d is None:
            errs[i] = serr.DiskNotFound(f"drive {i}")
            return
        try:
            results[i] = fn(i, d)
        except Exception as e:  # noqa: BLE001 — per-drive fault isolation
            errs[i] = e

    from ..utils import telemetry
    if telemetry.current_span() is not None:
        # carry the caller's span into the pool workers so per-drive
        # I/O attaches to the request tree; one Context copy per task
        # (a Context must never run in two threads at once)
        import contextvars
        futures = [_POOL.submit(contextvars.copy_context().run, run, i)
                   for i in range(len(disks))]
    else:
        futures = [_POOL.submit(run, i) for i in range(len(disks))]
    for f in futures:
        # each task is one drive verb, bounded by the drive/RPC
        # deadline; fan-outs that must not wait for stragglers ride
        # for_each_disk_quorum instead
        # check: allow(deadline) per-drive verb bounded by drive/RPC deadline
        f.result()
    return results, errs


def submit_disk_task(fn, *args):
    """One task on the shared drive-io pool, carrying the caller's
    span context (the for_each_disk discipline) — the hedged-read
    state machine launches per-reader tasks through this so it can
    wait on them with a deadline instead of joining a whole fan-out."""
    from ..utils import telemetry
    if telemetry.current_span() is not None:
        import contextvars
        return _POOL.submit(contextvars.copy_context().run, fn, *args)
    return _POOL.submit(fn, *args)


def for_each_disk_quorum(disks: Sequence[Optional[StorageAPI]],
                         fn: Callable[[int, StorageAPI], object],
                         quorum: int, stall_s: Optional[float] = None,
                         stage: str = "write",
                         on_settle: Optional[Callable[[int], None]]
                         = None
                         ) -> tuple[list, list[Optional[Exception]]]:
    """for_each_disk with quorum-ack semantics: returns once every
    drive finished OR `quorum` successes are in and the laggards have
    outlived `stall_s` (measured from fan-out start). Stragglers keep
    running on the drive-io pool — the bounded background lane — and
    are reported as serr.StorageStalled so the caller's quorum reduce
    counts them as missed writes (the MRF degraded-write feed).

    `on_settle(i)` fires when an ABANDONED straggler finally completes
    (however it ends). Namespace-mutating laggards (a rename) need it:
    by the time the op lands, the commit lock is long released and a
    NEWER write may have committed — the callback lets the caller
    re-queue an MRF check so a late-landing stale op is healed back to
    quorum state instead of silently de-replicating the newer version.

    stall_s=None (quorum-ack off) degrades to exactly for_each_disk."""
    if stall_s is None:
        return for_each_disk(disks, fn)
    import time as _time
    from concurrent.futures import FIRST_COMPLETED
    from concurrent.futures import wait as _fwait
    from ..utils import healthtrack, telemetry

    results: list = [None] * len(disks)
    errs: list[Optional[Exception]] = [None] * len(disks)
    settled = [False] * len(disks)
    futs: dict = {}
    traced = telemetry.current_span() is not None
    if traced:
        import contextvars
    for i in range(len(disks)):
        if disks[i] is None:
            errs[i] = serr.DiskNotFound(f"drive {i}")
            settled[i] = True
            continue

        def run(i=i):
            return fn(i, disks[i])

        fut = _POOL.submit(contextvars.copy_context().run, run) \
            if traced else _POOL.submit(run)
        futs[fut] = i
    deadline = _time.monotonic() + stall_s
    while futs:
        ok = sum(1 for i in range(len(disks))
                 if settled[i] and errs[i] is None)
        remaining = deadline - _time.monotonic()
        if ok >= quorum and remaining <= 0:
            break
        # below quorum the wait is unbounded — quorum durability is
        # the correctness line; each task is itself bounded by its
        # drive/RPC deadline, so this cannot hang past the slowest
        # drive's own timeout
        done, _ = _fwait(set(futs), return_when=FIRST_COMPLETED,
                         timeout=remaining if ok >= quorum else None)
        for f in done:
            i = futs.pop(f)
            settled[i] = True
            try:
                results[i] = f.result(timeout=0)
            except Exception as e:  # noqa: BLE001 — per-drive isolation
                errs[i] = e
    for f, i in futs.items():
        # abandoned to the background lane: the future keeps the
        # task (and this slot's eventual completion) alive; nothing
        # joins it — that is the point
        errs[i] = serr.StorageStalled(
            f"drive {i}: {stage} abandoned after {stall_s:.3f}s "
            "(write quorum already durable)")
        healthtrack.note_laggard(stage)
        if on_settle is not None:
            f.add_done_callback(lambda _f, i=i: on_settle(i))
    return results, errs


def read_all_file_info(disks: Sequence[Optional[StorageAPI]], bucket: str,
                       object_path: str, version_id: str = ""
                       ) -> tuple[list[Optional[FileInfo]],
                                  list[Optional[Exception]]]:
    """Read xl.meta from every drive (reference readAllFileInfo,
    cmd/erasure-metadata-utils.go:118)."""
    results, errs = for_each_disk(
        disks, lambda i, d: d.read_version(bucket, object_path, version_id))
    return results, errs


# ---------------------------------------------------------------------------
# Agreement
# ---------------------------------------------------------------------------

def _fi_fingerprint(fi: FileInfo) -> tuple:
    """Equality class of one xl.meta copy, excluding per-drive fields
    (index/checksums) — reference findFileInfoInQuorum's meta hash."""
    return (round(fi.mod_time, 6), fi.size, fi.deleted, fi.version_id,
            fi.data_dir, fi.erasure.data_blocks, fi.erasure.parity_blocks,
            fi.erasure.block_size, tuple(fi.erasure.distribution),
            tuple((p.number, p.size) for p in fi.parts))


def find_file_info_in_quorum(metas: Sequence[Optional[FileInfo]],
                             quorum: int) -> FileInfo:
    """The FileInfo content attested by >= quorum drives
    (cmd/erasure-metadata.go findFileInfoInQuorum)."""
    counts: dict = {}
    for fi in metas:
        if fi is None:
            continue
        counts[_fi_fingerprint(fi)] = counts.get(_fi_fingerprint(fi), 0) + 1
    if not counts:
        raise api_errors.InsufficientReadQuorum("no readable xl.meta")
    best = max(counts.items(), key=lambda kv: kv[1])
    if best[1] < quorum:
        raise api_errors.InsufficientReadQuorum(
            f"best xl.meta agreement {best[1]} < quorum {quorum}")
    for fi in metas:
        if fi is not None and _fi_fingerprint(fi) == best[0]:
            return fi
    raise api_errors.InsufficientReadQuorum("unreachable")


def pick_valid_file_info(metas, quorum: int) -> FileInfo:
    return find_file_info_in_quorum(metas, quorum)


def get_latest_file_info(metas: Sequence[Optional[FileInfo]],
                         errs: Sequence[Optional[Exception]]
                         ) -> FileInfo:
    """Latest (max modTime) FileInfo present on >= half the drives
    (reference getLatestFileInfo)."""
    live = [fi for fi in metas if fi is not None]
    if not live:
        err = reduce_read_quorum_errs(errs, OBJECT_OP_IGNORED_ERRS, 1)
        raise err if err else api_errors.InsufficientReadQuorum()
    mod_time = max(fi.mod_time for fi in live)
    count = sum(1 for fi in live if fi.mod_time == mod_time)
    if count < len(metas) // 2:
        raise api_errors.InsufficientReadQuorum(
            f"latest xl.meta on {count} < N/2 drives")
    for fi in live:
        if fi.mod_time == mod_time:
            return fi
    raise api_errors.InsufficientReadQuorum("unreachable")


def write_quorum_for(data_blocks: int, parity_blocks: int) -> int:
    """writeQuorum = data (+1 when data == parity)
    (cmd/erasure-metadata.go:333-336) — the single home of this rule."""
    return data_blocks + 1 if data_blocks == parity_blocks else data_blocks


def object_quorum_from_meta(metas, errs, default_parity: int
                            ) -> tuple[int, int]:
    """(readQuorum, writeQuorum) for an object from its stored geometry
    (reference objectQuorumFromMeta, cmd/erasure-metadata.go:320)."""
    latest = get_latest_file_info(metas, errs)
    data = latest.erasure.data_blocks
    parity = latest.erasure.parity_blocks or default_parity or data
    return data, write_quorum_for(data, parity)


def list_online_disks(disks: Sequence[Optional[StorageAPI]],
                      metas: Sequence[Optional[FileInfo]],
                      errs: Sequence[Optional[Exception]]
                      ) -> tuple[list[Optional[StorageAPI]], float]:
    """(onlineDisks, latest modTime): drives whose xl.meta carries the
    latest modTime stay; others become None (reference listOnlineDisks,
    cmd/erasure-healing-common.go)."""
    mod_time = 0.0
    for fi in metas:
        if fi is not None and fi.mod_time > mod_time:
            mod_time = fi.mod_time
    online: list[Optional[StorageAPI]] = [None] * len(disks)
    for i, fi in enumerate(metas):
        if fi is not None and fi.mod_time == mod_time:
            online[i] = disks[i]
    return online, mod_time


# ---------------------------------------------------------------------------
# Distribution shuffles
# ---------------------------------------------------------------------------

def shuffle_disks(disks: Sequence[Optional[StorageAPI]],
                  distribution: Sequence[int]
                  ) -> list[Optional[StorageAPI]]:
    """Order drives into shard-index order: shuffled[dist[i]-1] = disks[i]
    (reference shuffleDisks). Entry j then holds shard j."""
    if not distribution:
        return list(disks)
    out: list[Optional[StorageAPI]] = [None] * len(disks)
    for i, d in enumerate(disks):
        out[distribution[i] - 1] = d
    return out


def shuffle_parts_metadata(metas: Sequence[Optional[FileInfo]],
                           distribution: Sequence[int]
                           ) -> list[Optional[FileInfo]]:
    if not distribution:
        return list(metas)
    out: list[Optional[FileInfo]] = [None] * len(metas)
    for i, m in enumerate(metas):
        out[distribution[i] - 1] = m
    return out


def eval_disks(disks: Sequence[Optional[StorageAPI]],
               errs: Sequence[Optional[Exception]]
               ) -> list[Optional[StorageAPI]]:
    """Null out drives whose last op failed (reference evalDisks)."""
    return [d if e is None else None for d, e in zip(disks, errs)]


def write_unique_file_info(disks: Sequence[Optional[StorageAPI]],
                           bucket: str, prefix: str,
                           files: Sequence[FileInfo], quorum: int,
                           stall_s: Optional[float] = None
                           ) -> list[Optional[StorageAPI]]:
    """Write per-drive xl.meta (Erasure.Index = i+1) to all drives,
    enforcing write quorum (reference writeUniqueFileInfo,
    cmd/erasure-metadata.go:294). `stall_s` selects the quorum-ack
    lane: laggard metadata writers past it are abandoned (and counted
    lost by the caller) once quorum is durable."""
    def write(i: int, d: StorageAPI):
        files[i].erasure.index = i + 1
        d.write_metadata(bucket, prefix, files[i])

    _, errs = for_each_disk_quorum(disks, write, quorum,
                                   stall_s=stall_s, stage="meta")
    err = reduce_write_quorum_errs(errs, OBJECT_OP_IGNORED_ERRS, quorum)
    if err is not None:
        raise err
    return eval_disks(disks, errs)
